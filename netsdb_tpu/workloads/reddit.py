"""Reddit workload — the reference's social-graph + ML-feature pipeline.

The reference ships a Reddit comment/author/subreddit workload
(``src/reddit``, ~3.4 kLoC) used to drive its Lachesis placement
experiments: JSON/CSV record types (``headers/RedditComment.h``,
``RedditAuthor.h``, ``RedditSub.h``), a three-way equi-join
Comment⋈Author⋈Sub (``headers/RedditThreeWayJoin.h:12-30``), comment →
feature-vector extraction with time features
(``headers/CommentFeatures.h:31-47``), chunking of feature vectors into
``FFMatrixBlock``s (``CommentsToChunks.h`` → ``CommentFeatureChunks.h``
→ ``CommentBlockToMatrix.h:22-56``), label-propagation selections
(``RedditPositiveLabelSelection.h``, ``RedditNegativeLabelSelection.h``
and the 60+ tiny ``RedditLabelSelection{i}_{j}.h`` partition variants),
a comment⋈label join (``RedditCommentLabelJoin.h``) and a comment ⋈
model-output inference join (``RedditCommentInferenceJoin.h``).

Here the record side runs on the host-relational plan path
(Scan→Filter→Join→Aggregate through :mod:`netsdb_tpu.plan`) and the
feature matrix is one :class:`BlockedTensor` — the chunk/block plumbing
the reference needs to turn row records into a distributed matrix
collapses into ``BlockedTensor.from_dense`` (padding handles the
ragged last chunk the reference special-cases). Inference over the
features is the FF model on the MXU; the inference join puts predicted
labels back on comment records by row index.
"""

from __future__ import annotations

import dataclasses
import math
import random
import zlib
from typing import List, Sequence, Tuple

import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.plan.computations import (
    Aggregate, Filter, Join, ScanSet, WriteSet,
)

# Feature layout: 9 time-derived features (reference
# ``CommentFeatures.h::push_time_features`` normalizes mday/sec/min/hour/
# mon/year/wday/yday/isdst) + numeric comment fields + hashed body terms.
NUM_TIME_FEATURES = 9
NUM_NUMERIC_FEATURES = 6
DEFAULT_HASH_FEATURES = 49  # total 64 — a lane-friendly width (vs the
                            # reference's sparse NUM_FEATURES=400000 one-hot)


@dataclasses.dataclass
class Comment:
    """Reference ``reddit::Comment`` (``RedditComment.h:21-66``),
    reduced to the fields its feature extractor and joins consume."""

    index: int
    id: str
    author: str
    subreddit_id: str
    body: str = ""
    label: int = 0
    score: int = 0
    gilded: int = 0
    controversiality: int = 0
    archived: bool = False
    stickied: bool = False
    created_utc: int = 0
    author_created_utc: int = 0


@dataclasses.dataclass
class Author:
    """Reference ``reddit::Author`` (``RedditAuthor.h:16-35``)."""

    author_id: int
    author: str
    karma: int = 0


@dataclasses.dataclass
class Sub:
    """Reference ``reddit::Sub`` (``RedditSub.h:17-65``), reduced."""

    id: str
    display_name: str = ""
    subscribers: int = 0
    lang: str = "en"


@dataclasses.dataclass
class FullFeatures:
    """Three-way-join output — reference ``reddit::FullFeatures``
    (``RedditFullFeatures.h``): one row joining comment, author, sub."""

    index: int
    label: int
    comment_id: str
    author_id: int
    sub_id: str
    features: np.ndarray


def generate(num_comments: int = 200, num_authors: int = 20,
             num_subs: int = 8, seed: int = 0,
             ) -> Tuple[List[Comment], List[Author], List[Sub]]:
    """Seeded micro-instance (the reference loads real dump files via
    ``LoadRedditComments.cc``; tests use synthetic data)."""
    rng = random.Random(seed)
    authors = [Author(author_id=i, author=f"user{i}",
                      karma=rng.randrange(0, 100000))
               for i in range(num_authors)]
    subs = [Sub(id=f"t5_{i:05x}", display_name=f"sub{i}",
                subscribers=rng.randrange(100, 10_000_000))
            for i in range(num_subs)]
    words = ["the", "a", "cat", "dog", "tpu", "jax", "mesh", "pallas",
             "good", "bad", "fast", "slow"]
    comments = []
    for i in range(num_comments):
        comments.append(Comment(
            index=i,
            id=f"c{i:06d}",
            author=rng.choice(authors).author,
            subreddit_id=rng.choice(subs).id,
            body=" ".join(rng.choices(words, k=rng.randrange(3, 12))),
            label=rng.choice([0, 1]),
            score=rng.randrange(-50, 5000),
            gilded=rng.randrange(0, 3),
            controversiality=rng.choice([0, 0, 0, 1]),
            archived=rng.random() < 0.1,
            stickied=rng.random() < 0.05,
            created_utc=1_500_000_000 + rng.randrange(0, 200_000_000),
            author_created_utc=1_200_000_000 + rng.randrange(0, 300_000_000),
        ))
    return comments, authors, subs


# --- feature extraction (CommentsToFeatures / CommentFeatures) --------

def _time_features(utc: int) -> List[float]:
    """Normalized calendar features — reference ``push_time_features``
    (``CommentFeatures.h:36-46``). Pure arithmetic (no tm struct): day
    granularity is what the normalization keeps anyway."""
    days = utc / 86400.0
    secs = utc % 86400
    return [
        ((days % 30.44) + 1) / 31.0,          # mday
        (utc % 60) / 60.0,                    # sec
        ((utc // 60) % 60) / 59.0,            # min
        (secs // 3600) / 23.0,                # hour
        ((days / 30.44) % 12) / 11.0,         # mon
        (1970 + days / 365.25) / 2021.0,      # year
        ((int(days) + 4) % 7) / 6.0,          # wday (epoch was Thursday)
        ((days % 365.25)) / 365.0,            # yday
        0.0,                                  # isdst (UTC: never)
    ]


def body_hash_counts(body: str,
                     hash_dim: int = DEFAULT_HASH_FEATURES) -> np.ndarray:
    """Body text → term-count buckets (hash_dim - 9 wide; 9 slots are
    taken by the second time-feature set). crc32, not hash(): per-
    process salting would make features differ across runs and break
    stored-set reproducibility. Shared by the scalar path here and the
    columnar ingest (``reddit_columnar.columnarize``) so the two
    feature pipelines cannot drift."""
    counts = np.zeros(hash_dim - 9, np.float32)
    for w in body.split():
        counts[zlib.crc32(w.encode()) % (hash_dim - 9)] += 1.0
    return counts


def comment_features(c: Comment,
                     hash_dim: int = DEFAULT_HASH_FEATURES) -> np.ndarray:
    """Comment → dense feature vector. The reference emits author-time
    features + comment-time features + numeric fields + a 400k-wide
    sparse body encoding; we emit the same signal with the body hashed
    into ``hash_dim - 9`` buckets (dense, MXU-friendly; the total
    vector width is ``feature_dim(hash_dim)``)."""
    if hash_dim <= 9:
        raise ValueError(f"hash_dim must be > 9, got {hash_dim}")
    feats = _time_features(c.author_created_utc)
    feats += _time_features(c.created_utc)
    numeric = [
        math.tanh(c.score / 1000.0),
        float(c.gilded),
        float(c.controversiality),
        float(c.archived),
        float(c.stickied),
        math.tanh(len(c.body) / 256.0),
    ]
    body = body_hash_counts(c.body, hash_dim)
    vec = np.concatenate([
        np.asarray(feats, np.float32),
        np.asarray(numeric, np.float32),
        np.tanh(body),
    ])
    return vec


def feature_dim(hash_dim: int = DEFAULT_HASH_FEATURES) -> int:
    return 2 * NUM_TIME_FEATURES + NUM_NUMERIC_FEATURES + (hash_dim - 9)


# --- record → blocked matrix (CommentsToChunks → CommentBlockToMatrix)

def features_to_blocked(rows: Sequence[np.ndarray],
                        block: Tuple[int, int] = (128, 128),
                        ) -> BlockedTensor:
    """Stack per-row feature vectors into one ``batch × features``
    BlockedTensor — the reference's chunk/block pipeline
    (``CommentsToChunks.h``, ``CommentChunksToBlocks.h``,
    ``CommentBlockToMatrix.h:45-56``) whose ragged-last-chunk handling
    becomes block padding. Layout is (batch, features), the FF model's
    input convention."""
    dense = np.stack(list(rows), axis=0).astype(np.float32)
    return BlockedTensor.from_dense(dense, block)


# --- computation DAG builders ----------------------------------------

def build_three_way_join(db: str = "reddit") -> WriteSet:
    """Comment⋈Author⋈Sub → FullFeatures rows — reference
    ``ThreeWayJoin : JoinComp<FullFeatures, Comment, Author, Sub>``
    (``RedditThreeWayJoin.h:12-30``; driver
    ``src/tests/source/TestRedditThreeWayJoin.cc``). Two chained hash
    equi-joins on the host-relational path."""
    comments = ScanSet(db, "comments")
    authors = ScanSet(db, "authors")
    subs = ScanSet(db, "subs")
    ca = Join(comments, authors,
              left_key=lambda c: c.author,
              right_key=lambda a: a.author,
              label="comment_author")
    cas = Join(ca, subs,
               left_key=lambda p: p[0].subreddit_id,
               right_key=lambda s: s.id,
               project=lambda p, s: FullFeatures(
                   index=p[0].index, label=p[0].label,
                   comment_id=p[0].id, author_id=p[1].author_id,
                   sub_id=s.id,
                   features=comment_features(p[0])),
               label="three_way")
    return WriteSet(cas, db, "full_features")


def build_three_way_join_device(db: str = "reddit") -> WriteSet:
    """The SAME three-way Comment⋈Author⋈Sub as a device-engine DAG:
    sets created with ``type_name="objects"`` columnarize at ingest
    (string keys dictionary-encode), and ``Join(on=...)`` lowers each
    string-key equi-join to one device LUT gather
    (``relational.autojoin.equijoin``) — the automatic routing round 3
    only offered as hand calls. Output: one ColumnTable extending
    comments with the gathered author/sub columns (reference
    ``RedditThreeWayJoin.h:12-30``; per-tuple String hash probes
    ``JoinPairArray.h:122`` re-priced as code gathers)."""
    comments = ScanSet(db, "comments")
    ca = Join(comments, ScanSet(db, "authors"),
              on=("author", "author"), take=("author_id", "karma"),
              label="comment_author_dev")
    cas = Join(ca, ScanSet(db, "subs"),
               on=("subreddit_id", "id"), take=("subscribers",),
               label="three_way_dev")
    return WriteSet(cas, db, "full_features_table")


def label_selection(db: str, positive: bool) -> WriteSet:
    """Reference ``RedditPositiveLabelSelection`` /
    ``RedditNegativeLabelSelection`` — filter comments by label."""
    want = 1 if positive else 0
    scan = ScanSet(db, "comments")
    f = Filter(scan, lambda c, w=want: c.label == w,
               label="positive" if positive else "negative")
    return WriteSet(f, db, "labeled_pos" if positive else "labeled_neg")


def label_partition_selections(db: str, num_parts: int = 11,
                               ) -> List[WriteSet]:
    """The reference's 2×11 grid of tiny ``RedditLabelSelection{i}_{j}``
    variants partitions labeled comments by (label, index % parts) so
    each slice lands in its own set (Lachesis placement fodder). One
    parameterized builder replaces the 60 generated classes."""
    outs = []
    for label in (0, 1):
        for part in range(num_parts):
            scan = ScanSet(db, "comments")
            f = Filter(scan,
                       lambda c, l=label, p=part, n=num_parts:
                       c.label == l and c.index % n == p,
                       label=f"label{label}_{part}")
            outs.append(WriteSet(f, db, f"labeled_{label}_{part}"))
    return outs


def build_label_propagation(db: str = "reddit") -> WriteSet:
    """Reference ``RedditCommentLabelJoin`` — join unlabeled comments
    with a labeled set by author and adopt the neighbour's label
    (label propagation over the author relation)."""
    unlabeled = ScanSet(db, "comments")
    labeled = ScanSet(db, "labeled_pos")

    def adopt(c: Comment, l: Comment) -> Comment:
        out = dataclasses.replace(c)
        out.label = l.label
        return out

    j = Join(unlabeled, labeled,
             left_key=lambda c: c.author,
             right_key=lambda l: l.author,
             project=adopt, label="label_join")
    return WriteSet(j, db, "propagated")


def build_author_comment_counts(db: str = "reddit") -> WriteSet:
    """Group-by used in the workload's stats queries: author → number of
    comments (the aggregation side of the Lachesis experiments)."""
    scan = ScanSet(db, "comments")
    agg = Aggregate(scan, key=lambda c: c.author, value=lambda c: 1,
                    combine=lambda a, b: a + b, label="per_author_count")
    return WriteSet(agg, db, "author_counts")


# --- inference join ---------------------------------------------------

def infer_labels(client, comments: Sequence[Comment], model, params,
                 db: str = "reddit",
                 block: Tuple[int, int] = (128, 128)) -> List[Comment]:
    """Feature-extract → blocked matrix → FF forward on device → argmax
    → join predictions back onto comment records by row index — the
    reference's ``RedditCommentInferenceJoin`` over the model output set
    (driver ``TestRedditInference.cc`` pattern)."""
    feats = [comment_features(c) for c in comments]
    x = features_to_blocked(feats, block)
    probs = model.forward(params, x)          # labels × batch
    pred = np.asarray(probs.to_dense()).argmax(axis=0)[:len(comments)]
    out = []
    for c, p in zip(comments, pred):
        c2 = dataclasses.replace(c)
        c2.label = int(p)
        out.append(c2)
    if client is not None:
        if not client.set_exists(db, "inferred"):
            client.create_set(db, "inferred")
        client.clear_set(db, "inferred")
        client.send_data(db, "inferred", out)
    return out
