"""Served-inference benchmark — FF throughput through the RPC hop.

The reference's serving story: the master loads model weight sets once
and many ``PDBClient`` processes run inference queries against them
concurrently (``src/mainServer/source/MasterMain.cc:64-96``,
``src/queries/headers/QueryClient.h:160-224``). This benchmark measures
the same shape here: one resident daemon (owning the device + weight
sets + compiled-plan cache), N separate *client processes*, each sending
its private input set once and then running M inference jobs whose only
per-job wire traffic is the plan.

Reported: aggregate rows/s across clients (wall), per-job latency
percentiles, and the daemon's view (jobs done, cache stats). On the lab
rig the controller↔device tunnel adds ~65-200 ms per job (the sync
barrier is a scalar pull); on directly-attached TPU hosts per-job
overhead is the localhost RPC + dispatch only.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

BATCH = 16384
FEATURES = 1024
HIDDEN = 4096
LABELS = 1024
BLOCK = (512, 512)


def _python() -> str:
    venv = "/opt/venv/bin/python"
    return venv if os.path.exists(venv) else sys.executable


def _wait_port(host: str, port: int, timeout: float = 120.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            with socket.create_connection((host, port), timeout=1):
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"daemon on {host}:{port} did not come up")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def load_model(address: str, db: str = "ffserve", seed: int = 0,
               features: int = FEATURES, hidden: int = HIDDEN,
               labels: int = LABELS) -> None:
    """Load the FF weight sets into the daemon ONCE (ref ff::setup +
    loadMatrix). Runs in whatever process calls it — only thin-client
    RPC, no device work here."""
    import numpy as np

    from netsdb_tpu.serve.client import RemoteClient

    c = RemoteClient(address)
    rng = np.random.default_rng(seed)
    c.create_database(db)
    for s in ("w1", "b1", "wo", "bo"):
        c.create_set(db, s)
    c.send_matrix(db, "w1",
                  rng.standard_normal((hidden, features)).astype(np.float32)
                  * np.sqrt(2.0 / features), BLOCK)
    c.send_matrix(db, "b1",
                  (rng.standard_normal((hidden, 1)) * 0.01).astype(np.float32),
                  (BLOCK[0], 1))
    c.send_matrix(db, "wo",
                  rng.standard_normal((labels, hidden)).astype(np.float32)
                  * np.sqrt(2.0 / hidden), BLOCK)
    c.send_matrix(db, "bo",
                  (rng.standard_normal((labels, 1)) * 0.01).astype(np.float32),
                  (BLOCK[0], 1))
    c.close()


def run_client_worker(address: str, client_id: int, jobs: int,
                      batch: int = BATCH, db: str = "ffserve",
                      features: int = FEATURES) -> Dict[str, Any]:
    """One client process: send a private input set once, then run
    ``jobs`` inference jobs against the RESIDENT weights. Returns
    timing; also printed as JSON when run via --worker."""
    import numpy as np

    from netsdb_tpu.models.ff import FFModel
    from netsdb_tpu.serve.client import RemoteClient

    c = RemoteClient(address)
    inp = f"inputs_c{client_id}"
    out = f"output_c{client_id}"
    rng = np.random.default_rng(client_id)
    c.create_set(db, inp)
    c.create_set(db, out)
    t_load0 = time.perf_counter()
    c.send_matrix(db, inp,
                  rng.standard_normal((batch, features)).astype(np.float32),
                  BLOCK)
    load_s = time.perf_counter() - t_load0

    model = FFModel(db=db, block=BLOCK)
    sink = model.build_inference_dag(input_set=inp, output_set=out)
    # warmup: first job compiles (cached thereafter — and shared across
    # clients, since the canonical plan signature is identical)
    c.execute_computations(sink, job_name="ff-serve",
                           fetch_results=False)
    lat: List[float] = []
    t_start = time.time()  # epoch: lets the parent compute the union
    t0 = time.perf_counter()
    for _ in range(jobs):
        t1 = time.perf_counter()
        c.execute_computations(sink, job_name="ff-serve",
                               fetch_results=False)
        lat.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    c.close()
    lat.sort()
    return {
        "client_id": client_id, "jobs": jobs, "batch": batch,
        "wall_s": wall, "input_load_s": load_s,
        "t_start": t_start, "t_end": t_start + wall,
        "job_p50_s": lat[len(lat) // 2],
        "job_p90_s": lat[int(len(lat) * 0.9)],
        "rows_per_sec": jobs * batch / wall,
    }


def run_serve_bench(clients: int = 2, jobs_per_client: int = 8,
                    batch: int = BATCH, port: int = 0,
                    platform: Optional[str] = None,
                    daemon_env: Optional[Dict[str, str]] = None,
                    ) -> Dict[str, Any]:
    """Spawn (or reuse) a daemon, load weights once, run N concurrent
    client PROCESSES, aggregate."""
    host = "127.0.0.1"
    daemon: Optional[subprocess.Popen] = None
    if port == 0:
        port = _free_port()
        env = dict(os.environ)
        env.update(daemon_env or {})
        argv = [_python(), "-m", "netsdb_tpu", "serve", "--port", str(port),
                "--root", f"/tmp/netsdb_serve_bench_{port}"]
        if platform:
            argv += ["--platform", platform]
        daemon = subprocess.Popen(
            argv, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
    address = f"{host}:{port}"
    try:
        _wait_port(host, port)
        load_model(address)

        procs = []
        t0 = time.perf_counter()
        for i in range(clients):
            procs.append(subprocess.Popen(
                [_python(), "-m", "netsdb_tpu.workloads.serve_bench",
                 "--worker", "--address", address, "--client-id", str(i),
                 "--jobs", str(jobs_per_client), "--batch", str(batch)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
            ))
        results = []
        for p in procs:
            out_text, err_text = p.communicate(timeout=1800)
            if p.returncode != 0:
                raise RuntimeError(
                    f"client worker failed rc={p.returncode}:\n{err_text[-4000:]}")
            results.append(json.loads(out_text.strip().splitlines()[-1]))
        wall = time.perf_counter() - t0

        from netsdb_tpu.serve.client import RemoteClient

        c = RemoteClient(address)
        stats = c.collect_stats()
        server_jobs = [j for j in c.list_jobs() if j["name"] == "ff-serve"]
        elapsed = sorted(j["elapsed"] for j in server_jobs
                         if j["elapsed"] is not None)
        c.close()
        total_rows = sum(r["jobs"] * r["batch"] for r in results)
        # measurement window = union of the clients' job loops (spawn +
        # import + warmup-compile time excluded: steady-state serving)
        window = max(r["t_end"] for r in results) - min(
            r["t_start"] for r in results)
        return {
            "clients": clients, "jobs_per_client": jobs_per_client,
            "batch": batch,
            "aggregate_rows_per_sec": total_rows / window,
            "measurement_window_s": window,
            "wall_s_incl_spawn": wall,
            "per_client": results,
            "server_jobs_done": sum(j["status"] == "done"
                                    for j in server_jobs),
            "server_job_elapsed_p50":
                elapsed[len(elapsed) // 2] if elapsed else None,
            "cache_stats": stats.get("cache"),
        }
    finally:
        if daemon is not None:
            daemon.terminate()
            try:
                daemon.wait(timeout=10)
            except subprocess.TimeoutExpired:
                daemon.kill()


def run_stream_bench(rows: int = 50_000, row_bytes: int = 2000,
                     tensor_mb: int = 128) -> Dict[str, Any]:
    """Transfer-path comparison on loopback: single-frame SCAN_SET /
    GET_TENSOR (whole payload held twice on each end) vs the round-3
    streamed forms (bounded continuation frames). Throughput should be
    comparable — the point of streaming is the MEMORY bound, reported
    here as the largest single frame each path holds."""
    import tempfile

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController

    ctl = ServeController(Configuration(root_dir=tempfile.mkdtemp(
        prefix="stream_bench_")), port=0)
    port = ctl.start()
    out: Dict[str, Any] = {}
    try:
        c = RemoteClient(f"127.0.0.1:{port}")
        c.create_database("b")
        c.create_set("b", "objs", type_name="object")
        pad = "x" * row_bytes
        c.send_data("b", "objs", [{"i": i, "p": pad} for i in range(rows)])
        obj_bytes = rows * (row_bytes + 50)

        t0 = time.perf_counter()
        n1 = len(list(c.get_set_iterator("b", "objs")))
        t_single = time.perf_counter() - t0
        t0 = time.perf_counter()
        n2 = sum(1 for _ in c.scan_stream("b", "objs",
                                          max_frame_bytes=4 << 20))
        t_stream = time.perf_counter() - t0
        assert n1 == n2 == rows
        out["scan"] = {
            "payload_mb": round(obj_bytes / 2**20, 1),
            "single_frame_s": round(t_single, 3),
            "streamed_s": round(t_stream, 3),
            "single_peak_frame_mb": round(obj_bytes / 2**20, 1),
            "streamed_peak_frame_mb": 4,
            "streamed_mb_per_s": round(obj_bytes / 2**20 / t_stream, 1),
        }

        side = int((tensor_mb * 2**20 / 4) ** 0.5) // 128 * 128
        dense = np.random.default_rng(0).standard_normal(
            (side, side)).astype(np.float32)
        c.create_set("b", "w")
        c.send_matrix("b", "w", dense, (512, 512))
        t0 = time.perf_counter()
        a1 = c.get_tensor("b", "w").to_dense()
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter()
        a2 = c.get_tensor_chunked("b", "w", chunk_bytes=8 << 20).to_dense()
        t_chunk = time.perf_counter() - t0
        assert np.array_equal(a1, a2)
        out["tensor"] = {
            "payload_mb": round(dense.nbytes / 2**20, 1),
            "single_frame_s": round(t_one, 3),
            "chunked_s": round(t_chunk, 3),
            "chunked_peak_frame_mb": 8,
            "chunked_mb_per_s": round(dense.nbytes / 2**20 / t_chunk, 1),
        }
        c.close()
    finally:
        ctl.shutdown()
    return out


def run_data_plane_bench(table_mb: int = 64, chunk_mb: int = 8,
                         window: int = 4,
                         hedge_reads: int = 40) -> Dict[str, Any]:
    """v3 data-plane numbers on loopback: bulk-table ingest MB/s for
    the pre-change single-frame path (one pickled monolith) vs the
    streamed pipelined path (row-range column slices riding out-of-band
    segments, ``window`` chunks in flight), streamed scan MB/s, tensor
    push/pull MB/s over the zero-copy framing, and hedged-read p99
    against a tail-latency-injected primary.

    The daemon runs as a REAL subprocess (like ``run_serve_bench``):
    pipelining only overlaps client encode/send with server
    decode/apply when the two sides don't share a GIL."""
    import tempfile

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.serve.chaos import ChaosInjector
    from netsdb_tpu.serve.client import RemoteClient, RetryPolicy
    from netsdb_tpu.serve.server import ServeController

    out: Dict[str, Any] = {"table_mb": table_mb, "chunk_mb": chunk_mb,
                           "window": window}
    nrows = table_mb * (1 << 20) // 8  # two f32/int32 columns per row
    cols = {"a": np.arange(nrows, dtype=np.int32),
            "b": np.random.default_rng(0).standard_normal(nrows)
            .astype(np.float32)}
    table = ColumnTable(dict(cols), {}, None)
    payload_mb = sum(c.nbytes for c in cols.values()) / 2**20

    host = "127.0.0.1"
    port = _free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    daemon = subprocess.Popen(
        [_python(), "-m", "netsdb_tpu", "serve", "--port", str(port),
         "--root", tempfile.mkdtemp(prefix="dataplane_bench_")],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    try:
        _wait_port(host, port)
        c = RemoteClient(f"{host}:{port}", ingest_window=window,
                         ingest_chunk_bytes=chunk_mb << 20)
        c.create_database("b")

        def ingest(set_name: str, pipeline: bool, repeats: int = 2) -> float:
            """Best-of-N wall time of one full ingest (machine-load
            noise on shared hosts dwarfs run-to-run variance)."""
            best = None
            for r in range(repeats):
                name = f"{set_name}{r}"
                c.create_set("b", name, type_name="table")
                t0 = time.perf_counter()
                c.send_table("b", name, table, pipeline=pipeline)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best

        ingest("warm", True, repeats=1)  # compile/alloc warmup, excluded
        t_single = ingest("single", False)
        t_stream = ingest("streamed", True)
        out["ingest"] = {
            "payload_mb": round(payload_mb, 1),
            "single_frame_s": round(t_single, 3),
            "single_frame_mb_per_s": round(payload_mb / t_single, 1),
            "streamed_s": round(t_stream, 3),
            "streamed_mb_per_s": round(payload_mb / t_stream, 1),
            "speedup": round(t_single / t_stream, 2),
        }

        t0 = time.perf_counter()
        back = c.get_table_streamed("b", "streamed0",
                                    max_frame_bytes=chunk_mb << 20)
        t_scan = time.perf_counter() - t0
        assert back.num_rows == nrows
        out["scan"] = {"streamed_s": round(t_scan, 3),
                       "streamed_mb_per_s": round(payload_mb / t_scan, 1)}

        side = int((table_mb * (1 << 20) / 4) ** 0.5) // 128 * 128
        dense = np.random.default_rng(1).standard_normal(
            (side, side)).astype(np.float32)
        c.create_set("b", "w")
        t0 = time.perf_counter()
        c.send_matrix("b", "w", dense, (512, 512))
        t_push = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = c.get_tensor("b", "w").to_dense()
        t_pull = time.perf_counter() - t0
        assert got.shape == dense.shape
        mb = dense.nbytes / 2**20
        out["tensor"] = {
            "payload_mb": round(mb, 1),
            "push_mb_per_s": round(mb / t_push, 1),
            "pull_mb_per_s": round(mb / t_pull, 1),
        }
        c.close()

        # hedged reads: a replica daemon + a primary whose replies
        # stall with seeded probability — p99 with hedging should sit
        # near the replica RTT, not the injected stall
        pchaos = ChaosInjector(seed=7, delay=0.25, delay_s=0.15)
        slow = ServeController(Configuration(root_dir=tempfile.mkdtemp(
            prefix="dataplane_slow_")), port=0, chaos=pchaos)
        sport = slow.start()
        try:
            small = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
            for p in (sport, port):
                boot = RemoteClient(f"127.0.0.1:{p}")
                boot.create_database("h")
                boot.create_set("h", "w")
                boot.send_matrix("h", "w", small, (32, 32))
                boot.close()

            def read_p99(client) -> Dict[str, float]:
                lat = []
                for _ in range(hedge_reads):
                    t0 = time.perf_counter()
                    client.get_tensor("h", "w")
                    lat.append(time.perf_counter() - t0)
                lat.sort()
                return {"p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
                        "p99_ms": round(lat[int(0.99 * (len(lat) - 1))]
                                        * 1e3, 2)}

            plain = RemoteClient(f"127.0.0.1:{sport}",
                                 retry=RetryPolicy(max_attempts=2))
            unhedged = read_p99(plain)
            plain.close()
            hedged_c = RemoteClient(f"127.0.0.1:{sport}",
                                    replicas=[f"127.0.0.1:{port}"],
                                    hedge_delay_s=0.02,
                                    retry=RetryPolicy(max_attempts=2))
            hedged = read_p99(hedged_c)
            out["hedged_reads"] = {
                "injected_stall_ms": 150, "stall_rate": 0.25,
                "unhedged": unhedged, "hedged": hedged,
                "hedges_issued": hedged_c.hedges_issued,
                "hedges_won": hedged_c.hedges_won,
            }
            hedged_c.close()
        finally:
            slow.shutdown()
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
    return out


def run_device_cache_bench(rows: int = 1_200_000, page_rows: int = 65_536,
                           pool_mb: int = 8, repeats: int = 4,
                           cache_mb: int = 256) -> Dict[str, Any]:
    """Cold vs warm EXECUTE latency for a q01-style query over a
    device-cache-resident paged set — the buffer-pool payoff measured
    at the serve surface (``--device-cache``).

    One in-process daemon owns a paged ``lineitem`` whose arena pool is
    far smaller than the table (cold streams re-read spilled pages).
    Phases:

    * **uncached** — device cache budget 0: every EXECUTE re-reads the
      arena, re-pads and re-uploads each chunk (first run additionally
      compiles; reported separately). Best-of-N steady state.
    * **warm** — cache on: one installing run, then best-of-N warm
      runs that replay device-resident blocks. The cache's miss
      counter is asserted FLAT across the warm runs — zero host→device
      transfers for the cached set blocks.

    ``speedup`` = uncached steady / warm. On CPU the "device" is host
    RAM, so the number understates real HBM transfer savings (same
    caveat as the PR 3 staging bench); the structural claims — miss
    counter flat, hit counters advancing — are platform-independent."""
    import tempfile

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController

    cfg = Configuration(root_dir=tempfile.mkdtemp(prefix="devcache_bench_"),
                        page_size_bytes=page_rows * 4,
                        page_pool_bytes=pool_mb << 20,
                        device_cache_bytes=cache_mb << 20)
    ctl = ServeController(cfg, port=0)
    port = ctl.start()
    out: Dict[str, Any] = {"rows": rows, "pool_mb": pool_mb,
                           "cache_mb": cache_mb}
    try:
        c = RemoteClient(f"127.0.0.1:{port}")
        rng = np.random.default_rng(0)
        cols = {
            "l_shipdate": rng.integers(19920101, 19981231, rows,
                                       dtype=np.int32),
            "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
            "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
            "l_quantity": rng.integers(1, 51, rows,
                                       dtype=np.int32).astype(np.float32),
            "l_extendedprice": rng.uniform(1000, 100000,
                                           rows).astype(np.float32),
            "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
            "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
        }
        out["table_mb"] = round(sum(v.nbytes for v in cols.values())
                                / 2**20, 1)
        c.create_database("d")
        c.create_set("d", "lineitem", type_name="table", storage="paged")
        c.send_table("d", "lineitem",
                     ColumnTable(cols, {"l_returnflag": ["A", "N", "R"],
                                        "l_linestatus": ["F", "O"]}))
        sink = rdag.q01_sink("d")
        cache = ctl.library.store.device_cache()

        def run_once() -> float:
            t0 = time.perf_counter()
            c.execute_computations(sink, job_name="q01-devcache",
                                   fetch_results=False)
            return time.perf_counter() - t0

        # phase 1: cache off — the pre-cache serve data path
        cache.resize(0)
        out["cold_first_s"] = round(run_once(), 4)  # includes compile
        out["uncached_steady_s"] = round(
            min(run_once() for _ in range(repeats)), 4)

        # phase 2: cache on — one installing run, then warm replays
        cache.resize(cache_mb << 20)
        out["install_run_s"] = round(run_once(), 4)
        m0 = cache.stats()["misses"]
        out["warm_s"] = round(min(run_once() for _ in range(repeats)), 4)
        st = cache.stats()
        out["warm_misses_flat"] = (st["misses"] == m0)
        out["speedup_warm_vs_uncached"] = round(
            out["uncached_steady_s"] / out["warm_s"], 2)
        out["cache_stats"] = st
        c.close()
    finally:
        ctl.shutdown()
    return out


def run_partial_cache_bench(rows: int = 1_200_000,
                            page_rows: int = 65_536,
                            pool_mb: int = 8, cache_mb: int = 256,
                            append_frac: float = 0.01,
                            cycles: int = 3) -> Dict[str, Any]:
    """Paired A/B for block-granular partial-run caching
    (``--partial-cache``): the WARM RE-QUERY AFTER A SMALL APPEND,
    partial dirty-range invalidation vs whole-run invalidation.

    Both arms run the identical protocol on a fresh in-process daemon:
    ingest a 1.2M-row paged q01 ``lineitem``, warm the device cache
    (install + one warm run), then ``cycles`` rounds of: append
    ``append_frac`` of the rows → time ONE warm re-query. Under
    whole-run invalidation the append unkeys the entire cached run, so
    the re-query re-reads/re-uploads every page; under partial
    invalidation only the appended tail range is dirty, so the
    re-query stitches every pre-append block from HBM and stages only
    the tail. Reported per arm: best-of-cycles warm-after-append
    seconds; plus the partial arm's structural proof — ZERO evictions
    of pre-append blocks across the appends and ``partial_hits`` > 0.

    ``devcache_partial_speedup`` = whole_run / partial (the bench.py
    ``--compare`` headline; acceptance floor 2×). CPU-container
    caveat: the "device" is host RAM, so re-upload savings understate
    real HBM numbers — the ratio is the claim, not the absolute
    seconds (same caveat as ``--device-cache``)."""
    import shutil
    import tempfile

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController

    rng = np.random.default_rng(0)
    cols = {
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows,
                                   dtype=np.int32).astype(np.float32),
        "l_extendedprice": rng.uniform(1000, 100000,
                                       rows).astype(np.float32),
        "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
        "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
    }
    dicts = {"l_returnflag": ["A", "N", "R"],
             "l_linestatus": ["F", "O"]}
    n_extra = max(int(rows * append_frac), 1)
    out: Dict[str, Any] = {"rows": rows, "pool_mb": pool_mb,
                           "cache_mb": cache_mb,
                           "append_rows": n_extra, "cycles": cycles}

    def arm(partial: bool) -> Dict[str, Any]:
        root = tempfile.mkdtemp(prefix="partial_bench_")
        cfg = Configuration(root_dir=root,
                            page_size_bytes=page_rows * 4,
                            page_pool_bytes=pool_mb << 20,
                            device_cache_bytes=cache_mb << 20,
                            device_cache_partial=partial)
        ctl = ServeController(cfg, port=0)
        port = ctl.start()
        try:
            c = RemoteClient(f"127.0.0.1:{port}")
            c.create_database("d")
            c.create_set("d", "lineitem", type_name="table",
                         storage="paged")
            c.send_table("d", "lineitem", ColumnTable(cols, dicts))
            sink = rdag.q01_sink("d")
            cache = ctl.library.store.device_cache()

            def run_once() -> float:
                t0 = time.perf_counter()
                c.execute_computations(sink, job_name="q01-partial",
                                       fetch_results=False)
                return time.perf_counter() - t0

            run_once()                      # cold (compile + install)
            warm_s = run_once()             # fully warm
            blocks0 = cache.stats()["entries"]
            ev0 = cache.stats()["evictions"]
            times = []
            for i in range(cycles):
                extra = {k: v[:n_extra] for k, v in cols.items()}
                c.send_table("d", "lineitem",
                             ColumnTable(extra, dicts), append=True)
                times.append(run_once())    # warm-after-append
            st = cache.stats()
            res = {"warm_s": round(warm_s, 4),
                   "warm_after_append_s": round(min(times), 4),
                   "warm_after_append_all": [round(t, 4)
                                             for t in times],
                   "blocks_before_appends": blocks0,
                   "cache_stats": st}
            if partial:
                res["pre_append_evictions"] = st["evictions"] - ev0
                res["partial_hits"] = st["partial_hits"]
            c.close()
            return res
        finally:
            ctl.shutdown()
            shutil.rmtree(root, ignore_errors=True)

    out["whole_run"] = arm(False)
    out["partial"] = arm(True)
    p, w = out["partial"], out["whole_run"]
    if p["warm_after_append_s"] > 0:
        out["devcache_partial_speedup"] = round(
            w["warm_after_append_s"] / p["warm_after_append_s"], 2)
    # the acceptance structure: appends evicted NOTHING and the warm
    # re-queries stitched resident blocks
    out["partial_zero_evictions"] = (p.get("pre_append_evictions") == 0)
    out["partial_hits_positive"] = (p.get("partial_hits", 0) > 0)
    return out


# --- horizontal scale-out (--scale) ----------------------------------

def scaleout_table(rows: int, seed: int = 0):
    """The q01-style paged workload with INTEGER measures: partial
    sums stay exactly representable, so the 4-daemon scatter-gather
    result must be BYTE-equal to the 1-daemon run (float q01 differs
    by merge-order reassociation in the last ulp — this workload is
    the acceptance oracle, the shape is identical)."""
    import numpy as np

    from netsdb_tpu.relational.table import ColumnTable

    rng = np.random.default_rng(seed)
    cols = {
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows, dtype=np.int32),
        "l_price": rng.integers(1, 1000, rows, dtype=np.int32),
    }
    return ColumnTable(cols, {"l_returnflag": ["A", "N", "R"],
                              "l_linestatus": ["F", "O"]})


def scaleout_q01_sink(db: str, cutoff: int = 19980902,
                      lineitem_set: str = "lineitem",
                      output_set: str = "scale_q01_out"):
    """SCAN(lineitem) → APPLY(int group-by fold) → OUTPUT: per
    (returnflag, linestatus) group, int32 count + sum(qty) +
    sum(price) under a shipdate cutoff. Single-pass fold with a
    declared ``state_merge`` (tree add) — the scatterable q01 shape
    with exact integer accumulators."""
    import jax.numpy as jnp

    from netsdb_tpu.plan.computations import Apply, ScanSet, WriteSet
    from netsdb_tpu.plan.fold import single_pass, tree_add_states
    from netsdb_tpu.relational.table import ColumnTable

    n_groups = 6  # 3 returnflags x 2 linestatuses

    def init(prev, src):
        z = jnp.zeros((n_groups,), jnp.int32)
        return (z, z, z)

    def step(state, chunk):
        counts, qty, price = state
        ok = chunk.mask() & (chunk["l_shipdate"] <= cutoff)
        gid = jnp.where(ok, chunk["l_returnflag"] * 2
                        + chunk["l_linestatus"], 0)
        one = jnp.where(ok, 1, 0).astype(jnp.int32)
        return (counts.at[gid].add(one),
                qty.at[gid].add(jnp.where(ok, chunk["l_quantity"], 0)),
                price.at[gid].add(jnp.where(ok, chunk["l_price"], 0)))

    def fin(state, src):
        counts, qty, price = state
        gid = jnp.arange(n_groups, dtype=jnp.int32)
        return ColumnTable(
            cols={"l_returnflag": gid // 2, "l_linestatus": gid % 2,
                  "count": counts, "sum_qty": qty, "sum_price": price},
            dicts={"l_returnflag": src.dicts["l_returnflag"],
                   "l_linestatus": src.dicts["l_linestatus"]},
            valid=counts > 0)

    return WriteSet(Apply(ScanSet(db, lineitem_set),
                          fold=single_pass(init, step, fin,
                                           state_merge=tree_add_states),
                          label=f"scaleq01:{cutoff}"),
                    db, output_set)


def scaleout_join_sink(db: str, key_space: int,
                       lineitem_set: str = "lineitem",
                       orders_set: str = "orders",
                       output_set: str = "scale_join_out"):
    """Grace-hash-capable revenue join with INTEGER accumulators:
    per-order sum of lineitem prices via a LUT probe. Declared
    probe/build keys + an output merge make it a distributed-shuffle
    join over a sharded pool; every order's lineitems co-locate on its
    key's shuffle bucket, so the sharded result is byte-equal to the
    single-node run."""
    import jax.numpy as jnp

    from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet
    from netsdb_tpu.plan.fold import single_pass
    from netsdb_tpu.relational.table import ColumnTable

    def init(prev, src, orders):
        return jnp.zeros((orders.num_rows,), jnp.int32)

    def step(acc, li, orders):
        lut = jnp.full((key_space,), -1, jnp.int32).at[
            orders["o_orderkey"]].set(
            jnp.arange(orders.num_rows, dtype=jnp.int32))
        oidx = lut[li["l_orderkey"]]
        ok = (oidx >= 0) & li.mask()
        return acc.at[jnp.where(ok, oidx, 0)].add(
            jnp.where(ok, li["l_price"], 0))

    def fin(acc, src, orders):
        return ColumnTable(cols={"okey": orders["o_orderkey"],
                                 "rev": acc},
                           valid=acc > 0)

    def merge(a, b):
        return ColumnTable(
            cols={"okey": jnp.concatenate([a["okey"], b["okey"]]),
                  "rev": jnp.concatenate([a["rev"], b["rev"]])},
            valid=jnp.concatenate([a.mask(), b.mask()]))

    return WriteSet(
        Join(ScanSet(db, lineitem_set), ScanSet(db, orders_set),
             fold=single_pass(init, step, fin, merge,
                              probe_key="l_orderkey",
                              build_key="o_orderkey",
                              probe_columns=("l_price",)),
             label=f"scalejoin:{key_space}"),
        db, output_set)


def _scale_rows(client, db: str, out_set: str):
    """Decoded, canonically-ordered result rows (the byte-equality
    probe)."""
    import numpy as np

    t = client.get_table(db, out_set)
    ok = np.asarray(t.mask()) if t.valid is not None \
        else np.ones(t.num_rows, bool)
    names = sorted(t.cols)
    rows = [tuple(int(np.asarray(t[n])[i]) for n in names)
            for i in range(t.num_rows) if ok[i]]
    return sorted(rows)


def run_scaleout_bench(rows: int = 6_000_000, daemons: int = 4,
                       queries: int = 6, page_rows: int = 65_536,
                       join_orders: int = 2048,
                       join_rows: int = 400_000) -> Dict[str, Any]:
    """Paired 1 vs N-daemon arm (``--scale``): aggregate ingest MB/s
    (client-routed partitions vs one daemon) and cold scatter-gather
    q01 QPS over the same paged workload, plus the byte-equality
    checks — the sharded q01 result AND a grace-hash join routed
    through the distributed shuffle must equal the single-node run
    exactly (integer accumulators).

    Daemons are real subprocesses (parallel apply needs separate
    GILs). The device cache is disabled daemon-side so every query
    re-streams its pages — the COLD query path is what capacity
    scaling is about. CPU-container caveat: all daemons share one
    machine's cores, so the reported scale is a lower bound on a
    real multi-host pool (same caveat class as BENCH_r06/r07)."""
    import tempfile

    import numpy as np

    from netsdb_tpu.serve.client import RemoteClient

    table = scaleout_table(rows)
    payload_mb = sum(np.asarray(v).nbytes
                     for v in table.cols.values()) / 2**20
    rng = np.random.default_rng(7)
    join_li_cols = {
        "l_orderkey": rng.integers(0, join_orders, join_rows,
                                   dtype=np.int32),
        "l_price": rng.integers(1, 1000, join_rows, dtype=np.int32)}
    from netsdb_tpu.relational.table import ColumnTable

    join_li = ColumnTable(join_li_cols, {}, None)
    join_orders_tbl = ColumnTable(
        {"o_orderkey": np.arange(join_orders, dtype=np.int32)}, {},
        None)

    def spawn(port: int, workers: Optional[List[str]] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        argv = [_python(), "-m", "netsdb_tpu", "serve",
                "--port", str(port),
                "--root", tempfile.mkdtemp(prefix=f"scale_{port}_"),
                "--device-cache-mb", "0",
                "--page-kb", str(page_rows * 4 // 1024)]
        if workers:
            argv += ["--workers", ",".join(workers)]
        return subprocess.Popen(
            argv, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))

    def run_arm(n: int) -> Dict[str, Any]:
        ports = [_free_port() for _ in range(n)]
        worker_addrs = [f"127.0.0.1:{p}" for p in ports[1:]]
        procs = [spawn(p) for p in ports[1:]]
        procs.insert(0, spawn(ports[0], workers=worker_addrs or None))
        out: Dict[str, Any] = {"daemons": n}
        try:
            for p in ports:
                _wait_port("127.0.0.1", p)
            c = RemoteClient(f"127.0.0.1:{ports[0]}")
            c.create_database("d")
            kw = {"placement": "range"} if n > 1 else {}
            # ingest warmup: every daemon's first ingest pays lazy
            # imports + arena setup once — both arms exclude it
            c.create_set("d", "warm", type_name="table",
                         storage="paged", **kw)
            c.send_table("d", "warm", scaleout_table(4096, seed=9))
            c.create_set("d", "lineitem", type_name="table",
                         storage="paged", **kw)
            t0 = time.perf_counter()
            c.send_table("d", "lineitem", table)
            ingest_s = time.perf_counter() - t0
            out["ingest_s"] = round(ingest_s, 3)
            out["ingest_mb_per_s"] = round(payload_mb / ingest_s, 1)

            sink = scaleout_q01_sink("d")
            # warmup compiles (both arms pay it once, excluded)
            c.execute_computations(sink, job_name="scale-q01-warm",
                                   fetch_results=False)
            t0 = time.perf_counter()
            for _ in range(queries):
                c.execute_computations(sink, job_name="scale-q01",
                                       fetch_results=False)
            q_s = time.perf_counter() - t0
            out["query_s_total"] = round(q_s, 3)
            out["cold_query_qps"] = round(queries / q_s, 3)
            out["q01_rows"] = _scale_rows(c, "d", "scale_q01_out")

            # the distributed-shuffle join leg
            jkw = {"placement": "hash"} if n > 1 else {}
            c.create_set("d", "jli", type_name="table", **jkw)
            c.create_set("d", "jorders", type_name="table", **jkw)
            c.send_table("d", "jli", join_li)
            c.send_table("d", "jorders", join_orders_tbl)
            jsink = scaleout_join_sink("d", join_orders,
                                       lineitem_set="jli",
                                       orders_set="jorders")
            t0 = time.perf_counter()
            c.execute_computations(jsink, job_name="scale-join",
                                   fetch_results=False)
            out["join_s"] = round(time.perf_counter() - t0, 3)
            out["join_rows"] = _scale_rows(c, "d", "scale_join_out")
            c.close()
        finally:
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        return out

    single = run_arm(1)
    pool = run_arm(daemons)
    out: Dict[str, Any] = {
        "rows": rows, "payload_mb": round(payload_mb, 1),
        "daemons": daemons, "queries": queries,
        "single": {k: v for k, v in single.items()
                   if not k.endswith("_rows")},
        "pool": {k: v for k, v in pool.items()
                 if not k.endswith("_rows")},
        "ingest_scale_x": round(pool["ingest_mb_per_s"]
                                / single["ingest_mb_per_s"], 2),
        "query_scale_x": round(pool["cold_query_qps"]
                               / single["cold_query_qps"], 2),
        "q01_byte_equal": pool["q01_rows"] == single["q01_rows"],
        "join_byte_equal": pool["join_rows"] == single["join_rows"],
    }
    out["scaleout_throughput_x"] = round(
        min(out["ingest_scale_x"], out["query_scale_x"]), 2)
    return out


def run_scheduler_bench(clients: int = 8, rows: int = 600_000,
                        page_rows: int = 65_536, pool_mb: int = 8,
                        cache_mb: int = 256) -> Dict[str, Any]:
    """Paired A/B for the query scheduler (``--scheduler``): N
    concurrent byte-identical cold EXECUTEs over one paged set,
    scheduler on vs off. Reported per phase: executions actually run,
    devcache installs, coalesce hits, and client latency p50/p99.

    With the scheduler ON the N identical frames collapse into ONE
    execution (one devcache install, N−1 coalesce hits) and every
    client's latency ≈ the single execution; OFF, N cold streams race
    through one arena (N executions, up to N installs) and the p99 is
    the thrashed tail. Both phases run compile-warm (a separate warmup
    daemon pays the XLA trace once — the in-process jit cache is
    shared) and devcache-cold (fresh store per phase), so the delta
    isolates the scheduling policy."""
    import tempfile
    import threading

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController
    from netsdb_tpu import obs

    rng = np.random.default_rng(0)
    cols = {
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows,
                                   dtype=np.int32).astype(np.float32),
        "l_extendedprice": rng.uniform(1000, 100000,
                                       rows).astype(np.float32),
        "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
        "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
    }
    table = ColumnTable(cols, {"l_returnflag": ["A", "N", "R"],
                               "l_linestatus": ["F", "O"]})
    sink = rdag.q01_sink("d")

    def make_ctl(sched_on: bool) -> ServeController:
        cfg = Configuration(
            root_dir=tempfile.mkdtemp(prefix="sched_bench_"),
            page_size_bytes=page_rows * 4,
            page_pool_bytes=pool_mb << 20,
            device_cache_bytes=cache_mb << 20,
            sched_coalesce=sched_on, sched_affinity=sched_on)
        ctl = ServeController(cfg, port=0, max_jobs=clients)
        ctl.start()
        return ctl

    def load(addr: str) -> None:
        c = RemoteClient(addr)
        c.create_database("d")
        c.create_set("d", "lineitem", type_name="table",
                     storage="paged")
        c.send_table("d", "lineitem", table)
        c.close()

    def phase(sched_on: bool) -> Dict[str, Any]:
        ctl = make_ctl(sched_on)
        addr = f"127.0.0.1:{ctl.port}"
        try:
            load(addr)
            cache = ctl.library.store.device_cache()
            installs0 = cache.stats()["installs"]
            hits0 = obs.REGISTRY.counter("sched.coalesce_hits").value
            barrier = threading.Barrier(clients)
            lat: List[Optional[float]] = [None] * clients

            def worker(i: int) -> None:
                c = RemoteClient(addr, client_id=f"tenant-{i}")
                try:
                    barrier.wait()
                    t0 = time.perf_counter()
                    c.execute_computations(sink, job_name="q01-sched",
                                           fetch_results=False)
                    lat[i] = time.perf_counter() - t0
                finally:
                    c.close()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            done = sorted(v for v in lat if v is not None)
            with ctl._jobs_lock:
                executions = sum(1 for j in ctl._jobs.values()
                                 if j["name"] == "q01-sched")
            return {
                "clients": clients,
                "executions_run": executions,
                "devcache_installs": cache.stats()["installs"]
                - installs0,
                "coalesce_hits":
                    obs.REGISTRY.counter("sched.coalesce_hits").value
                    - hits0,
                # nearest-rank (ceil) quantiles throughout: at N=8 the
                # p99 is the MAX — the thrashed single worst client is
                # exactly the tail this metric exists to measure,
                # never dropped
                "p50_s": round(
                    done[max(-(-50 * len(done) // 100) - 1, 0)], 4)
                if done else None,
                "p99_s": round(
                    done[min(len(done) - 1,
                             -(-99 * len(done) // 100) - 1)], 4)
                if done else None,
            }
        finally:
            ctl.shutdown()

    # compile warmup on a throwaway daemon (the jit cache is
    # process-wide; both measured phases then isolate the data path)
    warm = make_ctl(True)
    try:
        load(f"127.0.0.1:{warm.port}")
        c = RemoteClient(f"127.0.0.1:{warm.port}")
        c.execute_computations(sink, job_name="warmup",
                               fetch_results=False)
        c.close()
    finally:
        warm.shutdown()

    off = phase(False)
    on = phase(True)
    out: Dict[str, Any] = {"rows": rows, "clients": clients,
                           "scheduler_off": off, "scheduler_on": on}
    if on.get("p99_s") and off.get("p99_s"):
        out["p99_speedup"] = round(off["p99_s"] / on["p99_s"], 2)
        out["p50_speedup"] = round(off["p50_s"] / on["p50_s"], 2)
    return out


def run_serving_bench(daemons: int = 4, batch: int = 8192,
                      features: int = 256, hidden: int = 512,
                      labels: int = 64, frames: int = 6,
                      block=(128, 128)) -> Dict[str, Any]:
    """End-to-end model serving over the sharded pool (``--serving``):
    the ``ff_inference_rows_per_sec_per_chip`` headline measured the
    way the reference serves it — deploy once (weights replicated,
    inputs range-partitioned over leader + N−1 workers), then batched
    scoring frames through ``models.serving.ModelServing``: routed
    batch ingest, the tensor_chain scatter, ONE compiled program per
    shard, slot-order gather.

    The figure is only trusted when the structural gates hold on this
    run: (1) the pool output is byte-equal to a solo daemon scoring
    the same bytes (integer-valued f32 weights make it bit-exact);
    (2) every shard's EXPLAIN tree reports ``whole_plan_jit`` with
    every plan node fused — one program per shard; (3) no daemon holds
    more than ceil(B/N) input rows — the ≤1/N staged-bytes proof.
    CPU-container caveat: all daemons share one machine's cores, so
    rows/s/chip is a lower bound on a per-chip pool; the gates are
    platform-independent."""
    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.models.ff import FFModel
    from netsdb_tpu.models.serving import ff_serving
    from netsdb_tpu.serve import placement as PL
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController
    from netsdb_tpu.storage.store import SetIdentifier
    import tempfile

    rng = np.random.default_rng(0)

    def ints(shape):
        return rng.integers(-3, 3, size=shape).astype(np.float32)

    weights = (ints((hidden, features)), ints((hidden,)),
               ints((labels, hidden)), ints((labels,)))
    batches = [ints((batch, features)) for _ in range(frames)]

    def make_ctl(tag, workers=None):
        ctl = ServeController(
            Configuration(root_dir=tempfile.mkdtemp(
                prefix=f"serving_{tag}_")),
            port=0, workers=workers)
        ctl.start()
        return ctl

    def solo_arm() -> Dict[str, Any]:
        ctl = make_ctl("solo")
        try:
            c = RemoteClient(f"127.0.0.1:{ctl.port}")
            m = FFModel(db="ffsolo", block=block)
            m.setup(c)
            m.load_weights(c, *weights)
            m.load_inputs(c, batches[0])
            res = c.execute_computations(m.build_inference_dag(),
                                         job_name="solo-warm")
            oracle = np.asarray(next(iter(res.values())).to_dense())
            t0 = time.perf_counter()
            for b in batches:
                m.load_inputs(c, b)
                c.execute_computations(m.build_inference_dag(),
                                       job_name="solo",
                                       fetch_results=False)
            dt = time.perf_counter() - t0
            c.close()
            return {"oracle": oracle,
                    "rows_per_sec": round(frames * batch / dt, 1)}
        finally:
            ctl.shutdown()

    solo = solo_arm()
    out: Dict[str, Any] = {
        "daemons": daemons, "batch": batch, "frames": frames,
        "shape": [features, hidden, labels],
        "solo_rows_per_sec": solo["rows_per_sec"],
    }

    workers = [make_ctl(f"w{i}") for i in range(daemons - 1)]
    leader = make_ctl("leader",
                      workers=[f"127.0.0.1:{w.port}" for w in workers])
    try:
        model = FFModel(db="ffserving", block=block)

        def load(c):
            model.setup(c)
            model.load_weights(c, *weights)

        srv = ff_serving(model, f"127.0.0.1:{leader.port}",
                         block=model.block)
        addrs = srv.deploy(load)
        out["slots"] = len(addrs)

        # cold frame carries the per-layer EXPLAIN decomposition and
        # the structural gates
        cold, forest = srv.score(batches[0], explain=True)
        out["byte_equal"] = bool(
            np.asarray(cold.to_dense()).tobytes()
            == solo["oracle"].tobytes())
        one_program = sorted(forest) == sorted(addrs)
        shard_trees = {}
        for daemon, tree in forest.items():
            nodes = [n for n in tree["nodes"]
                     if n.get("kind") != "WholePlanJit"]
            one_program &= tree["mode"] == "whole_plan_jit" \
                and bool(nodes) and all(n.get("fused") for n in nodes)
            shard_trees[daemon] = {
                "mode": tree["mode"],
                "layers": [f"{n['kind']}:{n.get('label', '')}"
                           for n in nodes]}
        out["one_program_per_shard"] = bool(one_program)
        out["explain_shard"] = shard_trees[addrs[0]]

        # <=1/N structural proof: no daemon holds more input rows
        # than its contiguous range slice
        bound = max(hi - lo
                    for lo, hi in PL.range_slices(batch, len(addrs)))
        max_rows, total_rows = 0, 0
        for ctl in [leader] + workers:
            for it in ctl.library.store.get_items(
                    SetIdentifier("ffserving", "inputs")):
                rows = int(np.asarray(it.to_dense()).shape[0]) \
                    if hasattr(it, "to_dense") else 0
                max_rows = max(max_rows, rows)
                total_rows += rows
        out["rows_bound_ok"] = bool(
            max_rows <= bound and total_rows == batch)
        out["per_shard_max_row_frac"] = round(max_rows / batch, 3)

        # warm frames: every shard rides its compiled program; each
        # frame is DIFFERENT bytes so no coalescing can shortcut it
        t0 = time.perf_counter()
        for b in batches:
            srv.score(b)
        dt = time.perf_counter() - t0
        srv.close()
        total = frames * batch
        out["pool_rows_per_sec"] = round(total / dt, 1)
        out["rows_per_sec_per_chip"] = round(total / dt / daemons, 1)
        out["gates_ok"] = bool(out["byte_equal"]
                               and out["one_program_per_shard"]
                               and out["rows_bound_ok"])
    finally:
        for d in [leader] + workers:
            d.shutdown()
    return out


def run_failover_bench(batches: int = 24, rows_each: int = 2000,
                       kill_after: int = 12,
                       election_s: float = 0.35) -> Dict[str, Any]:
    """Failover-under-traffic (``--failover``): the measured HA
    p99-blip bound the PR 16 acceptance left open. A client streams
    append batches against an armed leader+follower pair (every write
    log-shipped); mid-stream the leader is killed. Each logical
    request's latency INCLUDES its typed-retry failover rotation, so
    the post-kill maximum is the client-observed blip bound. The
    record is only trusted when the promotion happened and totals are
    exact — zero lost, zero doubled writes."""
    import tempfile

    from netsdb_tpu import obs
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.serve import ha as ha_mod
    from netsdb_tpu.serve.client import RemoteClient, RetryPolicy
    from netsdb_tpu.serve.errors import RetryableRemoteError
    from netsdb_tpu.serve.server import ServeController
    from netsdb_tpu.storage.store import SetIdentifier

    kw = dict(heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
              heartbeat_misses=2, mirror_ack_timeout_s=5.0,
              resync_grace_s=2.0)
    follower = ServeController(
        Configuration(root_dir=tempfile.mkdtemp(prefix="ha_f_")),
        port=0, **kw)
    follower.start()
    leader = ServeController(
        Configuration(root_dir=tempfile.mkdtemp(prefix="ha_l_")),
        port=0, followers=[follower.advertise_addr], **kw)
    leader.start()
    out: Dict[str, Any] = {"batches": batches, "rows_each": rows_each,
                           "election_s": election_s}
    try:
        peers = [leader.advertise_addr, follower.advertise_addr]
        for d in (leader, follower):
            d.arm_ha(peers, election_timeout_s=election_s)
        c = RemoteClient(leader.advertise_addr,
                         failover=[follower.advertise_addr],
                         retry=RetryPolicy(max_attempts=80,
                                           base_delay_s=0.05,
                                           max_delay_s=0.25))
        c.create_database("d")
        c.create_set("d", "t", type_name="table")
        table = scaleout_table(rows_each, seed=1)
        lat: List[float] = []
        promos0 = obs.REGISTRY.counter("ha.promotions").value
        done = 0
        for i in range(batches):
            if i == kill_after:
                leader.shutdown()  # mid-traffic kill
            t0 = time.perf_counter()
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    c.send_table("d", "t",
                                 scaleout_table(rows_each, seed=i),
                                 append=True)
                    done += 1
                    break
                except RetryableRemoteError:
                    if time.monotonic() > deadline:
                        break
                    time.sleep(0.05)
            lat.append(time.perf_counter() - t0)
        del table

        def pctl(vals, p):
            vals = sorted(vals)
            return vals[min(len(vals) - 1, -(-p * len(vals) // 100) - 1)]

        steady = lat[:kill_after]
        after = lat[kill_after:]
        out["steady_p50_s"] = round(pctl(steady, 50), 4)
        out["steady_p99_s"] = round(pctl(steady, 99), 4)
        out["blip_p99_s"] = round(pctl(after, 99), 4)
        out["blip_max_s"] = round(max(after), 4)
        out["blip_x"] = round(out["blip_p99_s"]
                              / max(out["steady_p99_s"], 1e-9), 2)
        out["promoted"] = bool(
            follower._ha.role == ha_mod.LEADER
            and obs.REGISTRY.counter("ha.promotions").value
            == promos0 + 1)
        total = sum(
            int(getattr(it, "num_rows", 0) or 0)
            for it in follower.library.store.get_items(
                SetIdentifier("d", "t")))
        out["exact_totals"] = bool(done == batches
                                   and total == batches * rows_each)
        c.close()
    finally:
        for d in (leader, follower):
            d.shutdown()
    return out


# --- distributed fusion A/B (--fusion-distributed) --------------------

def run_fusion_distributed_bench(rows: int = 400_000, daemons: int = 4,
                                 queries: int = 6) -> Dict[str, Any]:
    """Paired mapper A/B over the distributed compilation path
    (``--fusion-distributed``): the 4-daemon scatter q01 plus a
    3-sink dashboard fan, run under three arms — ``optimal`` (the
    region path: ONE compiled partial-fold program per shard, ONE
    coordinator merge+finalize program, the fan shipped as one
    multi-sink subplan per shard), ``greedy`` (``fusion_mapper=
    greedy``: the pre-region scatter path) and ``off``
    (``plan_fusion=False``). Arms share nothing but the workload:
    each gets its own in-process pool, ingest and job names, so every
    arm cold-compiles its own programs.

    The headline ``plan_fusion_distributed_speedup`` is off-arm p50
    round latency over optimal-arm p50 across the warm timed rounds
    (each round = one q01 + one 3-cutoff fan), and is only trusted
    when the
    structural gates hold on THIS run: (1) the optimal arm's cold
    q01 minted exactly one ``fold::`` key and one
    ``region::…::merge`` key with ``shard.subplans`` advancing by
    ``daemons``; (2) the fan ran as ONE scatter query with one
    multi-sink subplan per daemon; (3) q01 rows and every fan sink
    are byte-equal across all three arms. CPU-container caveat: all
    daemons share one machine's cores and the q01 fold states are
    small, so the paired delta is a lower bound on a pool whose
    merge+finalize closes over real state width; the gates are
    platform-independent."""
    import tempfile

    from netsdb_tpu import obs
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.plan import executor
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController

    cuts = (19950101, 19970101, 19980902)

    def counter(name: str) -> int:
        return obs.REGISTRY.counter(name).value

    def pool(tag: str, **cfg_extra):
        cfg = dict({"page_size_bytes": 64 * 1024}, **cfg_extra)
        ctls = []
        for i in range(daemons - 1):
            w = ServeController(Configuration(
                root_dir=tempfile.mkdtemp(prefix=f"fzd_{tag}_w{i}_"),
                **cfg), port=0)
            w.start()
            ctls.append(w)
        leader = ServeController(Configuration(
            root_dir=tempfile.mkdtemp(prefix=f"fzd_{tag}_l_"), **cfg),
            port=0, workers=[f"127.0.0.1:{w.port}" for w in ctls])
        leader.start()
        return [leader] + ctls

    table = scaleout_table(rows)

    def run_arm(tag: str, **cfg_extra) -> Dict[str, Any]:
        ctls = pool(tag, **cfg_extra)
        try:
            c = RemoteClient(f"127.0.0.1:{ctls[0].port}")
            c.create_database("d")
            c.create_set("d", "lineitem", type_name="table",
                         storage="paged", placement="range")
            c.send_table("d", "lineitem", table)

            def fan_sinks(prefix: str):
                return [scaleout_q01_sink(
                    "d", cutoff=ct, output_set=f"{prefix}_{i}")
                    for i, ct in enumerate(cuts)]

            # cold round: compiles every program the warm rounds ride
            keys0 = set(executor.compiled_cache_keys())
            sp0 = counter("shard.subplans")
            sq0 = counter("shard.scatter_queries")
            c.execute_computations(scaleout_q01_sink("d"),
                                   job_name=f"fzd-{tag}-q01",
                                   fetch_results=False)
            q01_new = set(executor.compiled_cache_keys()) - keys0
            q01_subplans = counter("shard.subplans") - sp0
            sp1 = counter("shard.subplans")
            sq1 = counter("shard.scatter_queries")
            c.execute_computations(*fan_sinks("fan"),
                                   job_name=f"fzd-{tag}-fan",
                                   fetch_results=False)
            arm = {
                "q01_fold_keys": sum(
                    1 for k in q01_new if k.startswith("fold::")),
                "q01_merge_keys": sum(
                    1 for k in q01_new
                    if k.startswith(f"region::fzd-{tag}-q01::scatter::")
                    and f"::merge::k{daemons}::" in k),
                "q01_other_keys": sum(
                    1 for k in q01_new
                    if not k.startswith(("fold::", "region::"))),
                "q01_subplans": q01_subplans,
                "fan_scatter_queries":
                    counter("shard.scatter_queries") - sq1,
                "fan_subplans": counter("shard.subplans") - sp1,
                "q01_scatter_queries": sq1 - sq0,
            }

            # warm timed rounds: every program cached, so the paired
            # delta isolates the dispatch path (region executor +
            # compiled merge vs eager per-node + eager merge). Two
            # untimed warm rounds first — the jit dispatch path keeps
            # warming for a couple of calls after the cold compile,
            # and timing those would charge warmup to the fused arm.
            def round_once() -> float:
                t0 = time.perf_counter()
                c.execute_computations(scaleout_q01_sink("d"),
                                       job_name=f"fzd-{tag}-q01",
                                       fetch_results=False)
                c.execute_computations(*fan_sinks("fan"),
                                       job_name=f"fzd-{tag}-fan",
                                       fetch_results=False)
                return time.perf_counter() - t0

            for _ in range(2):
                round_once()
            lat = sorted(round_once() for _ in range(queries))
            arm["wall_s"] = round(sum(lat), 4)
            arm["round_p50_s"] = round(lat[len(lat) // 2], 4)
            arm["round_min_s"] = round(lat[0], 4)
            arm["rounds_per_sec"] = round(queries / max(
                arm["wall_s"], 1e-9), 2)
            arm["q01_rows"] = _scale_rows(c, "d", "scale_q01_out")
            arm["fan_rows"] = [_scale_rows(c, "d", f"fan_{i}")
                               for i in range(len(cuts))]
            c.close()
            return arm
        finally:
            for d in ctls:
                d.shutdown()

    opt = run_arm("opt")
    greedy = run_arm("greedy", fusion_mapper="greedy")
    off = run_arm("off", plan_fusion=False)

    rows_equal = bool(
        opt["q01_rows"] == greedy["q01_rows"] == off["q01_rows"]
        and opt["fan_rows"] == greedy["fan_rows"] == off["fan_rows"])
    one_program = bool(
        opt["q01_fold_keys"] == 1 and opt["q01_merge_keys"] == 1
        and opt["q01_other_keys"] == 0
        and opt["q01_subplans"] == daemons
        and opt["q01_scatter_queries"] == 1)
    fan_one_subplan = bool(opt["fan_scatter_queries"] == 1
                           and opt["fan_subplans"] == daemons)
    rollback_clean = bool(
        greedy["q01_merge_keys"] == 0 and off["q01_merge_keys"] == 0)

    def strip(arm):
        return {k: v for k, v in arm.items()
                if k not in ("q01_rows", "fan_rows")}

    out: Dict[str, Any] = {
        "rows": rows, "daemons": daemons, "queries": queries,
        "optimal": strip(opt), "greedy": strip(greedy),
        "off": strip(off),
        "byte_equal": rows_equal,
        "one_program_per_shard_plus_merge": one_program,
        "fan_one_subplan_per_shard": fan_one_subplan,
        "rollback_no_region_keys": rollback_clean,
        "gates_ok": bool(rows_equal and one_program
                         and fan_one_subplan and rollback_clean),
    }
    if opt["round_p50_s"] > 0:
        # p50 of per-round latency, not total wall: one straggler
        # round (GC, a page-cache miss) would otherwise decide a
        # paired A/B whose honest signal is the typical round
        out["plan_fusion_distributed_speedup"] = round(
            off["round_p50_s"] / opt["round_p50_s"], 3)
        out["speedup_vs_greedy"] = round(
            greedy["round_p50_s"] / opt["round_p50_s"], 3)
    return out


def run_rebalance_bench(rows: int = 400_000, daemons: int = 4,
                        clients: int = 4, measure_s: float = 6.0,
                        settle_s: float = 4.0) -> Dict[str, Any]:
    """Self-rebalancing paired A/B (``--rebalance``): a
    ``daemons``-strong pool serves an 80/20 hot/cold routed-read mix
    from ``clients`` concurrent threads; mid-run a fresh daemon
    registers (``RESHARD op=add_worker``). The **on** arm lets the
    rebalancer run its forced campaign — slot ownership moves onto
    the new member under live traffic — while the **frozen** arm
    leaves it slot-less. The headline is the RECOVERED throughput
    ratio (``serve_rebalance_recovery_x``): the recovery window opens
    ``settle_s`` after the campaign returns, so it measures the
    steady state the pool recovers TO, not the one-time transient of
    the move itself (the moved slot's first scans re-stage cold
    pages; that cost is the campaign's, not the recovered level's).
    The ratio is gated on the
    flagship exactness story: ZERO failed client requests in either
    arm (in-flight old-epoch frames absorb typed ``PlacementStale``/
    ``ShardUnavailable`` retries inside the client), and the
    post-campaign scan-back must be row- and checksum-exact against
    the ingested tables in BOTH arms.

    Daemons are real subprocesses (parallel scans need separate
    GILs); same single-machine caveat class as ``--scale``."""
    import tempfile
    import threading

    import numpy as np

    from netsdb_tpu.serve.client import RemoteClient

    hot = scaleout_table(rows, seed=1)
    cold = scaleout_table(max(rows // 10, 64), seed=2)

    def checksum(t) -> int:
        return int(np.asarray(t["l_price"], dtype=np.int64).sum())

    want = {"hot": (hot.num_rows, checksum(hot)),
            "cold": (cold.num_rows, checksum(cold))}

    def spawn(port: int, on: bool,
              workers: Optional[List[str]] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        argv = [_python(), "-m", "netsdb_tpu", "serve",
                "--port", str(port),
                "--root", tempfile.mkdtemp(prefix=f"rebal_{port}_"),
                "--device-cache-mb", "0"]
        if on:
            argv.append("--rebalance")
        if workers:
            argv += ["--workers", ",".join(workers)]
        return subprocess.Popen(
            argv, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))

    def run_arm(on: bool) -> Dict[str, Any]:
        ports = [_free_port() for _ in range(daemons + 1)]
        worker_addrs = [f"127.0.0.1:{p}" for p in ports[1:daemons]]
        procs = [spawn(p, on) for p in ports[1:daemons]]
        procs.insert(0, spawn(ports[0], on,
                              workers=worker_addrs or None))
        leader_addr = f"127.0.0.1:{ports[0]}"
        out: Dict[str, Any] = {"rebalance": on}
        try:
            for p in ports[:daemons]:
                _wait_port("127.0.0.1", p)
            c = RemoteClient(leader_addr)
            c.create_database("d")
            c.create_set("d", "hot", type_name="table",
                         placement="range")
            c.create_set("d", "cold", type_name="table",
                         placement="range")
            c.send_table("d", "hot", hot)
            c.send_table("d", "cold", cold)
            c.get_table_streamed("d", "hot")  # warm the scan path

            stop = threading.Event()
            counts = [0] * clients
            failures: List[str] = []
            retries = [0] * clients

            def load(i: int) -> None:
                lc = RemoteClient(leader_addr)
                n = 0
                try:
                    while not stop.is_set():
                        name = "hot" if n % 5 else "cold"
                        try:
                            t = lc.get_table_streamed("d", name)
                            if t.num_rows != want[name][0]:
                                failures.append(
                                    f"{name}: {t.num_rows} rows")
                        except Exception as e:  # noqa: BLE001 — the
                            # gate: NOTHING typed-retryable may
                            # escape the client during the campaign
                            failures.append(f"{name}: {e!r}")
                        n += 1
                        counts[i] = n
                finally:
                    retries[i] = lc.total_retries
                    lc.close()

            threads = [threading.Thread(target=load, args=(i,),
                                        daemon=True)
                       for i in range(clients)]
            for t in threads:
                t.start()
            time.sleep(measure_s)
            baseline = sum(counts)
            out["baseline_qps"] = round(baseline / measure_s, 2)

            # the 5th daemon joins mid-run; on the on arm the forced
            # campaign moves slots under this very traffic
            w5 = spawn(ports[daemons], on)
            procs.append(w5)
            _wait_port("127.0.0.1", ports[daemons])
            t0 = time.perf_counter()
            reply = c.add_worker(f"127.0.0.1:{ports[daemons]}")
            out["campaign_s"] = round(time.perf_counter() - t0, 3)
            out["moves"] = [
                {k: m[k] for k in ("db", "set", "slot", "src", "dst",
                                   "ok") if k in m}
                for m in (reply.get("moves") or [])]
            # settle: let the moved slot's cold first scans drain so
            # the recovery window measures the steady state (both
            # arms wait, keeping the within-run warming symmetric)
            time.sleep(settle_s)
            at_join = sum(counts)
            time.sleep(measure_s)
            recovered = sum(counts) - at_join
            stop.set()
            for t in threads:
                t.join(timeout=30)
            out["recovery_qps"] = round(recovered / measure_s, 2)
            out["failed_requests"] = len(failures)
            out["failures"] = failures[:8]
            out["retries_absorbed"] = sum(retries)

            # exactness gates: the campaign must not lose or double
            # a single row
            totals = {}
            for name in ("hot", "cold"):
                t = c.get_table_streamed("d", name)
                totals[name] = (t.num_rows, checksum(t))
            out["totals"] = {k: list(v) for k, v in totals.items()}
            out["totals_exact"] = totals == want
            view = c.placement_view()
            out["placement_epoch"] = (view.get("status")
                                      or {}).get("epoch")
            out["member_slots"] = {m["addr"]: m["slots"]
                                   for m in view.get("members") or []}
            c.close()
        finally:
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        return out

    frozen = run_arm(False)
    live = run_arm(True)
    out: Dict[str, Any] = {
        "rows": rows, "daemons": daemons, "clients": clients,
        "measure_s": measure_s, "settle_s": settle_s,
        "frozen": frozen, "on": live,
        "moved_slots": sum(1 for m in live.get("moves") or []
                           if m.get("ok")),
        "zero_failed_requests": frozen["failed_requests"] == 0
        and live["failed_requests"] == 0,
        "totals_exact": frozen["totals_exact"]
        and live["totals_exact"],
        "byte_equal": frozen["totals"] == live["totals"],
    }
    out["serve_rebalance_recovery_x"] = round(
        live["recovery_qps"] / max(frozen["recovery_qps"], 1e-9), 2)
    return out


def run_sessions_bench(sessions: int = 8, steps: int = 32,
                       hidden: int = 64, workers: int = 2,
                       kind: str = "lstm") -> Dict[str, Any]:
    """Stateful interactive serving (``--sessions``): ``sessions``
    concurrent decode loops over one model on a sharded pool (a
    leader routing sticky to ``workers`` session-owning shards), each
    driving ``steps`` GENERATE rounds from its own client thread.

    The headline is aggregate warm decode throughput
    (``serve_sessions_steps_per_sec``), but the number only records
    when the structural gates hold — a fast-but-wrong run must never
    snapshot:

    * **one compiled step program** for the whole timed phase: the
      bucket-rows padding ladder maps every coalesced batch size to
      one (kind, hidden, bucket) program, so the decode trace count
      is PINNED across the run (delta 0 after warmup);
    * **zero arena reads** on the warm path: session state stays
      devcache-resident between steps, never revived from the host
      spill arena;
    * **byte-equality**: every session's full output stream equals a
      solo unbatched replay of the same inputs — coalescing must be
      invisible to results.

    Daemons are in-process (the trace/arena gates read the
    process-global decode stats); on a CPU container the wall number
    measures GIL-shared host stepping, so treat the throughput as a
    lower bound and the gates as the point.
    """
    import tempfile
    import threading

    import numpy as np

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.models import decode as decode_mod
    from netsdb_tpu.models.decode import deploy_decode_model
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController

    root = tempfile.mkdtemp(prefix="sessions_bench_")
    daemons: List[ServeController] = []
    out: Dict[str, Any] = {
        "sessions": sessions, "steps": steps, "hidden": hidden,
        "workers": workers, "kind": kind,
    }
    try:
        pool = []
        for i in range(workers):
            w = ServeController(
                Configuration(root_dir=os.path.join(root, f"w{i}")),
                port=0)
            w.start()
            daemons.append(w)
            pool.append(w)
        leader = ServeController(
            Configuration(root_dir=os.path.join(root, "leader")),
            port=0, workers=[w.advertise_addr for w in pool])
        leader.start()
        daemons.append(leader)

        deploy = RemoteClient(leader.advertise_addr)
        deploy_decode_model(deploy, "m", kind=kind, hidden=hidden,
                            seed=7)

        def x_row(i: int, s: int) -> np.ndarray:
            rng = np.random.default_rng(7000 + 1000 * i + s)
            return rng.standard_normal(hidden).astype(np.float32)

        clients = [RemoteClient(leader.advertise_addr)
                   for _ in range(sessions)]
        handles = [clients[i].open_session("m", kind=kind)
                   for i in range(sessions)]

        outputs: Dict[int, List[np.ndarray]] = {
            i: [] for i in range(sessions)}
        errors: List[str] = []
        barrier = threading.Barrier(sessions)

        def drive(i: int) -> None:
            try:
                barrier.wait()
                for s in range(steps):
                    outputs[i].append(np.asarray(
                        handles[i].generate(x_row(i, s),
                                            deadline_s=120.0)))
            except Exception as e:  # noqa: BLE001 — gate below
                errors.append(f"session {i}: {e!r}")

        # warmup OUTSIDE the timed window: first steps compile the
        # padded program and install per-session state
        for i in range(sessions):
            outputs[i].append(np.asarray(
                handles[i].generate(x_row(i, -1), deadline_s=120.0)))
            outputs[i].clear()

        def arena_reads() -> int:
            return sum(d.sessions.arena.stats()["reads"]
                       for d in daemons)

        traces0 = decode_mod.decode_stats()["traces"]
        reads0 = arena_reads()
        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(sessions)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

        stats = decode_mod.decode_stats()
        out["errors"] = errors
        out["wall_s"] = round(wall, 3)
        out["decode"] = dict(stats)
        out["traces_delta"] = stats["traces"] - traces0
        out["arena_reads_delta"] = arena_reads() - reads0
        out["batch_occupancy_avg"] = round(
            stats["steps"] / stats["batches"], 2) \
            if stats.get("batches") else None

        # byte-equality: every session vs a solo unbatched replay on
        # a fresh runtime over the same library (same weights)
        byte_equal = not errors
        rt = decode_mod.DecodeRuntime(leader.library)
        rt.register_model("m", kind)
        for i in range(sessions):
            st = rt.init_state("m")
            for s in range(-1, steps):
                new, ys = rt.step_batch(
                    "m", [st], [x_row(i, s if s >= 0 else -1)])
                st = new[0]
                if s >= 0 and not np.array_equal(
                        np.asarray(ys[0]), outputs[i][s]):
                    byte_equal = False
        out["byte_equal"] = byte_equal
        out["one_program"] = out["traces_delta"] == 0
        out["zero_warm_arena_reads"] = out["arena_reads_delta"] == 0
        if not errors and wall > 0:
            out["serve_sessions_steps_per_sec"] = round(
                sessions * steps / wall, 1)
        for h in handles:
            h.close()
        for c in clients:
            c.close()
        deploy.close()
    finally:
        for d in daemons:
            d.shutdown()
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="serve_bench")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--address", default="127.0.0.1:8108")
    ap.add_argument("--client-id", type=int, default=0)
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=BATCH)
    # None = per-mode default (2 for the FF bench, 8 for --scheduler);
    # an explicit value — however small — is always respected
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--stream", action="store_true",
                    help="transfer-path comparison: single-frame vs "
                         "streamed scan / chunked tensor")
    ap.add_argument("--data-plane", action="store_true",
                    help="v3 data-plane numbers: single-frame vs "
                         "streamed pipelined ingest MB/s, scan MB/s, "
                         "zero-copy tensor push/pull, hedged-read p99")
    ap.add_argument("--device-cache", action="store_true",
                    help="cold vs warm EXECUTE latency over a "
                         "device-cache-resident paged set, plus "
                         "hit/miss counters")
    ap.add_argument("--partial-cache", action="store_true",
                    help="paired A/B: warm re-query after a 1%% "
                         "append, partial dirty-range invalidation "
                         "vs whole-run invalidation")
    ap.add_argument("--scheduler", action="store_true",
                    help="paired A/B: N concurrent identical cold "
                         "EXECUTEs with the query scheduler on vs "
                         "off — executions run, devcache installs, "
                         "coalesce hits, client p50/p99")
    ap.add_argument("--scale", action="store_true",
                    help="horizontal scale-out: paired 1 vs N-daemon "
                         "arm — aggregate routed-ingest MB/s, cold "
                         "scatter-gather q01 QPS, byte-equality incl. "
                         "a distributed-shuffle join")
    ap.add_argument("--serving", action="store_true",
                    help="end-to-end model serving over the sharded "
                         "pool: deploy + batched scoring frames via "
                         "ModelServing, with byte-equality / one-"
                         "program-per-shard / <=1-N structural gates")
    ap.add_argument("--failover", action="store_true",
                    help="failover-under-traffic: client-observed "
                         "p99 blip across a leader kill on an armed "
                         "HA pair, exact-totals gated")
    ap.add_argument("--fusion-distributed", action="store_true",
                    help="distributed fusion paired A/B: 4-daemon "
                         "scatter q01 + 3-sink fan under the optimal "
                         "mapper vs greedy vs plan_fusion=off, with "
                         "one-program-per-shard + byte-equality gates")
    ap.add_argument("--sessions", action="store_true",
                    help="stateful serving: N concurrent decode "
                         "sessions over a sharded pool — aggregate "
                         "steps/s gated on one-compiled-program, "
                         "zero warm arena reads, byte-equality vs "
                         "solo replay")
    ap.add_argument("--rebalance", action="store_true",
                    help="self-rebalancing paired A/B: 80/20 skewed "
                         "mix over a 4-daemon pool, a 5th daemon "
                         "registers mid-run — rebalance on vs "
                         "frozen, recovery throughput + exactness "
                         "gates")
    ap.add_argument("--daemons", type=int, default=4,
                    help="pool size for --scale (leader + N-1 shards)")
    ap.add_argument("--rows", type=int, default=6_000_000,
                    help="lineitem rows for --scale")
    ap.add_argument("--table-mb", type=int, default=64)
    args = ap.parse_args(argv)
    if args.worker:
        out = run_client_worker(args.address, args.client_id, args.jobs,
                                args.batch)
    elif args.serving:
        out = run_serving_bench(daemons=args.daemons)
    elif args.failover:
        out = run_failover_bench()
    elif args.fusion_distributed:
        out = run_fusion_distributed_bench(daemons=args.daemons)
    elif args.sessions:
        out = run_sessions_bench()
    elif args.rebalance:
        out = run_rebalance_bench(daemons=args.daemons)
    elif args.scale:
        out = run_scaleout_bench(rows=args.rows, daemons=args.daemons)
    elif args.scheduler:
        out = run_scheduler_bench(
            clients=args.clients if args.clients is not None else 8)
    elif args.partial_cache:
        out = run_partial_cache_bench()
    elif args.device_cache:
        out = run_device_cache_bench()
    elif args.data_plane:
        out = run_data_plane_bench(table_mb=args.table_mb)
    elif args.stream:
        out = run_stream_bench()
    else:
        out = run_serve_bench(clients=args.clients
                              if args.clients is not None else 2,
                              jobs_per_client=args.jobs, batch=args.batch,
                              port=args.port)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
