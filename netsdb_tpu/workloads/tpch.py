"""TPC-H workload — the reference's relational benchmark family.

The reference implements queries 01/02/03/04/06/12/13/14/17/22 as
Computation DAGs over C++ table types (``src/tpch/source/Query*/``,
table loaders ``Customer.cc``…``tpchDataLoader.cc``). Here each query
is the same DAG shape (Scan → Filter → Join → Aggregate → Write) over
host record sets through :mod:`netsdb_tpu.plan` — the host-relational
execution path of the framework. Tensors play no role: this exists for
capability parity and exercises the equi-join/group-by machinery.

Dates are ISO strings (lexicographically ordered, so range predicates
are string compares — same trick the reference's drivers use with
encoded ints). ``generate()`` makes a seeded micro-instance of the 8
tables for tests/demos.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List

from netsdb_tpu.plan.computations import (
    Aggregate, Apply, Filter, Join, ScanSet, WriteSet,
)

TABLES = ("region", "nation", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem")

_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_MODES = ["MAIL", "SHIP", "AIR", "TRUCK", "RAIL", "FOB", "REG AIR"]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_CONTAINERS = ["SM CASE", "MED BOX", "LG JAR", "WRAP PACK", "JUMBO PKG"]
_TYPES = ["PROMO BURNISHED", "STANDARD POLISHED", "ECONOMY ANODIZED",
          "PROMO PLATED", "MEDIUM BRUSHED"]
_FLAGS = [("R", "F"), ("A", "F"), ("N", "O")]


def _date(rng, y0=1992, y1=1998) -> str:
    return f"{rng.randint(y0, y1):04d}-{rng.randint(1, 12):02d}-{rng.randint(1, 28):02d}"


def generate(scale: int = 1, seed: int = 0) -> Dict[str, List[Dict[str, Any]]]:
    """Micro TPC-H instance: ~scale x (5 regions, 10 nations, 20 suppliers,
    50 customers, 40 parts, 80 partsupps, 150 orders, ~450 lineitems)."""
    rng = random.Random(seed)
    region = [{"r_regionkey": i, "r_name": n}
              for i, n in enumerate(["AFRICA", "AMERICA", "ASIA", "EUROPE",
                                     "MIDDLE EAST"])]
    nation = [{"n_nationkey": i, "n_name": f"NATION{i}",
               "n_regionkey": i % 5} for i in range(10)]
    supplier = [{"s_suppkey": i, "s_name": f"Supplier{i}",
                 "s_nationkey": rng.randrange(10),
                 "s_acctbal": round(rng.uniform(-999, 9999), 2)}
                for i in range(20 * scale)]
    customer = [{"c_custkey": i, "c_name": f"Customer{i}",
                 "c_nationkey": rng.randrange(10),
                 "c_mktsegment": rng.choice(_SEGMENTS),
                 "c_acctbal": round(rng.uniform(-999, 9999), 2),
                 "c_phone": f"{rng.randint(10, 34)}-{rng.randint(100, 999)}-{rng.randint(1000, 9999)}"}
                for i in range(50 * scale)]
    part = [{"p_partkey": i, "p_name": f"part {i}",
             "p_brand": rng.choice(_BRANDS), "p_type": rng.choice(_TYPES),
             "p_size": rng.randint(1, 50),
             "p_container": rng.choice(_CONTAINERS),
             "p_retailprice": round(rng.uniform(900, 2000), 2)}
            for i in range(40 * scale)]
    partsupp = [{"ps_partkey": rng.randrange(40 * scale),
                 "ps_suppkey": rng.randrange(20 * scale),
                 "ps_supplycost": round(rng.uniform(1, 1000), 2),
                 "ps_availqty": rng.randint(1, 9999)}
                for _ in range(80 * scale)]
    comment_words = ["express", "special", "pending", "requests", "deposits",
                     "accounts", "packages", "final"]
    orders, lineitem = [], []
    for okey in range(150 * scale):
        ckey = rng.randrange(50 * scale)
        odate = _date(rng)
        orders.append({"o_orderkey": okey, "o_custkey": ckey,
                       "o_orderdate": odate,
                       "o_orderpriority": rng.choice(_PRIORITIES),
                       "o_shippriority": 0,
                       "o_totalprice": 0.0,
                       "o_comment": " ".join(rng.choices(comment_words, k=4))})
        for _ in range(rng.randint(1, 5)):
            rf, ls = rng.choice(_FLAGS)
            ship = _date(rng)
            commit = _date(rng)
            receipt = _date(rng)
            lineitem.append({
                "l_orderkey": okey,
                "l_partkey": rng.randrange(40 * scale),
                "l_suppkey": rng.randrange(20 * scale),
                "l_quantity": rng.randint(1, 50),
                "l_extendedprice": round(rng.uniform(1000, 100000), 2),
                "l_discount": round(rng.uniform(0.0, 0.1), 2),
                "l_tax": round(rng.uniform(0.0, 0.08), 2),
                "l_returnflag": rf, "l_linestatus": ls,
                "l_shipdate": ship, "l_commitdate": commit,
                "l_receiptdate": receipt,
                "l_shipmode": rng.choice(_MODES),
            })
    return {"region": region, "nation": nation, "supplier": supplier,
            "customer": customer, "part": part, "partsupp": partsupp,
            "orders": orders, "lineitem": lineitem}


# dbgen column layouts (official TPC-H spec order) — the reference's
# ``tpchDataLoader.cc`` parses the same pipe-separated .tbl files.
# (name, type) with type in {int, float, str}.
_TBL_SCHEMAS: Dict[str, List[tuple]] = {
    "region": [("r_regionkey", int), ("r_name", str), ("r_comment", str)],
    "nation": [("n_nationkey", int), ("n_name", str),
               ("n_regionkey", int), ("n_comment", str)],
    "supplier": [("s_suppkey", int), ("s_name", str), ("s_address", str),
                 ("s_nationkey", int), ("s_phone", str),
                 ("s_acctbal", float), ("s_comment", str)],
    "customer": [("c_custkey", int), ("c_name", str), ("c_address", str),
                 ("c_nationkey", int), ("c_phone", str),
                 ("c_acctbal", float), ("c_mktsegment", str),
                 ("c_comment", str)],
    "part": [("p_partkey", int), ("p_name", str), ("p_mfgr", str),
             ("p_brand", str), ("p_type", str), ("p_size", int),
             ("p_container", str), ("p_retailprice", float),
             ("p_comment", str)],
    "partsupp": [("ps_partkey", int), ("ps_suppkey", int),
                 ("ps_availqty", int), ("ps_supplycost", float),
                 ("ps_comment", str)],
    "orders": [("o_orderkey", int), ("o_custkey", int),
               ("o_orderstatus", str), ("o_totalprice", float),
               ("o_orderdate", str), ("o_orderpriority", str),
               ("o_clerk", str), ("o_shippriority", int),
               ("o_comment", str)],
    "lineitem": [("l_orderkey", int), ("l_partkey", int),
                 ("l_suppkey", int), ("l_linenumber", int),
                 ("l_quantity", float), ("l_extendedprice", float),
                 ("l_discount", float), ("l_tax", float),
                 ("l_returnflag", str), ("l_linestatus", str),
                 ("l_shipdate", str), ("l_commitdate", str),
                 ("l_receiptdate", str), ("l_shipinstruct", str),
                 ("l_shipmode", str), ("l_comment", str)],
}


def parse_tbl(path: str, table: str) -> List[Dict[str, Any]]:
    """Parse one dbgen ``.tbl`` file (pipe-separated, trailing pipe) into
    row dicts — ``tpchDataLoader.cc``'s per-table parse loop."""
    schema = _TBL_SCHEMAS.get(table)
    if schema is None:
        raise ValueError(f"unknown TPC-H table {table!r}; "
                         f"one of {sorted(_TBL_SCHEMAS)}")
    rows = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.rstrip("\r\n")
            if not line:
                continue
            fields = line.split("|")
            if fields and fields[-1] == "":
                fields.pop()  # dbgen's trailing delimiter
            if len(fields) != len(schema):
                raise ValueError(
                    f"{path}:{lineno}: expected {len(schema)} fields for "
                    f"{table}, got {len(fields)}")
            rows.append({name: typ(val)
                         for (name, typ), val in zip(schema, fields)})
    return rows


def parse_tbl_columnar(path: str, table: str):
    """Columnar parse → {column: numpy array}. Uses the native C++
    parser (``native/tblparse.cpp``) when available — the reference's
    C++ loader role, an order of magnitude faster than row dicts — and
    falls back to transposing the Python row parser."""
    schema = _TBL_SCHEMAS.get(table)
    if schema is None:
        raise ValueError(f"unknown TPC-H table {table!r}; "
                         f"one of {sorted(_TBL_SCHEMAS)}")
    from netsdb_tpu.native import tblparse

    cols = tblparse.parse_columnar(path, schema)
    if cols is not None:
        return cols
    import numpy as np

    rows = parse_tbl(path, table)
    return {name: np.array([r[name] for r in rows],
                           dtype=(np.int64 if typ is int else
                                  np.float64 if typ is float else object))
            for name, typ in schema}


def load_tbl_dir(client, directory: str, db: str = "tpch",
                 tables=None) -> Dict[str, int]:
    """Load a dbgen output directory (``<table>.tbl`` files) — the
    reference's data-loading workflow (``README.md:216-256``:
    dbgen then tpchDataLoader). Returns {table: row count}."""
    import os

    counts = {}
    client.create_database(db)
    for table in (tables or sorted(_TBL_SCHEMAS)):
        path = os.path.join(directory, f"{table}.tbl")
        if not os.path.exists(path):
            continue
        rows = parse_tbl(path, table)
        if not client.set_exists(db, table):
            client.create_set(db, table, type_name="object")
        client.clear_set(db, table)
        client.send_data(db, table, rows)
        counts[table] = len(rows)
    return counts


def load_tbl_dir_columnar(client, directory: str, db: str = "tpch",
                          tables=None) -> Dict[str, int]:
    """Columnar dbgen ingestion: ``<table>.tbl`` → one ColumnTable per
    set (native parser fast path), the input format of the device
    relational engine (:mod:`netsdb_tpu.relational`). Returns
    {table: row count}."""
    import os

    from netsdb_tpu.relational.table import ColumnTable

    date_cols = {"o_orderdate", "l_shipdate", "l_commitdate",
                 "l_receiptdate"}
    counts = {}
    client.create_database(db)
    for table in (tables or sorted(_TBL_SCHEMAS)):
        path = os.path.join(directory, f"{table}.tbl")
        if not os.path.exists(path):
            continue
        cols = parse_tbl_columnar(path, table)
        ct = ColumnTable.from_columns(cols, date_cols=date_cols)
        set_name = f"{table}_columnar"
        if not client.set_exists(db, set_name):
            client.create_set(db, set_name, type_name="columnar")
        client.clear_set(db, set_name)
        client.send_data(db, set_name, [ct])
        counts[table] = ct.num_rows
    return counts


def load_tables(client, db: str = "tpch", tables=None, scale: int = 1,
                seed: int = 0) -> None:
    """``tpchDataLoader`` analogue."""
    tables = tables or generate(scale, seed)
    client.create_database(db)
    for name, rows in tables.items():
        if not client.set_exists(db, name):
            client.create_set(db, name, type_name="object")
        client.clear_set(db, name)
        client.send_data(db, name, rows)


def _dict_to_rows():
    return lambda d: sorted(d.items())


# ---------------------------------------------------------------- Q01
def q01(db: str = "tpch", delta_date: str = "1998-09-02") -> WriteSet:
    """Pricing summary report (ref ``src/tpch/source/Query01``): filter
    shipdate, group by (returnflag, linestatus), sum qty/price/disc
    price/charge + counts."""
    li = Filter(ScanSet(db, "lineitem"),
                lambda l: l["l_shipdate"] <= delta_date, label="shipdate<=d")

    def value(l):
        disc_price = l["l_extendedprice"] * (1 - l["l_discount"])
        return {"sum_qty": l["l_quantity"],
                "sum_base_price": l["l_extendedprice"],
                "sum_disc_price": disc_price,
                "sum_charge": disc_price * (1 + l["l_tax"]),
                "sum_disc": l["l_discount"], "count": 1}

    def combine(a, b):
        return {k: a[k] + b[k] for k in a}

    agg = Aggregate(li, key=lambda l: (l["l_returnflag"], l["l_linestatus"]),
                    value=value, combine=combine, label="Q01Agg")

    def finalize(d):
        out = []
        for k, v in sorted(d.items()):
            v = dict(v)
            v["avg_qty"] = v["sum_qty"] / v["count"]
            v["avg_price"] = v["sum_base_price"] / v["count"]
            v["avg_disc"] = v["sum_disc"] / v["count"]
            out.append((k, v))
        return out

    return WriteSet(Apply(agg, finalize, label="Q01Finalize"), db, "q01_out")


# ---------------------------------------------------------------- Q02
def q02(db: str = "tpch", size: int = 15, type_suffix: str = "BRUSHED",
        region: str = "EUROPE") -> WriteSet:
    """Minimum-cost supplier (ref ``Query02``): parts of a size/type in a
    region, suppliers achieving the min supplycost."""
    nr = Join(Filter(ScanSet(db, "region"), lambda r: r["r_name"] == region,
                     label="region"),
              ScanSet(db, "nation"),
              left_key=lambda r: r["r_regionkey"],
              right_key=lambda n: n["n_regionkey"],
              project=lambda r, n: n, label="nation⋈region")
    sup = Join(nr, ScanSet(db, "supplier"),
               left_key=lambda n: n["n_nationkey"],
               right_key=lambda s: s["s_nationkey"],
               project=lambda n, s: {**s, "n_name": n["n_name"]},
               label="supplier⋈nation")
    parts = Filter(ScanSet(db, "part"),
                   lambda p: p["p_size"] == size
                   and p["p_type"].endswith(type_suffix), label="part filter")
    ps = Join(parts, ScanSet(db, "partsupp"),
              left_key=lambda p: p["p_partkey"],
              right_key=lambda x: x["ps_partkey"],
              project=lambda p, x: {**x, "p_partkey": p["p_partkey"]},
              label="part⋈partsupp")
    full = Join(ps, sup, left_key=lambda x: x["ps_suppkey"],
                right_key=lambda s: s["s_suppkey"],
                project=lambda x, s: {"partkey": x["p_partkey"],
                                      "cost": x["ps_supplycost"],
                                      "s_name": s["s_name"],
                                      "n_name": s["n_name"]},
                label="⋈supplier")
    best = Aggregate(full, key=lambda r: r["partkey"], value=lambda r: r,
                     combine=lambda a, b: a if a["cost"] <= b["cost"] else b,
                     label="min cost per part")
    return WriteSet(Apply(best, _dict_to_rows(), label="rows"), db, "q02_out")


# ---------------------------------------------------------------- Q03
def q03(db: str = "tpch", segment: str = "BUILDING",
        date: str = "1995-03-15") -> WriteSet:
    """Shipping priority (ref ``Query03``): top unshipped orders by
    revenue."""
    cust = Filter(ScanSet(db, "customer"),
                  lambda c: c["c_mktsegment"] == segment, label="segment")
    orders = Filter(ScanSet(db, "orders"),
                    lambda o: o["o_orderdate"] < date, label="orderdate<d")
    co = Join(cust, orders, left_key=lambda c: c["c_custkey"],
              right_key=lambda o: o["o_custkey"],
              project=lambda c, o: o, label="cust⋈orders")
    li = Filter(ScanSet(db, "lineitem"), lambda l: l["l_shipdate"] > date,
                label="shipdate>d")
    col = Join(co, li, left_key=lambda o: o["o_orderkey"],
               right_key=lambda l: l["l_orderkey"],
               project=lambda o, l: {
                   "okey": o["o_orderkey"], "odate": o["o_orderdate"],
                   "rev": l["l_extendedprice"] * (1 - l["l_discount"])},
               label="⋈lineitem")
    agg = Aggregate(col, key=lambda r: (r["okey"], r["odate"]),
                    value=lambda r: r["rev"], combine=lambda a, b: a + b,
                    label="revenue per order")

    def top10(d):
        rows = [{"okey": k[0], "odate": k[1], "revenue": v}
                for k, v in d.items()]
        rows.sort(key=lambda r: (-r["revenue"], r["odate"]))
        return rows[:10]

    return WriteSet(Apply(agg, top10, label="top10"), db, "q03_out")


# ---------------------------------------------------------------- Q04
def q04(db: str = "tpch", d0: str = "1993-07-01",
        d1: str = "1993-10-01") -> WriteSet:
    """Order-priority checking (ref ``Query04``): orders in a quarter with
    at least one late lineitem, counted per priority."""
    late = Filter(ScanSet(db, "lineitem"),
                  lambda l: l["l_commitdate"] < l["l_receiptdate"],
                  label="late lineitems")
    late_keys = Aggregate(late, key=lambda l: l["l_orderkey"],
                          value=lambda l: 1, combine=lambda a, b: 1,
                          label="distinct orderkeys")
    orders = Filter(ScanSet(db, "orders"),
                    lambda o: d0 <= o["o_orderdate"] < d1, label="quarter")
    joined = Join(orders, Apply(late_keys, _dict_to_rows(), label="rows"),
                  left_key=lambda o: o["o_orderkey"],
                  right_key=lambda kv: kv[0],
                  project=lambda o, kv: o, label="semi-join")
    counts = Aggregate(joined, key=lambda o: o["o_orderpriority"],
                       value=lambda o: 1, combine=lambda a, b: a + b,
                       label="count per priority")
    return WriteSet(Apply(counts, _dict_to_rows(), label="rows"),
                    db, "q04_out")


# ---------------------------------------------------------------- Q06
def q06(db: str = "tpch", d0: str = "1994-01-01", d1: str = "1995-01-01",
        disc: float = 0.06, qty: int = 24) -> WriteSet:
    """Revenue-change forecast (ref ``Query06``): one filtered sum."""
    li = Filter(
        ScanSet(db, "lineitem"),
        lambda l: (d0 <= l["l_shipdate"] < d1
                   and disc - 0.011 <= l["l_discount"] <= disc + 0.011
                   and l["l_quantity"] < qty),
        label="Q06 filter")
    rev = Aggregate(li, key=lambda l: "revenue",
                    value=lambda l: l["l_extendedprice"] * l["l_discount"],
                    combine=lambda a, b: a + b, label="sum revenue")
    return WriteSet(Apply(rev, _dict_to_rows(), label="rows"), db, "q06_out")


# ---------------------------------------------------------------- Q12
def q12(db: str = "tpch", mode1: str = "MAIL", mode2: str = "SHIP",
        d0: str = "1994-01-01", d1: str = "1995-01-01") -> WriteSet:
    """Shipping modes & order priority (ref ``Query12``)."""
    li = Filter(
        ScanSet(db, "lineitem"),
        lambda l: (l["l_shipmode"] in (mode1, mode2)
                   and l["l_commitdate"] < l["l_receiptdate"]
                   and l["l_shipdate"] < l["l_commitdate"]
                   and d0 <= l["l_receiptdate"] < d1),
        label="Q12 filter")
    jo = Join(li, ScanSet(db, "orders"),
              left_key=lambda l: l["l_orderkey"],
              right_key=lambda o: o["o_orderkey"],
              project=lambda l, o: {"mode": l["l_shipmode"],
                                    "pri": o["o_orderpriority"]},
              label="⋈orders")

    def value(r):
        high = 1 if r["pri"] in ("1-URGENT", "2-HIGH") else 0
        return {"high": high, "low": 1 - high}

    agg = Aggregate(jo, key=lambda r: r["mode"], value=value,
                    combine=lambda a, b: {"high": a["high"] + b["high"],
                                          "low": a["low"] + b["low"]},
                    label="high/low per mode")
    return WriteSet(Apply(agg, _dict_to_rows(), label="rows"), db, "q12_out")


# ---------------------------------------------------------------- Q13
def q13(db: str = "tpch", word1: str = "special",
        word2: str = "requests") -> WriteSet:
    """Customer distribution (ref ``Query13``): histogram of per-customer
    order counts, customers with zero orders included; orders whose
    comment matches %word1%word2% are excluded."""
    import re as _re

    pat = _re.compile(f"{_re.escape(word1)}.*{_re.escape(word2)}")
    keep = Filter(ScanSet(db, "orders"),
                  lambda o: not pat.search(o.get("o_comment", "")),
                  label="comment not like %w1%w2%")
    per_cust = Aggregate(keep,
                         key=lambda o: o["o_custkey"], value=lambda o: 1,
                         combine=lambda a, b: a + b, label="orders per cust")
    custs = ScanSet(db, "customer")

    def left_outer(customers, counts):
        # customers with no orders land in the 0 bucket (left outer join)
        return [{"cust": c["c_custkey"],
                 "n": counts.get(c["c_custkey"], 0)} for c in customers]

    with_counts = Join(custs, per_cust, fn=left_outer, label="cust⟕counts")
    hist = Aggregate(with_counts, key=lambda r: r["n"], value=lambda r: 1,
                     combine=lambda a, b: a + b, label="histogram")
    return WriteSet(Apply(hist, _dict_to_rows(), label="rows"), db, "q13_out")


# ---------------------------------------------------------------- Q14
def q14(db: str = "tpch", d0: str = "1995-09-01",
        d1: str = "1995-10-01") -> WriteSet:
    """Promotion effect (ref ``Query14``): % of revenue from PROMO parts."""
    li = Filter(ScanSet(db, "lineitem"),
                lambda l: d0 <= l["l_shipdate"] < d1, label="month")
    jp = Join(li, ScanSet(db, "part"),
              left_key=lambda l: l["l_partkey"],
              right_key=lambda p: p["p_partkey"],
              project=lambda l, p: {
                  "rev": l["l_extendedprice"] * (1 - l["l_discount"]),
                  "promo": p["p_type"].startswith("PROMO")},
              label="⋈part")
    agg = Aggregate(jp, key=lambda r: "all",
                    value=lambda r: {"promo": r["rev"] if r["promo"] else 0.0,
                                     "total": r["rev"]},
                    combine=lambda a, b: {"promo": a["promo"] + b["promo"],
                                          "total": a["total"] + b["total"]},
                    label="promo/total")

    def ratio(d):
        v = d.get("all", {"promo": 0.0, "total": 0.0})
        pct = 100.0 * v["promo"] / v["total"] if v["total"] else 0.0
        return [("promo_revenue_pct", pct)]

    return WriteSet(Apply(agg, ratio, label="ratio"), db, "q14_out")


# ---------------------------------------------------------------- Q17
def q17(db: str = "tpch", brand: str = "Brand#23",
        container: str = "MED BOX") -> WriteSet:
    """Small-quantity-order revenue (ref ``Query17``): lineitems under
    20% of the part's average quantity."""
    parts = Filter(ScanSet(db, "part"),
                   lambda p: p["p_brand"] == brand
                   and p["p_container"] == container, label="brand+container")
    li_parts = Join(ScanSet(db, "lineitem"), parts,
                    left_key=lambda l: l["l_partkey"],
                    right_key=lambda p: p["p_partkey"],
                    project=lambda l, p: l, label="⋈part")
    avg_qty = Aggregate(li_parts, key=lambda l: l["l_partkey"],
                        value=lambda l: {"sum": l["l_quantity"], "n": 1},
                        combine=lambda a, b: {"sum": a["sum"] + b["sum"],
                                              "n": a["n"] + b["n"]},
                        label="avg qty per part")
    small = Join(li_parts, Apply(avg_qty, _dict_to_rows(), label="rows"),
                 left_key=lambda l: l["l_partkey"],
                 right_key=lambda kv: kv[0],
                 project=lambda l, kv: {
                     "price": l["l_extendedprice"],
                     "small": l["l_quantity"] < 0.2 * kv[1]["sum"] / kv[1]["n"]},
                 label="⋈avg")
    total = Aggregate(Filter(small, lambda r: r["small"], label="small only"),
                      key=lambda r: "avg_yearly",
                      value=lambda r: r["price"] / 7.0,
                      combine=lambda a, b: a + b, label="sum/7")
    return WriteSet(Apply(total, _dict_to_rows(), label="rows"),
                    db, "q17_out")


# ---------------------------------------------------------------- Q22
def q22(db: str = "tpch", prefixes=("13", "31", "23", "29", "30", "18", "17")
        ) -> WriteSet:
    """Global sales opportunity (ref ``Query22``): well-funded customers
    with no orders, grouped by phone prefix."""
    custs = Filter(ScanSet(db, "customer"),
                   lambda c: c["c_phone"][:2] in prefixes, label="prefix")
    # avg positive acctbal among the prefix customers
    avg = Aggregate(custs, key=lambda c: "avg",
                    value=lambda c: ({"sum": c["c_acctbal"], "n": 1}
                                     if c["c_acctbal"] > 0
                                     else {"sum": 0.0, "n": 0}),
                    combine=lambda a, b: {"sum": a["sum"] + b["sum"],
                                          "n": a["n"] + b["n"]},
                    label="avg positive acctbal")
    rich = Join(custs, Apply(avg, _dict_to_rows(), label="rows"),
                left_key=lambda c: "avg", right_key=lambda kv: kv[0],
                project=lambda c, kv: (c, kv[1]["sum"] / max(kv[1]["n"], 1)),
                label="⋈avg")
    rich = Filter(rich, lambda cv: cv[0]["c_acctbal"] > cv[1],
                  label="acctbal>avg")
    ordered_custs = Aggregate(ScanSet(db, "orders"),
                              key=lambda o: o["o_custkey"], value=lambda o: 1,
                              combine=lambda a, b: 1, label="custs w/ orders")

    def anti_join(rich_rows, ordered):
        have = set(ordered.keys())
        return [c for c, _ in rich_rows if c["c_custkey"] not in have]

    no_orders = Join(rich, ordered_custs, fn=anti_join, label="anti-join")
    byprefix = Aggregate(no_orders, key=lambda c: c["c_phone"][:2],
                         value=lambda c: {"n": 1, "bal": c["c_acctbal"]},
                         combine=lambda a, b: {"n": a["n"] + b["n"],
                                               "bal": a["bal"] + b["bal"]},
                         label="per prefix")
    return WriteSet(Apply(byprefix, _dict_to_rows(), label="rows"),
                    db, "q22_out")


QUERIES: Dict[str, Callable[..., WriteSet]] = {
    "q01": q01, "q02": q02, "q03": q03, "q04": q04, "q06": q06,
    "q12": q12, "q13": q13, "q14": q14, "q17": q17, "q22": q22,
}


def run_query(client, name: str, db: str = "tpch", **kwargs):
    """Execute one query, return its result rows."""
    sink = QUERIES[name](db=db, **kwargs)
    res = client.execute_computations(sink, job_name=f"tpch-{name}")
    return next(iter(res.values()))
