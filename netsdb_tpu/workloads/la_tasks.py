"""Headline LA benchmark tasks — the reference's only published
end-to-end numbers (reference ``selfLearning/documentation.md:5-10``;
see BASELINE.md rows 1-3):

    Gram matrix        X: 200000x1000 (1000x1000 blocks), G = Xt X
                       41.27 s plain -> 22.78 s with self-learning
    Linear regression  same X, ridge normal equations
                       83.45 s -> 43.91 s with self-learning
    Matrix multiply    C = X . W (W: 1000x1000)
                       42.21 s -> 11.41 s best self-learning round

Each task is expressed as a PDML program (the reference drives these
through its LA DSL — ``src/linearAlgebraDSL``, driver
``TestLA21_Instance.cc``) and evaluated over the op layer with inputs
pre-bound in the interpreter environment as device-resident
``BlockedTensor``s — the "data already loaded into sets" starting point
the reference's timings use (its numbers cover the query job, not
dbgen/ingest).

TPU-first design note: the reference executes every DSL statement as a
separate distributed job with materialized intermediates. Here the WHOLE
program is traced into one jaxpr (``compile_pdml``) so XLA fuses across
statements and schedules one program onto the MXU — the per-statement
job boundary, which exists only because the reference's engine needs a
shuffle between stages, disappears.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockMeta, BlockedTensor
from netsdb_tpu.dsl.interp import LAInterpreter
from netsdb_tpu.dsl.parser import parse_program

# Reference numbers (seconds) from selfLearning/documentation.md:5-10:
# plain = no self-learning; best = best self-learning run.
REFERENCE_SECONDS = {
    "gram": {"plain": 41.27, "best": 22.78},
    "linreg": {"plain": 83.45, "best": 43.91},
    "matmul": {"plain": 42.21, "best": 11.41},
}

# The programs. LAMI = lambda*I is pre-bound (PDML has no scalar
# literals in expressions; the reference's sample drivers likewise bind
# scalars by loading pre-scaled matrices).
PROGRAMS = {
    "gram": "G = X '* X",
    "linreg": "w = (X '* X + LAMI) ^-1 %*% (X '* y)",
    "matmul": "C = X %*% W",
}

TASKS = tuple(PROGRAMS)


def compile_pdml(text: str) -> Callable[[Dict[str, BlockedTensor]],
                                        Dict[str, BlockedTensor]]:
    """Trace a whole PDML program into one jit-compiled function
    ``env -> {target: value for each statement}``.

    This is the DSL's compile path: statements become one fused XLA
    program instead of the reference's one-distributed-job-per-statement
    execution (``LAEvaluateFunctions.cc`` calling executeComputations
    per AST node).
    """
    stmts = parse_program(text)

    def run(env: Dict[str, BlockedTensor]) -> Dict[str, BlockedTensor]:
        interp = LAInterpreter()
        interp.env.update(env)
        for stmt in stmts:
            interp.execute(stmt)
        return {stmt.target: interp.env[stmt.target] for stmt in stmts}

    return jax.jit(run)


def make_inputs(task: str, rows: int, cols: int, block: int,
                lam: float = 1.0, dtype=jnp.float32, seed: int = 0,
                ) -> Dict[str, BlockedTensor]:
    """Device-side random inputs at the task's shapes (no host round
    trip — the generator runs on the chip)."""
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)

    def randn(key, shape, bshape):
        meta = BlockMeta(shape, bshape)
        data = jax.random.normal(key, meta.padded_shape, dtype)
        if meta.is_padded:  # honor the zero-margin invariant
            mask_r = jnp.arange(meta.padded_shape[0]) < shape[0]
            mask_c = jnp.arange(meta.padded_shape[1]) < shape[1]
            data = data * (mask_r[:, None] & mask_c[None, :]).astype(dtype)
        return BlockedTensor(data, meta)

    env = {"X": randn(keys[0], (rows, cols), (block, block))}
    if task == "linreg":
        env["y"] = randn(keys[1], (rows, 1), (block, 1))
        eye = jnp.eye(env["X"].meta.padded_shape[1], dtype=dtype)
        n = cols
        eye = eye * (jnp.arange(eye.shape[0]) < n).astype(dtype)[:, None]
        env["LAMI"] = BlockedTensor(lam * eye,
                                    BlockMeta((cols, cols), (block, block)))
    elif task == "matmul":
        env["W"] = randn(keys[2], (cols, cols), (block, block))
    elif task != "gram":
        raise ValueError(f"unknown task {task!r}; have {TASKS}")
    return env


def run_task(task: str, rows: int = 200000, cols: int = 1000,
             block: int = 1000, iters: int = 5, lam: float = 1.0,
             dtype=jnp.float32, seed: int = 0) -> Dict[str, object]:
    """Time one headline task at the reference's scale. Returns timings
    plus the reference baselines and the speedup vs. the reference's
    BEST (self-learned) number."""
    env = make_inputs(task, rows, cols, block, lam, dtype, seed)
    for t in env.values():
        jax.block_until_ready(t.data)
    fn = compile_pdml(PROGRAMS[task])

    def sync(out):
        for v in out.values():
            jax.block_until_ready(v.data)
        # force a real device round-trip (block_until_ready alone is not
        # a reliable barrier over the axon tunnel)
        return float(jnp.sum(next(iter(out.values())).data))

    t0 = time.perf_counter()
    sync(fn(env))
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(env))
        times.append(time.perf_counter() - t0)
    exec_s = sorted(times)[len(times) // 2]

    # pure device time via the scan-slope protocol (wall includes the
    # controller round-trip, which dominates at these speeds)
    from functools import partial

    from netsdb_tpu.utils.timing import device_seconds

    @partial(jax.jit, static_argnums=1)
    def loop(e, n):
        def step(carry, _):
            e2 = dict(e)
            e2["X"] = e["X"].with_data(e["X"].data + carry)
            out = fn(e2)
            first = next(iter(out.values())).data
            return (jnp.sum(first) * 1e-20).astype(e["X"].data.dtype), None
        c, _ = jax.lax.scan(step, jnp.zeros((), e["X"].data.dtype), None,
                            length=n)
        return c

    dev_s = device_seconds(lambda n: float(loop(env, n)), lo=2, hi=8)

    ref = REFERENCE_SECONDS[task]
    out = {
        "task": task,
        "rows": rows, "cols": cols, "block": block,
        "dtype": str(jnp.dtype(dtype).name),
        "compile_s": round(compile_s, 4),
        "exec_s_median": round(exec_s, 6),
        "exec_s_min": round(min(times), 6),
        "ref_plain_s": ref["plain"],
        "ref_best_s": ref["best"],
        "speedup_vs_ref_best": round(ref["best"] / exec_s, 1),
    }
    if dev_s is not None:
        out["exec_s_device"] = round(dev_s, 6)
        out["speedup_vs_ref_best_device"] = round(ref["best"] / dev_s, 1)
    return out


def run_all(rows: int = 200000, cols: int = 1000, block: int = 1000,
            iters: int = 5) -> Dict[str, Dict[str, object]]:
    return {t: run_task(t, rows, cols, block, iters) for t in TASKS}
