"""Columnar reddit — the reference's social-graph pipeline on the
device engine.

Round 1 ran reddit (``src/reddit``) on the host-object plan path:
per-comment Python feature extraction and interpreter-loop joins
(``workloads/reddit.py``) — a correctness demo. This module gives the
workload the same treatment TPC-H got: records columnarize at ingest
(names dictionary-encoded, body terms hashed to count columns), and
every pipeline stage is a jitted array program over the relational
kernels —

- feature extraction (``CommentFeatures.h:31-47``): ONE vectorized
  kernel computing both time-feature sets, the numeric transforms and
  the hashed-body encoding for the whole table;
- three-way join Comment⋈Author⋈Sub (``RedditThreeWayJoin.h:12-30``):
  planner-chosen LUT joins, or the hash-repartition row shuffle on a
  mesh (``relational/shuffle.py``) when the build sides are fact-scale;
- label propagation (``RedditCommentLabelJoin.h``): per-author
  positive marks via one segment-max + one gather — device
  milliseconds at millions of comments (the round-1 host join is
  seconds at thousands).

Cross-checked against the host-object pipeline on identical synthetic
data (tests/test_reddit_columnar.py).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational import planner as PLN
from netsdb_tpu.relational.table import ColumnTable
from netsdb_tpu.workloads.reddit import (Author, Comment,
                                         DEFAULT_HASH_FEATURES, Sub,
                                         feature_dim)


# ------------------------------------------------------------- ingest
def columnarize(comments: Sequence[Comment], authors: Sequence[Author],
                subs: Sequence[Sub],
                hash_dim: int = DEFAULT_HASH_FEATURES,
                ) -> Dict[str, ColumnTable]:
    """Records → columnar tables. Author/sub references become int key
    columns (the dictionary encoding string joins ride everywhere in
    the columnar engine); body text hashes into count columns at ingest
    (text never reaches the device — same division of labor as the
    LIKE-predicate LUTs in TPC-H)."""
    from netsdb_tpu.workloads.reddit import body_hash_counts

    author_row = {a.author: a.author_id for a in authors}
    sub_row = {s.id: i for i, s in enumerate(subs)}
    n = len(comments)
    body_counts = np.zeros((n, hash_dim - 9), np.float32)
    body_len = np.zeros((n,), np.int32)
    for i, c in enumerate(comments):
        body_len[i] = len(c.body)
        body_counts[i] = body_hash_counts(c.body, hash_dim)

    ct = ColumnTable({
        "index": jnp.asarray(np.fromiter((c.index for c in comments),
                                         np.int32, n)),
        "author_id": jnp.asarray(np.fromiter(
            (author_row[c.author] for c in comments), np.int32, n)),
        "sub_id": jnp.asarray(np.fromiter(
            (sub_row[c.subreddit_id] for c in comments), np.int32, n)),
        "label": jnp.asarray(np.fromiter((c.label for c in comments),
                                         np.int32, n)),
        "score": jnp.asarray(np.fromiter((c.score for c in comments),
                                         np.int32, n)),
        "gilded": jnp.asarray(np.fromiter((c.gilded for c in comments),
                                          np.int32, n)),
        "controversiality": jnp.asarray(np.fromiter(
            (c.controversiality for c in comments), np.int32, n)),
        "archived": jnp.asarray(np.fromiter(
            (int(c.archived) for c in comments), np.int32, n)),
        "stickied": jnp.asarray(np.fromiter(
            (int(c.stickied) for c in comments), np.int32, n)),
        "created_utc": jnp.asarray(np.fromiter(
            (c.created_utc for c in comments), np.int32, n)),
        "author_created_utc": jnp.asarray(np.fromiter(
            (c.author_created_utc for c in comments), np.int32, n)),
        "body_len": jnp.asarray(body_len),
        # hashed-body buckets as FIRST-CLASS columns so every table
        # operation (filter/select/with_column) carries them along
        **{f"body_h{j}": jnp.asarray(body_counts[:, j])
           for j in range(hash_dim - 9)},
    }, dicts={"author_id": [a.author for a in authors],
              "sub_id": [s.id for s in subs]})

    at = ColumnTable({
        "author_id": jnp.asarray(np.fromiter(
            (a.author_id for a in authors), np.int32, len(authors))),
        "karma": jnp.asarray(np.fromiter((a.karma for a in authors),
                                         np.int32, len(authors))),
    })
    st = ColumnTable({
        "sub_row": jnp.asarray(np.arange(len(subs), dtype=np.int32)),
        "subscribers": jnp.asarray(np.fromiter(
            (s.subscribers for s in subs), np.int32, len(subs))),
    })
    from netsdb_tpu.relational.stats import analyze_table

    for t in (ct, at, st):
        analyze_table(t)
    return {"comments": ct, "authors": at, "subs": st}


# ------------------------------------------- vectorized features
def _time_features_cols(utc: jnp.ndarray) -> jnp.ndarray:
    """(N,) int32 epoch seconds → (N, 9) normalized calendar features —
    the vectorized ``reddit.comment_features`` time block. Integer
    sub-expressions stay int32 (exact: epoch < 2^31); only small
    residues reach float32, so the batch kernel matches the host
    float64 scalar path to ~1e-3."""
    days_i = utc // 86400
    secs = utc % 86400
    days = days_i.astype(jnp.float32) + secs.astype(jnp.float32) / 86400.0
    f = jnp.stack([
        ((days % 30.44) + 1.0) / 31.0,
        (utc % 60).astype(jnp.float32) / 60.0,
        ((utc // 60) % 60).astype(jnp.float32) / 59.0,
        (secs // 3600).astype(jnp.float32) / 23.0,
        ((days / 30.44) % 12.0) / 11.0,
        (1970.0 + days / 365.25) / 2021.0,
        ((days_i + 4) % 7).astype(jnp.float32) / 6.0,
        (days % 365.25) / 365.0,
        jnp.zeros_like(days),
    ], axis=1)
    return f


@jax.jit
def _features_core(author_created, created, score, gilded, contro,
                   archived, stickied, body_len, body_counts):
    numeric = jnp.stack([
        jnp.tanh(score.astype(jnp.float32) / 1000.0),
        gilded.astype(jnp.float32),
        contro.astype(jnp.float32),
        archived.astype(jnp.float32),
        stickied.astype(jnp.float32),
        jnp.tanh(body_len.astype(jnp.float32) / 256.0),
    ], axis=1)
    return jnp.concatenate([
        _time_features_cols(author_created),
        _time_features_cols(created),
        numeric,
        jnp.tanh(body_counts),
    ], axis=1)


def batch_features(comments_t: ColumnTable) -> jnp.ndarray:
    """(N, feature_dim) feature matrix in one device pass — replaces N
    calls of the per-record ``comment_features``."""
    c = comments_t
    hash_cols = sorted((n for n in c.cols if n.startswith("body_h")),
                       key=lambda n: int(n[6:]))
    body_counts = jnp.stack([c[n] for n in hash_cols], axis=1)
    return _features_core(c["author_created_utc"], c["created_utc"],
                          c["score"], c["gilded"],
                          c["controversiality"], c["archived"],
                          c["stickied"], c["body_len"],
                          body_counts)


# ------------------------------------------------- three-way join
def three_way_join(tables: Dict[str, ColumnTable]
                   ) -> Tuple[ColumnTable, jnp.ndarray]:
    """Comment⋈Author⋈Sub with planner-chosen joins; returns the
    joined table (comment cols + karma + subscribers) and the feature
    matrix for the joined rows — the reference's FullFeatures set."""
    ct, at, st = tables["comments"], tables["authors"], tables["subs"]
    jp_a = PLN.plan_join(at, "author_id", ct, "author_id")
    jp_s = PLN.plan_join(st, "sub_row", ct, "sub_id")
    aidx, ahit = K.pk_fk_join(at["author_id"], ct["author_id"],
                              plan=jp_a)
    sidx, shit = K.pk_fk_join(st["sub_row"], ct["sub_id"], plan=jp_s)
    hit = ahit & shit
    out = ct.with_column("karma", jnp.take(at["karma"], aidx)) \
            .with_column("subscribers", jnp.take(st["subscribers"], sidx)) \
            .filter(hit)
    return out, batch_features(ct)


def three_way_sink_for(client, db: str = "redditc",
                       output_set: str = "full_features"):
    """The three-way Comment⋈Author⋈Sub as a Computation DAG over
    STORED sets — the placed-set replacement for
    ``sharded_three_way(tables, mesh)``'s hand-mesh surface: create
    ``comments`` with a row-sharding Placement and ``authors``/``subs``
    replicated (or unplaced), and the SAME DAG runs distributed —
    statistics come from ``analyze_set`` summaries, shardings from the
    sets, collectives from XLA (``QuerySchedulerServer.cc:216-330``).
    Output: the joined relation (comment cols + karma + subscribers)."""
    import hashlib

    from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet
    from netsdb_tpu.relational.dag import _fold_mask
    from netsdb_tpu.relational.stats import inject_stats

    names = ("comments", "authors", "subs")
    captured = {n: client.analyze_set(db, n)["stats"] for n in names}
    stats_tag = hashlib.blake2s(repr(sorted(
        (n, sorted((c, s.n_rows, s.min_val, s.max_val)
                   for c, s in cs.items()))
        for n, cs in captured.items())).encode()).hexdigest()[:12]

    def run(pair, st: ColumnTable) -> ColumnTable:
        ct, at = pair
        tabs = {"comments": inject_stats(_fold_mask(ct),
                                         captured["comments"]),
                "authors": inject_stats(_fold_mask(at),
                                        captured["authors"]),
                "subs": inject_stats(_fold_mask(st), captured["subs"])}
        out, _ = three_way_join(tabs)
        return out

    node = Join(Join(ScanSet(db, "comments"), ScanSet(db, "authors"),
                     fn=lambda a, b: (a, b), label="gather:authors"),
                ScanSet(db, "subs"), fn=run,
                label=f"reddit3way:{stats_tag}")
    return WriteSet(node, db, output_set)


def sharded_three_way(tables: Dict[str, ColumnTable], mesh, axis="data",
                      slack: float = 2.0):
    """The distributed form: comments fact-sharded; each dimension side
    placed by the planner — broadcast (the LUT probe inside the shard,
    the common case for author/sub dimension tables) or the
    hash-repartition ROW shuffle (``relational/shuffle.hash_join``)
    when a side is fact-scale. Returns a ShardedRows with the same
    columns as the local join (tests cross-check)."""
    from netsdb_tpu.relational import shuffle as S
    from netsdb_tpu.relational.stats import key_space

    ct, at, st = tables["comments"], tables["authors"], tables["subs"]
    # the broadcast branch replicates BOTH dimension sides — cost both
    dim_bytes = 8 * (at.num_rows + st.num_rows)
    if PLN.plan_distribution(dim_bytes, mesh.shape[axis]).strategy \
            == "broadcast":
        # dimension sides replicated: one local LUT probe per shard —
        # round-trip through hash_repartition only to shard the fact
        t = S.hash_repartition(mesh, axis,
                               {n: ct[n] for n in ct.cols}, "index",
                               slack)
        jp_a = PLN.plan_join(at, "author_id", ct, "author_id")
        jp_s = PLN.plan_join(st, "sub_row", ct, "sub_id")
        aidx, ahit = K.pk_fk_join(at["author_id"], t.cols["author_id"],
                                  plan=jp_a)
        sidx, shit = K.pk_fk_join(st["sub_row"], t.cols["sub_id"],
                                  plan=jp_s)
        cols = dict(t.cols)
        cols["karma"] = jnp.take(at["karma"], aidx)
        cols["subscribers"] = jnp.take(st["subscribers"], sidx)
        return S.ShardedRows(cols, t.valid & ahit & shit, mesh, axis,
                             t.overflow)
    # fact-scale sides: chained row-output hash joins
    j1 = S.hash_join(
        mesh, axis,
        build={"author_id": at["author_id"], "karma": at["karma"]},
        build_key="author_id",
        probe={n: ct[n] for n in ct.cols}, probe_key="author_id",
        key_space=max(key_space(at, "author_id"),
                      key_space(ct, "author_id")), slack=slack)
    S.check_overflow(j1)
    j2 = S.hash_join(
        mesh, axis,
        build={"sub_row": st["sub_row"],
               "subscribers": st["subscribers"]},
        build_key="sub_row",
        probe=j1.cols, probe_key="sub_id",
        key_space=max(key_space(st, "sub_row"),
                      key_space(ct, "sub_id")),
        slack=slack, probe_valid=j1.valid)
    S.check_overflow(j2)
    return j2


# --------------------------------------------- label propagation
@functools.partial(jax.jit, static_argnums=(0,))
def _propagate_core(n_authors: int, author_id, label):
    """The whole RedditCommentLabelJoin as one scatter-free self-semi-
    join: grid-blocked one-hot MXU reduce + two-level gather
    (``kernels.any_by_key``). Round 2's segment-max + flat-gather form
    was scatter-serialized at 13.6 ms/1M rows; this is 3.45 ms on v5e."""
    return K.any_by_key(author_id, (label == 1).astype(jnp.int32),
                        n_authors)


def propagate_labels(comments_t: ColumnTable,
                     n_authors: Optional[int] = None) -> jnp.ndarray:
    """(N,) int32: 1 iff the comment's author has any positive-labeled
    comment — the label-propagation join's set semantics (the host
    object join emits one row per matching pair; collapsing to
    per-comment adoption is the fixed point both agree on)."""
    from netsdb_tpu.relational.stats import key_space

    if n_authors is None:
        n_authors = key_space(comments_t, "author_id")
    return _propagate_core(n_authors, comments_t["author_id"],
                           comments_t["label"])


def author_comment_counts(comments_t: ColumnTable,
                          n_authors: Optional[int] = None) -> jnp.ndarray:
    """(n_authors,) comment counts — the workload's group-by."""
    from netsdb_tpu.relational.stats import key_space

    if n_authors is None:
        n_authors = key_space(comments_t, "author_id")
    return K.segment_count(comments_t["author_id"], n_authors)


def label_partition_counts(comments_t: ColumnTable,
                           num_parts: int = 11) -> jnp.ndarray:
    """(2, num_parts) row counts of the reference's 2×11
    ``RedditLabelSelection{i}_{j}`` grid — the 60 generated selection
    classes as ONE segment count over (label, index % parts)."""
    seg = (comments_t["label"] * num_parts
           + comments_t["index"] % num_parts)
    return K.segment_count(seg, 2 * num_parts).reshape(2, num_parts)


# ----------------------------------------------------------- bench
def bench_label_propagation(rows: int = 1_000_000,
                            n_authors: int = 50_000,
                            seed: int = 0) -> Dict[str, object]:
    """≥1M comments through label propagation + the per-author
    group-by + the 2×11 partition grid, device-timed (scan-slope)."""
    from netsdb_tpu.utils.timing import scan_slope_seconds

    rng = np.random.default_rng(seed)
    t = ColumnTable({
        "index": jnp.asarray(np.arange(rows, dtype=np.int32)),
        "author_id": jnp.asarray(
            rng.integers(0, n_authors, rows).astype(np.int32)),
        "label": jnp.asarray(
            (rng.random(rows) < 0.01).astype(np.int32)),
    })

    @functools.partial(jax.jit, static_argnums=(3, 4, 5))
    def loop(author_id, label, index, n_auth, parts, n):
        def step(carry, _):
            aid = (author_id + carry) % n_auth  # carry-coupled: no hoist
            prop = _propagate_core(n_auth, aid, label)
            counts = K.segment_count(aid, n_auth)
            seg = label * parts + index % parts
            grid = K.segment_count(seg, 2 * parts)
            # carry keeps a live (non-constant) data dependency so XLA
            # can neither hoist the body nor dead-code-eliminate it
            return (prop.sum() + counts.max() + grid.sum()) % 127, None

        c, _ = jax.lax.scan(step, jnp.zeros((), jnp.int32), None,
                            length=n)
        return c

    res = scan_slope_seconds(
        lambda n: float(loop(t["author_id"], t["label"], t["index"],
                             n_authors, 11, n)), lo=2, hi=8)
    dt = res["seconds_per_iter"]
    if dt is None:  # below device timing noise (tiny smoke shapes)
        return {"rows": rows, "n_authors": n_authors,
                "device_ms": 0.0, "rows_per_sec": float("inf"),
                "below_noise": True}
    return {"rows": rows, "n_authors": n_authors,
            "device_ms": round(dt * 1e3, 3),
            "rows_per_sec": round(rows / dt, 1)}
