"""Top-K — reference ``src/sharedLibraries/headers/TopKTest.h`` (driver
``src/tests/source/TestTopK.cc``): an aggregation maintaining the K
nearest/highest-scored items. On TPU: ``jax.lax.top_k`` over a scored
array; the set driver scores host objects with a user lambda first."""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def top_k(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """→ (values, indices), descending."""
    return jax.lax.top_k(scores, k)


def top_k_on_set(client, db: str, set_name: str, k: int,
                 score: Callable[[Any], float],
                 out_set: str = "topk") -> List[Any]:
    """Score every item in a set, keep the K best (reference TopK over
    arbitrary pdb::Objects with a distance lambda)."""
    items = list(client.get_set_iterator(db, set_name))
    if not items:
        return []
    scores = jnp.asarray([score(it) for it in items], jnp.float32)
    k = min(k, len(items))
    _, idx = top_k(scores, k)
    winners = [items[int(i)] for i in np.asarray(idx)]
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="object")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, winners)
    return winners


def top_k_on_table_set(client, db: str, set_name: str, score_col: str,
                       k: int, out_set: str = "topk_table"):
    """Placed-set driver: scores live in a stored ColumnTable column,
    so a sharded set top-ks on device (one `top_k_masked` over the
    sharded column — XLA all-gathers the k winners, not the set). The
    result is a k-row relation {row, score} like the reference's TopK
    output set."""
    import jax.numpy as jnp
    import numpy as np

    from netsdb_tpu.relational import kernels as K
    from netsdb_tpu.relational.table import ColumnTable

    t = client.get_table(db, set_name)
    scores = t[score_col]
    kk = min(k, scores.shape[0])
    idx, ok = K.top_k_masked(scores, kk, t.mask())
    out = ColumnTable({"row": idx, "score": jnp.take(scores, idx)},
                      valid=ok)
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="table")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, [out])
    return out
