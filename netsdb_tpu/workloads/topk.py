"""Top-K — reference ``src/sharedLibraries/headers/TopKTest.h`` (driver
``src/tests/source/TestTopK.cc``): an aggregation maintaining the K
nearest/highest-scored items. On TPU: ``jax.lax.top_k`` over a scored
array; the set driver scores host objects with a user lambda first."""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def top_k(scores: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """→ (values, indices), descending."""
    return jax.lax.top_k(scores, k)


def top_k_on_set(client, db: str, set_name: str, k: int,
                 score: Callable[[Any], float],
                 out_set: str = "topk") -> List[Any]:
    """Score every item in a set, keep the K best (reference TopK over
    arbitrary pdb::Objects with a distance lambda)."""
    items = list(client.get_set_iterator(db, set_name))
    if not items:
        return []
    scores = jnp.asarray([score(it) for it in items], jnp.float32)
    k = min(k, len(items))
    _, idx = top_k(scores, k)
    winners = [items[int(i)] for i in np.asarray(idx)]
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="object")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, winners)
    return winners
