"""tpchBench — the reference's Customer⋈Order⋈LineItem micro-benchmark
family (``src/tpchBench``, ~5.3 kLoC).

Unlike the flat-table TPC-H suite (``workloads/tpch.py``), this family
works over a NESTED object model: ``Customer`` holds a
``Vector<Order>``, each ``Order`` a ``Vector<LineItem>``, each
``LineItem`` a ``Part`` and ``Supplier``
(``src/tpchBench/headers/Customer.h:25-40``, ``Order.h``,
``LineItem.h``) — exercising deep object graphs through the engine
rather than joins. The query shapes reproduced here:

- selections over customers by int/string predicates, plus negated
  variants (``CustomerIntegerSelection[Not].h``,
  ``CustomerStringSelection[Not].h``; the "virtual" variants differ
  only in C++ dispatch, which has no analogue here)
- flatten customers → (customerName, supplierName, partKey) triples
  (``CustomerMultiSelection.h`` → ``CustomerSupplierPartFlat.h:12``)
- group-by supplier name collecting per-customer part keys
  (``CustomerSupplierPartGroupBy.h:18-19`` → ``SupplierInfo.h``)
- count aggregations (``CountAggregation.h``, ``CountCustomer.h``)
- top-K customers by Jaccard similarity of their part set against a
  query part set (``TopJaccard.h:17``, result via
  ``JaccardResultWriter.h``)
"""

from __future__ import annotations

import dataclasses
import heapq
import random
from typing import Dict, FrozenSet, List, Sequence, Tuple

from netsdb_tpu.plan.computations import (
    Aggregate, Filter, MultiApply, ScanSet, WriteSet,
)


@dataclasses.dataclass
class LineItem:
    """``src/tpchBench/headers/LineItem.h`` — reduced to the fields the
    benchmark queries read (part + supplier identity)."""

    lineNumber: int
    partKey: int
    supplierName: str


@dataclasses.dataclass
class Order:
    orderKey: int
    lineItems: List[LineItem]


@dataclasses.dataclass
class Customer:
    """Nested customer object (``Customer.h:25-40``)."""

    custKey: int
    name: str
    nationKey: int
    mktsegment: str
    accbal: float
    orders: List[Order]


@dataclasses.dataclass
class CustomerSupplierPartFlat:
    """``CustomerSupplierPartFlat.h:12`` — one flattened triple."""

    customerName: str
    supplierName: str
    partKey: int


def generate(num_customers: int = 50, max_orders: int = 4,
             max_items: int = 5, num_parts: int = 60,
             num_suppliers: int = 12, seed: int = 0) -> List[Customer]:
    """Seeded nested instance — the reference's ``generateSmallDataset``
    in its tpchBench drivers."""
    rng = random.Random(seed)
    segs = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
    out = []
    order_key = 0
    for ck in range(num_customers):
        orders = []
        for _ in range(rng.randrange(1, max_orders + 1)):
            items = [LineItem(lineNumber=i,
                              partKey=rng.randrange(num_parts),
                              supplierName=f"Supplier{rng.randrange(num_suppliers)}")
                     for i in range(rng.randrange(1, max_items + 1))]
            orders.append(Order(orderKey=order_key, lineItems=items))
            order_key += 1
        out.append(Customer(custKey=ck, name=f"Customer{ck}",
                            nationKey=rng.randrange(25),
                            mktsegment=rng.choice(segs),
                            accbal=round(rng.uniform(-999, 9999), 2),
                            orders=orders))
    return out


def load(client, customers: Sequence[Customer], db: str = "tpchbench") -> None:
    client.create_database(db)
    if not client.set_exists(db, "customers"):
        client.create_set(db, "customers", type_name="object")
    client.clear_set(db, "customers")
    client.send_data(db, "customers", list(customers))


# --- selections -------------------------------------------------------

def customer_int_selection(db: str = "tpchbench", threshold: int = 0,
                           negate: bool = False) -> WriteSet:
    """``CustomerIntegerSelection[Not]`` — custKey predicate."""
    scan = ScanSet(db, "customers")
    if negate:
        pred = lambda c, t=threshold: not (c.custKey > t)
    else:
        pred = lambda c, t=threshold: c.custKey > t
    f = Filter(scan, pred, label=f"custkey{'_not' if negate else ''}>{threshold}")
    return WriteSet(f, db, "selected_int" + ("_not" if negate else ""))


def customer_string_selection(db: str = "tpchbench", segment: str = "BUILDING",
                              negate: bool = False) -> WriteSet:
    """``CustomerStringSelection[Not]`` — mktsegment predicate."""
    scan = ScanSet(db, "customers")
    if negate:
        pred = lambda c, s=segment: c.mktsegment != s
    else:
        pred = lambda c, s=segment: c.mktsegment == s
    f = Filter(scan, pred, label=f"seg{'_not' if negate else ''}={segment}")
    return WriteSet(f, db, "selected_str" + ("_not" if negate else ""))


# --- flatten + group-by ----------------------------------------------

def _flatten_customer(c: Customer) -> List[CustomerSupplierPartFlat]:
    return [CustomerSupplierPartFlat(c.name, li.supplierName, li.partKey)
            for o in c.orders for li in o.lineItems]


def flatten_triples(db: str = "tpchbench") -> WriteSet:
    """``CustomerMultiSelection`` — explode the nested object graph into
    (customer, supplier, part) triples (a FLATTEN atom)."""
    scan = ScanSet(db, "customers")
    m = MultiApply(scan, _flatten_customer, label="cust_supplier_part")
    return WriteSet(m, db, "triples")


def group_by_supplier(db: str = "tpchbench") -> WriteSet:
    """``CustomerSupplierPartGroupBy`` → ``SupplierInfo``: supplier name
    → {customer name → sorted part keys}."""
    scan = ScanSet(db, "triples")

    def combine(a: Dict[str, List[int]], b: Dict[str, List[int]]):
        out = {k: list(v) for k, v in a.items()}
        for cust, parts in b.items():
            out.setdefault(cust, []).extend(parts)
        return out

    agg = Aggregate(scan,
                    key=lambda t: t.supplierName,
                    value=lambda t: {t.customerName: [t.partKey]},
                    combine=combine, label="supplier_info")
    return WriteSet(agg, db, "supplier_info")


def count_customers(db: str = "tpchbench") -> WriteSet:
    """``CountCustomer``/``CountAggregation`` — single-group count."""
    scan = ScanSet(db, "customers")
    agg = Aggregate(scan, key=lambda c: 0, value=lambda c: 1,
                    combine=lambda a, b: a + b, label="count")
    return WriteSet(agg, db, "customer_count")


# --- top-K jaccard ----------------------------------------------------

def _part_set(c: Customer) -> FrozenSet[int]:
    return frozenset(li.partKey for o in c.orders for li in o.lineItems)


def top_jaccard(db: str = "tpchbench", query_parts: Sequence[int] = (),
                k: int = 5) -> WriteSet:
    """``TopJaccard : TopKComp<Customer, double, Handle<AllParts>>`` —
    score every customer by Jaccard(parts(c), query) and keep the top
    K. The reference's TopKComp is an aggregation maintaining a bounded
    heap; same here, as a single-group Aggregate whose combiner merges
    heaps (so it distributes exactly like ClusterAggregateComp)."""
    q = frozenset(query_parts)
    scan = ScanSet(db, "customers")

    def score(c: Customer) -> List[Tuple[float, int, str]]:
        parts = _part_set(c)
        denom = len(parts | q)
        j = (len(parts & q) / denom) if denom else 0.0
        return [(j, c.custKey, c.name)]

    def combine(a: List, b: List) -> List:
        return heapq.nlargest(k, a + b)

    agg = Aggregate(scan, key=lambda c: 0, value=score, combine=combine,
                    label=f"top{k}_jaccard")
    return WriteSet(agg, db, "top_jaccard")
