"""Model-family inference benchmarks: word2vec, LSTM, text classifier.

The reference ships these workloads (``src/word2vec/source/Word2Vec.cc``,
``src/LSTM`` + ``src/tests/source/LSTMTest.cc``,
``src/word2vec/source/TestSemanticClassifier.cc``) but publishes NO
performance numbers for them (BASELINE.md), so this module measures
both sides itself: the TPU path through this framework and the
netsDB-equivalent CPU path (numpy f64 block GEMMs — the per-worker
Eigen compute model) on this host.

Timing: device via ``utils.timing.scan_slope_seconds`` (see there);
CPU baselines by direct wall timing (no tunnel noise on host).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops.lstm import LSTMParams, lstm_cell
from netsdb_tpu.utils.timing import device_seconds


def _device_seconds(loop, *args) -> Optional[float]:
    return device_seconds(lambda n: float(loop(*args, n)))


def _cpu_median_seconds(fn, repeats: int = 3) -> float:
    """Median wall time of ``fn()`` — same median-of-repeats discipline
    as the device side, so one cold run (BLAS pool spin-up, scheduler
    hiccup) cannot inflate the published speedup."""
    fn()  # warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def bench_word2vec(vocab: int = 100_000, dim: int = 512,
                   batch: int = 65536, seed: int = 0) -> Dict[str, float]:
    """Embedding serving. TPU path = gather; CPU baseline = the
    reference's one-hot x table blocked matmul (Word2Vec.cc:19-80)."""
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, vocab, batch), jnp.int32)

    @partial(jax.jit, static_argnums=2)
    def loop(t, i, n):
        def step(carry, _):
            out = jnp.take(t, (i + carry) % vocab, axis=0)
            return jnp.sum(out).astype(jnp.int32) % vocab, None
        c, _ = jax.lax.scan(step, jnp.int32(0), None, length=n)
        return c

    dev = _device_seconds(loop, table, ids)

    # CPU equivalent at reduced batch, linear in batch: one-hot matmul.
    # One (chunk, vocab) one-hot is built OUTSIDE the timed region and
    # reused for cpu_batch/chunk GEMMs — identical timed FLOPs to the
    # single (cpu_batch, vocab) matmul (GEMM cost is independent of
    # which rows are hot) at ~200 MB instead of ~1.6 GB of one-hot
    cpu_batch = 2048
    chunk = 256
    onehot = np.zeros((chunk, vocab))
    onehot[np.arange(chunk), rng.integers(0, vocab, chunk)] = 1.0
    tbl64 = np.asarray(table, np.float64)

    def onehot_matmul():
        for _ in range(cpu_batch // chunk):
            onehot @ tbl64

    cpu = _cpu_median_seconds(onehot_matmul) / cpu_batch
    out = {"vocab": vocab, "dim": dim, "batch": batch,
           "cpu_onehot_matmul_ids_per_sec": round(1.0 / cpu, 1)}
    if dev is not None:
        out["tpu_lookup_ids_per_sec"] = round(batch / dev, 1)
        out["speedup"] = round((batch / dev) * cpu, 1)
    else:
        out["below_device_noise"] = True
    return out


def bench_lstm(hidden: int = 1024, inp: int = 1024, batch: int = 1024,
               block: int = 512, seed: int = 0) -> Dict[str, float]:
    """One LSTM cell step (8 matmuls + gates — the reference's
    LSTMTest DAG) in cells/s of (hidden x batch) state updates."""
    rng = np.random.default_rng(seed)

    def bt(r, c):
        return BlockedTensor.from_dense(
            rng.standard_normal((r, c)).astype(np.float32), (block, block))

    def bias(r):
        return BlockedTensor.from_dense(
            rng.standard_normal((r, 1)).astype(np.float32) * 0.1, (block, 1))

    params = LSTMParams(
        w_i=bt(hidden, inp), w_f=bt(hidden, inp), w_c=bt(hidden, inp),
        w_o=bt(hidden, inp),
        u_i=bt(hidden, hidden), u_f=bt(hidden, hidden),
        u_c=bt(hidden, hidden), u_o=bt(hidden, hidden),
        b_i=bias(hidden), b_f=bias(hidden), b_c=bias(hidden),
        b_o=bias(hidden),
    )
    x = bt(inp, batch)
    h0 = bt(hidden, batch)
    c0 = bt(hidden, batch)

    @partial(jax.jit, static_argnums=3)
    def loop(p, xx, state, n):
        def step(carry, _):
            h, c = carry
            # x must depend on the carry: with a loop-invariant x, XLA
            # hoists the four W·x matmuls out of the scan and the
            # "cell step" measures only half its matmuls (observed as
            # 2x-over-peak throughput)
            x_t = xx.with_data(xx.data + jnp.sum(h.data) * 1e-20)
            h2, c2 = lstm_cell(p, x_t, h, c, "bfloat16")
            return (h2, c2), None
        (h, c), _ = jax.lax.scan(step, state, None, length=n)
        return jnp.sum(h.data) + jnp.sum(c.data)

    dev = _device_seconds(loop, params, x, (h0, c0))

    # CPU equivalent: same 8 GEMMs + gates in f64 numpy at reduced batch
    cpu_batch = 128
    w = {k: np.asarray(getattr(params, k).to_dense(), np.float64)
         for k in ("w_i", "w_f", "w_c", "w_o", "u_i", "u_f", "u_c", "u_o")}
    xs = rng.standard_normal((inp, cpu_batch))
    hs = rng.standard_normal((hidden, cpu_batch))

    def cpu_cell():
        for gate_w, gate_u in (("w_i", "u_i"), ("w_f", "u_f"),
                               ("w_c", "u_c"), ("w_o", "u_o")):
            z = w[gate_w] @ xs + w[gate_u] @ hs
            _ = 1.0 / (1.0 + np.exp(-z))

    cpu = _cpu_median_seconds(cpu_cell) / cpu_batch
    out = {"hidden": hidden, "input": inp, "batch": batch,
           "cpu_cell_rows_per_sec": round(1.0 / cpu, 1)}
    if dev is not None:
        out["tpu_cell_rows_per_sec"] = round(batch / dev, 1)
        out["speedup"] = round((batch / dev) * cpu, 1)
    else:
        out["below_device_noise"] = True
    return out


def bench_text_classifier(vocab: int = 50_000, dim: int = 512,
                          labels: int = 16, batch: int = 16384,
                          seed: int = 0) -> Dict[str, float]:
    """word2vec layer + SemanticClassifier FC layer
    (``TestSemanticClassifier.cc`` / ``SemanticClassifier.h``): docs/s."""
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((labels, dim)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((labels,)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, vocab, batch), jnp.int32)

    @partial(jax.jit, static_argnums=4)
    def loop(t, ww, bb, i, n):
        def step(carry, _):
            feats = jnp.take(t, (i + carry) % vocab, axis=0)  # (batch, dim)
            logits = feats @ ww.T + bb
            probs = jax.nn.softmax(logits, axis=1)
            return jnp.sum(probs).astype(jnp.int32) % vocab, None
        c, _ = jax.lax.scan(step, jnp.int32(0), None, length=n)
        return c

    dev = _device_seconds(loop, table, w, b, ids)

    cpu_batch = 4096
    t64 = np.asarray(table, np.float64)
    w64 = np.asarray(w, np.float64)
    cids = rng.integers(0, vocab, cpu_batch)

    def cpu_cls():
        feats = t64[cids]
        logits = feats @ w64.T + np.asarray(b, np.float64)
        e = np.exp(logits - logits.max(1, keepdims=True))
        _ = e / e.sum(1, keepdims=True)

    cpu = _cpu_median_seconds(cpu_cls) / cpu_batch
    out = {"vocab": vocab, "dim": dim, "labels": labels, "batch": batch,
           "cpu_docs_per_sec": round(1.0 / cpu, 1)}
    if dev is not None:
        out["tpu_docs_per_sec"] = round(batch / dev, 1)
        out["speedup"] = round((batch / dev) * cpu, 1)
    else:
        out["below_device_noise"] = True
    return out


def run_model_bench(scale: float = 1.0, seed: int = 0) -> Dict[str, Dict]:
    s = lambda v: max(int(v * scale), 1)
    return {
        "word2vec": bench_word2vec(vocab=s(100_000), dim=s(512),
                                   batch=s(65536), seed=seed),
        "lstm": bench_lstm(hidden=s(1024), inp=s(1024), batch=s(1024),
                           block=min(s(512), 512), seed=seed),
        "text_classifier": bench_text_classifier(
            vocab=s(50_000), dim=s(512), labels=max(s(16), 2),
            batch=s(16384), seed=seed),
    }
