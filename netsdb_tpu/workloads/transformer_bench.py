"""Long-context transformer-layer benchmark — the in-database modern
model, end to end.

The reference's "in-database inference" story stops at FF/LSTM/conv;
this framework's beyond-reference claim is that the same set API serves
a modern long-context layer: weights live in database sets
(``models.transformer.TransformerLayerModel``), the attention core is
the pallas flash kernel, and the whole layer (LN → QKV → flash
attention → out-proj → MLP) runs as one jit. Reports tokens/s and the
layer's achieved TFLOP/s at reference-scale long sequences.
"""

from __future__ import annotations

import functools
import tempfile
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def layer_flops(batch: int, seq: int, embed: int, heads: int,
                causal: bool = True) -> float:
    """Matmul FLOPs of one layer forward: QKV (2*B*S*E*3E) + attention
    (2*2*B*H*S*S*D, halved causal) + out (2*B*S*E*E) + MLP
    (2*2*B*S*E*4E)."""
    d = embed // heads
    attn = 2 * 2 * batch * heads * seq * seq * d * (0.5 if causal else 1)
    proj = 2 * batch * seq * embed * (3 * embed + embed)
    mlp = 2 * 2 * batch * seq * embed * 4 * embed
    return attn + proj + mlp


def bench_transformer_layer(seq_lens: Sequence[int] = (4096, 8192),
                            batch: int = 2, embed: int = 1024,
                            heads: int = 8, seed: int = 0
                            ) -> Dict[str, Dict]:
    """Set-backed layer forward at long sequences, bf16 compute,
    device-timed via the scan-slope protocol."""
    import shutil

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.models.transformer import TransformerLayerModel
    from netsdb_tpu.utils.timing import scan_slope_seconds

    if embed % heads:
        raise ValueError(f"embed {embed} not divisible by heads {heads}")
    root = tempfile.mkdtemp(prefix="tfb_")
    try:
        client = Client(Configuration(root_dir=root))
        model = TransformerLayerModel(db="tfb", num_heads=heads)
        model.setup(client)
        model.load_random_weights(client, embed=embed, seed=seed)
        params = model.params_from_store(client)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    params = jax.tree_util.tree_map(
        lambda w: jnp.asarray(w, jnp.bfloat16), params)

    rng = np.random.default_rng(seed)
    out: Dict[str, Dict] = {}
    fwd = jax.jit(model.forward)
    for s in seq_lens:
        x = jnp.asarray(rng.standard_normal((batch, s, embed)),
                        jnp.bfloat16)

        @functools.partial(jax.jit, static_argnums=2)
        def loop(p, xx, n):
            def step(c, _):
                o = fwd(p, xx + c)
                return (jnp.sum(o) * 1e-20).astype(xx.dtype), None

            c, _ = jax.lax.scan(step, jnp.zeros((), xx.dtype), None,
                                length=n)
            return c

        res = scan_slope_seconds(lambda n: float(loop(params, x, n)),
                                 lo=2, hi=8)
        dt = res["seconds_per_iter"]
        if dt is None:
            out[f"seq_{s}"] = {"below_device_noise": True}
            continue
        fl = layer_flops(batch, s, embed, heads)
        out[f"seq_{s}"] = {
            "ms": round(dt * 1e3, 3),
            "tokens_per_sec": round(batch * s / dt, 1),
            "tflops": round(fl / dt / 1e12, 1),
        }
    return out
