"""Latent Dirichlet Allocation — reference ``src/sharedLibraries/headers/
LDA*`` (LDADocWordTopicAssignment, LDAInitialTopicProbSelection, …;
driver ``src/tests/source/TestLDA.cc``).

The reference runs collapsed-Gibbs-flavored updates as repeated
join/aggregate rounds over doc-word-topic assignment sets. Here the
same doc-topic/word-topic decomposition is learned with batch EM
(PLSA-with-priors — the deterministic counterpart of the reference's
sampled updates), one jitted loop over a dense (docs x vocab) count
matrix: E-step responsibilities and M-step count aggregations are the
matmuls/segment-sums the reference expressed relationally.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LDAState(NamedTuple):
    doc_topic: jax.Array   # (docs, k) θ
    topic_word: jax.Array  # (k, vocab) φ


def lda_em(counts: jax.Array, k: int, iters: int = 50, alpha: float = 0.1,
           beta: float = 0.01, seed: int = 0) -> LDAState:
    """``counts``: (docs x vocab) word counts → fitted θ, φ."""
    docs, vocab = counts.shape
    key1, key2 = jax.random.split(jax.random.key(seed))
    theta = jax.random.dirichlet(key1, jnp.full((k,), 1.0), (docs,))
    phi = jax.random.dirichlet(key2, jnp.full((vocab,), 1.0), (k,))

    def step(_, state):
        theta, phi = state
        # E+M fused without the (docs,k,vocab) responsibility cube:
        #   resp[d,t,w] = θ[d,t]φ[t,w]/norm[d,w]
        #   Σ_w resp·counts = θ ⊙ (counts/norm @ φᵀ)   (doc-topic counts)
        #   Σ_d resp·counts = φ ⊙ (θᵀ @ counts/norm)   (topic-word counts)
        norm = jnp.maximum(theta @ phi, 1e-12)
        ratio = counts / norm
        dt = theta * (ratio @ phi.T) + alpha
        tw = phi * (theta.T @ ratio) + beta
        return (dt / dt.sum(1, keepdims=True),
                tw / tw.sum(1, keepdims=True))

    theta, phi = jax.lax.fori_loop(0, iters, step, (theta, phi))
    return LDAState(doc_topic=theta, topic_word=phi)


def lda_perplexity(counts: jax.Array, state: LDAState) -> jax.Array:
    probs = jnp.maximum(state.doc_topic @ state.topic_word, 1e-12)
    ll = jnp.sum(counts * jnp.log(probs))
    return jnp.exp(-ll / jnp.maximum(counts.sum(), 1.0))


def lda_on_set(client, db: str, set_name: str, k: int, iters: int = 50,
               alpha: float = 0.1, beta: float = 0.01,
               out_set: str = "lda_topics", seed: int = 0) -> LDAState:
    """Set-oriented driver: the (docs × vocab) count matrix comes from a
    stored tensor set; a row-sharded placement distributes the EM (the
    matmuls against φ/θ contract over the sharded axis — XLA inserts
    the psums). φ (topic-word) is written back as the output set."""
    import numpy as np

    from netsdb_tpu.core.blocked import BlockedTensor
    from netsdb_tpu.storage.store import SetIdentifier

    counts = client.get_tensor(db, set_name)
    state = jax.jit(lambda c: lda_em(c, k, iters, alpha, beta,
                                     seed=seed))(counts.to_dense())
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set)
    client.store.put_tensor(
        SetIdentifier(db, out_set),
        BlockedTensor.from_dense(np.asarray(state.topic_word),
                                 counts.meta.block_shape))
    return state
