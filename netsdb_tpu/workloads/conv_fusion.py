"""Conv2D memory-fusion — the reference's staged relational im2col rewrite.

The reference subsystem ``src/conv2d_memory_fusion`` (driver
``src/tests/source/PipelinedConv2dMemFuseTest.cc:137-299``) lowers conv2d
onto the blocked-matmul engine through four materialized jobs:

1. ``kernel_bias_join``: Kernel set → ``KernelToChunks`` →
   ``ImageChunksToBlock`` → ``ImageBlockToMatrix`` → ``KernelBiasJoin``
   (bias written into the extra trailing column) → ``kernel_flat`` set.
2. ``image_ops``: Image set → ``ImageToChunks`` (im2col windows, each row
   ending in a literal 1.0 so the bias column multiplies through) →
   ``ImageChunksToBlock`` → ``ImageBlockToMatrix`` → ``image_flat`` set.
3. ``conv2d``: ``FFTransposeMult`` ⋈ + ``FFAggMatrix`` Σ over the two
   blocked matrices → ``result`` set.
4. reassembly: ``ConvResultToChunks`` → ``ImageChunksToBlock`` →
   ``ConvChunksToImage`` → output Image set (commented out in the
   reference driver but shipped in ``headers/ConvChunksToImage.h``).

Here each reference Computation is the same node kind on our plan DAG
(MultiApply = MultiSelectionComp, Aggregate = AggregateComp, Join =
JoinComp), the chunk→block→matrix plumbing is host-side data prep (as the
reference's per-tuple C++ lambdas are), and the one hot loop — the big
matmul — is a single jitted blocked ``dot_general`` on the MXU
(``ops.matmul.matmul_t``) instead of per-block-pair Eigen GEMMs.

The fused single-kernel fast path for production serving is
``ops.conv.conv2d_im2col``; this module is the capability-parity staged
pipeline (debuggable, materialized, set-to-set).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops.matmul import matmul_t
from netsdb_tpu.plan.computations import (
    Aggregate, Apply, Join, MultiApply, ScanSet, WriteSet)


# --- record types (reference headers/Image.h, Kernel.h, ImageChunk.h) ---

@dataclass
class Image:
    """(C,H,W) tensor with an integer key — reference ``Image.h``."""
    key: int
    data: np.ndarray  # (C, H, W)

    @property
    def channels(self) -> int:
        return self.data.shape[0]

    def window_count(self, k: int, stride: int, padding: int) -> int:
        _, h, w = self.data.shape
        oh = (h + 2 * padding - k) // stride + 1
        ow = (w + 2 * padding - k) // stride + 1
        return oh * ow


@dataclass
class Kernel:
    """One filter (I,KH,KW), key = output-channel index — ``Kernel.h``."""
    key: int
    data: np.ndarray  # (I, KH, KW)


@dataclass
class Chunk:
    """A block_y-wide slice of one im2col row — reference ``ImageChunk.h``
    (fields block_row/y_index/chunk/block_row_start)."""
    row: int          # global row index in the flattened matrix
    y_index: int      # column-block index
    values: np.ndarray  # length == block_y (zero-padded tail)


def _row_chunks(row_index: int, values: np.ndarray, block_y: int) -> List[Chunk]:
    n_blocks = -(-len(values) // block_y)
    padded = np.zeros(n_blocks * block_y, np.float32)
    padded[:len(values)] = values
    return [Chunk(row_index, j, padded[j * block_y:(j + 1) * block_y])
            for j in range(n_blocks)]


# --- the pipeline builder ----------------------------------------------

@dataclass
class ConvFusionPipeline:
    """Staged conv2d-as-relational-algebra over the engine.

    Shapes follow the reference driver: images (C,H,W), kernels (O,I,KH,KW);
    flattened width = C*KH*KW + 1 (the +1 carries the bias through the
    matmul — ``PipelinedConv2dMemFuseTest.cc`` "147 + 1").
    """
    db: str = "convfuse"
    kernel_size: int = 7
    stride: int = 1
    padding: int = 0
    block: Tuple[int, int] = (64, 64)
    compute_dtype: Optional[str] = None

    SETS = ("images", "kernels", "bias",
            "kernel_flat", "image_flat", "result", "output")

    # -- setup / load ---------------------------------------------------

    def setup(self, client: Client, placements=None) -> None:
        """``placements``: set name → Placement. The compute-heavy sets
        are ``image_flat`` (windows × flatwidth — row-shard on ``data``)
        and ``kernel_flat`` (replicate: it is the broadcast join side);
        the record sets (``images``/``kernels``) are host objects and
        ignore placement, exactly like the reference's pre-flatten
        stages running on the scan threads."""
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s))

    def load(self, client: Client, images: np.ndarray, kernels: np.ndarray,
             bias: Optional[np.ndarray] = None) -> None:
        """images (N,C,H,W) → N Image records; kernels (O,I,KH,KW) → O
        Kernel records; bias (O,) stored whole (an FFMatrixBlock set in
        the reference)."""
        images = np.asarray(images, np.float32)
        kernels = np.asarray(kernels, np.float32)
        for s in ("images", "kernels", "bias"):  # a load replaces, as
            client.clear_set(self.db, s)         # tpch.load_tables does
        client.send_data(self.db, "images",
                         [Image(i, images[i]) for i in range(len(images))])
        client.send_data(self.db, "kernels",
                         [Kernel(o, kernels[o]) for o in range(len(kernels))])
        b = (np.zeros(len(kernels), np.float32) if bias is None
             else np.asarray(bias, np.float32))
        client.send_data(self.db, "bias", [b])

    # -- per-stage computation factories (reference header per name) ----

    def _flat_width(self, channels: int) -> int:
        return channels * self.kernel_size * self.kernel_size + 1

    def image_to_chunks(self, img: Image) -> List[Chunk]:
        """``ImageToChunks.h``: im2col window rows (c-major, then kh, kw)
        with a trailing 1.0; global row = key*windows + window."""
        k, s, p = self.kernel_size, self.stride, self.padding
        data = img.data
        if p:
            data = np.pad(data, ((0, 0), (p, p), (p, p)))
        c, h, w = data.shape
        oh = (h - k) // s + 1
        ow = (w - k) // s + 1
        row_start = img.key * oh * ow
        out: List[Chunk] = []
        for wi in range(oh * ow):
            y, x = (wi // ow) * s, (wi % ow) * s
            patch = data[:, y:y + k, x:x + k].reshape(-1)
            row = np.concatenate([patch, [1.0]]).astype(np.float32)
            out.extend(_row_chunks(row_start + wi, row, self.block[1]))
        return out

    def kernel_to_chunks(self, ker: Kernel) -> List[Chunk]:
        """``KernelToChunks.h``: one row per filter, same layout, last
        column left 0 for the bias join to fill."""
        flat = ker.data.reshape(-1).astype(np.float32)
        row = np.concatenate([flat, [0.0]]).astype(np.float32)
        return _row_chunks(ker.key, row, self.block[1])

    def chunks_to_blocks(self, scan):
        """``ImageChunksToBlock.h``: aggregate chunks of the same
        (row-block, col-block) into one partial block; disjoint rows sum."""
        bx, by = self.block

        def place(ch: Chunk) -> np.ndarray:
            blk = np.zeros((bx, by), np.float32)
            blk[ch.row % bx] = ch.values
            return blk

        return Aggregate(scan, key=lambda ch: (ch.row // bx, ch.y_index),
                         value=place, combine=np.add,
                         label="ImageChunksToBlock")

    def blocks_to_matrix(self, blocks_node, total_rows: int, total_cols: int):
        """``ImageBlockToMatrix.h``: {(bi,bj): block} dict → one blocked
        matrix of the given logical shape (zero block-padded)."""
        def assemble(block_dict) -> BlockedTensor:
            return BlockedTensor.from_blocks(
                block_dict, (total_rows, total_cols), self.block)

        return Apply(blocks_node, assemble, label="ImageBlockToMatrix")

    # -- the four jobs --------------------------------------------------

    def build_kernel_flat(self, channels: int, num_filters: int) -> WriteSet:
        """Job 1 — ``kernel_bias_join``."""
        width = self._flat_width(channels)
        scan = ScanSet(self.db, "kernels")
        chunks = MultiApply(scan, self.kernel_to_chunks, label="KernelToChunks")
        matrix = self.blocks_to_matrix(self.chunks_to_blocks(chunks),
                                       num_filters, width)
        bias = ScanSet(self.db, "bias")

        def bias_join(kmat: BlockedTensor, bias_items) -> BlockedTensor:
            dense = np.array(kmat.to_dense())
            b = np.asarray(bias_items[0], np.float32)
            dense[:len(b), width - 1] = b
            return BlockedTensor.from_dense(dense, self.block)

        joined = Join(matrix, bias, fn=bias_join, label="KernelBiasJoin")
        return WriteSet(joined, self.db, "kernel_flat")

    def build_image_flat(self, channels: int, total_windows: int) -> WriteSet:
        """Job 2 — ``image_ops``."""
        width = self._flat_width(channels)
        scan = ScanSet(self.db, "images")
        chunks = MultiApply(scan, self.image_to_chunks, label="ImageToChunks")
        matrix = self.blocks_to_matrix(self.chunks_to_blocks(chunks),
                                       total_windows, width)
        return WriteSet(matrix, self.db, "image_flat")

    def build_conv(self) -> WriteSet:
        """Job 3 — ``conv2d``: FFTransposeMult ⋈ + FFAggMatrix Σ. The
        join-on-contraction-block-index + block-product aggregation is one
        ``dot_general`` on the MXU (SURVEY §2.6 relational-SUMMA row)."""
        image_flat = ScanSet(self.db, "image_flat")
        kernel_flat = ScanSet(self.db, "kernel_flat")
        prod = Join(image_flat, kernel_flat,
                    fn=lambda a, b: matmul_t(
                        a, b, compute_dtype=self.compute_dtype),
                    label="FFTransposeMult+FFAggMatrix")
        return WriteSet(prod, self.db, "result")

    def build_reassemble(self, out_h: int, out_w: int,
                         num_filters: int) -> WriteSet:
        """Job 4 — ``ConvResultToChunks`` + ``ConvChunksToImage``: rows of
        the result matrix regrouped per image into (O, out_h, out_w)."""
        result = ScanSet(self.db, "result")
        windows = out_h * out_w

        def to_images(res: BlockedTensor) -> List[Image]:
            dense = np.asarray(res.to_dense())[:, :num_filters]
            n = dense.shape[0] // windows
            return [Image(i, dense[i * windows:(i + 1) * windows]
                          .reshape(out_h, out_w, num_filters)
                          .transpose(2, 0, 1))
                    for i in range(n)]

        images = Apply(result, to_images, label="ConvChunksToImage",
                       traceable=False)
        return WriteSet(images, self.db, "output")

    # -- driver ---------------------------------------------------------

    def run(self, client: Client, images: np.ndarray, kernels: np.ndarray,
            bias: Optional[np.ndarray] = None) -> List[Image]:
        """The full staged pipeline, one ``execute_computations`` per
        reference job (same materialization boundaries)."""
        images = np.asarray(images, np.float32)
        kernels = np.asarray(kernels, np.float32)
        n, c, h, w = images.shape
        o = kernels.shape[0]
        k, s, p = self.kernel_size, self.stride, self.padding
        oh = (h + 2 * p - k) // s + 1
        ow = (w + 2 * p - k) // s + 1

        self.setup(client)
        self.load(client, images, kernels, bias)
        client.execute_computations(self.build_kernel_flat(c, o),
                                    job_name=f"{self.db}-kernel_bias_join")
        client.execute_computations(self.build_image_flat(c, n * oh * ow),
                                    job_name=f"{self.db}-image_ops")
        client.execute_computations(self.build_conv(),
                                    job_name=f"{self.db}-conv2d")
        client.execute_computations(self.build_reassemble(oh, ow, o),
                                    job_name=f"{self.db}-reassemble")
        return list(client.get_set_iterator(self.db, "output"))
