"""Runtime micro-benchmarks — the reference's serviceBenchmarks family.

``src/serviceBenchmarks/source`` times four substrate pieces in
isolation: allocator throughput (``AllocationTest.cc``), int- and
string-keyed hash-map inserts under different allocators
(``HashMapTest.cc``, ``StringHashMapTest.cc``), and the shuffle write
path (``ShuffleTest.cc``). These exist to size the runtime's building
blocks, not the queries. The equivalents here time OUR building blocks:
the native arena (pagestore), host group-by (what hash aggregation
became), device segment-sum (what keyed aggregation becomes on TPU),
and the all-to-all resharding collective (what the shuffle became).

Each benchmark returns ``(ops, seconds, ops_per_sec)``; ``run_all``
prints one line per benchmark. Used by the CLI (``micro-bench``
subcommand) and smoke-tested in ``tests/test_micro_bench.py``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import numpy as np

Result = Tuple[int, float, float]


def _timed(n_ops: int, fn: Callable[[], None]) -> Result:
    t0 = time.perf_counter()
    fn()
    dt = max(time.perf_counter() - t0, 1e-9)
    return n_ops, dt, n_ops / dt


def bench_arena_alloc(n: int = 20_000, size: int = 4096,
                      pool_mb: int = 64) -> Result:
    """Native arena page write/free churn — ``AllocationTest.cc`` /
    ``SlabAllocator`` role. Falls back to a host bytearray pool if the
    native library is unavailable."""
    import tempfile

    from netsdb_tpu.native.pagestore import NativePageStore, native_available

    payload = bytes(size)
    if native_available():
        with tempfile.TemporaryDirectory() as d:
            store = NativePageStore(pool_bytes=pool_mb << 20, spill_dir=d)
            store.create_set(1)

            def run():
                live: List[int] = []
                for i in range(n):
                    live.append(store.write_page(1, payload))
                    if len(live) > 64:  # bounded live set → free-list churn
                        store.free_page(live.pop(0))
                for h in live:
                    store.free_page(h)

            res = _timed(n, run)
            store.close()
            return res

    def run():
        live: List[bytearray] = []
        for i in range(n):
            live.append(bytearray(size))
            if len(live) > 64:
                live.pop(0)

    return _timed(n, run)


def bench_int_groupby(n: int = 1_000_000, keys: int = 10_000) -> Result:
    """Int-keyed hash aggregation on the host — ``HashMapTest.cc``'s
    unordered_map insert loop (what CombinerProcessor did per page)."""
    ks = np.random.default_rng(0).integers(0, keys, n).tolist()

    def run():
        acc: Dict[int, int] = {}
        for k in ks:
            acc[k] = acc.get(k, 0) + 1

    return _timed(n, run)


def bench_string_groupby(n: int = 300_000, keys: int = 10_000) -> Result:
    """String-keyed variant — ``StringHashMapTest.cc``."""
    ks = [str(x) for x in
          np.random.default_rng(1).integers(0, keys, n).tolist()]

    def run():
        acc: Dict[str, int] = {}
        for k in ks:
            acc[k] = acc.get(k, 0) + 1

    return _timed(n, run)


def bench_segment_sum(n: int = 1_000_000, keys: int = 10_000) -> Result:
    """The same keyed aggregation where it actually runs in this
    framework: ``jax.ops.segment_sum`` on the device — the TPU path
    that replaces the host hash map for tensor aggregations."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    seg = jnp.asarray(rng.integers(0, keys, n))
    val = jnp.asarray(rng.standard_normal(n), jnp.float32)
    f = jax.jit(lambda s, v: jax.ops.segment_sum(v, s, num_segments=keys))
    float(jnp.sum(f(seg, val)))  # compile + sync

    def run():
        float(jnp.sum(f(seg, val)))

    return _timed(n, run)


def bench_shuffle(elems_per_dev: int = 1 << 16) -> Result:
    """All-to-all resharding over the device mesh — ``ShuffleTest.cc``'s
    role (the ShuffleSink/combiner/snappy/TCP path became one XLA
    collective)."""
    import jax
    import jax.numpy as jnp

    from netsdb_tpu.parallel.collectives import all_to_all_resharding
    from netsdb_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev,), ("data",))
    # (n_dev, elems) sharded on dim 0 → resharded onto dim 1
    x = jnp.arange(n_dev * elems_per_dev, dtype=jnp.float32
                   ).reshape(n_dev, elems_per_dev)
    f = jax.jit(lambda v: all_to_all_resharding(v, mesh, "data",
                                                from_dim=0, to_dim=1))
    float(jnp.sum(f(x)))  # compile + sync
    total = n_dev * elems_per_dev

    def run():
        float(jnp.sum(f(x)))

    return _timed(total, run)


def bench_planner(n: int = 2_000) -> Result:
    """Plan build + textual dump + re-parse round-trips on a
    selection⋈join DAG — the reference's ``src/optimizerBenchmark``
    (MovieStar⋈StarsIn TCAP generation/optimization experiments). Times
    the planner substrate itself, not query execution."""
    from netsdb_tpu.plan.computations import (Aggregate, Filter, Join,
                                              ScanSet, WriteSet)
    from netsdb_tpu.plan.parser import parse_plan
    from netsdb_tpu.plan.planner import plan_from_sinks

    def build():
        movies = ScanSet("mdb", "movies")
        stars = ScanSet("mdb", "starsin")
        sel = Filter(movies, lambda m: True, label="SimpleMovieSelection")
        j = Join(sel, stars, left_key=lambda m: m["title"],
                 right_key=lambda s: s["movie"], label="SimpleMovieJoin")
        agg = Aggregate(j, key=lambda p: p[0]["title"], value=lambda p: 1,
                        combine=lambda a, b: a + b, label="countStars")
        return WriteSet(agg, "mdb", "out")

    def run():
        for _ in range(n):
            plan = plan_from_sinks([build()])
            parse_plan(plan.to_plan_string())

    return _timed(n, run)


BENCHMARKS: Dict[str, Callable[[], Result]] = {
    "arena_alloc": bench_arena_alloc,
    "int_groupby": bench_int_groupby,
    "string_groupby": bench_string_groupby,
    "segment_sum": bench_segment_sum,
    "shuffle": bench_shuffle,
    "planner": bench_planner,
}


def run_all(names=None, out=print) -> Dict[str, Result]:
    results = {}
    for name in (names or BENCHMARKS):
        ops, secs, rate = BENCHMARKS[name]()
        results[name] = (ops, secs, rate)
        out(f"{name}: {ops} ops in {secs:.3f}s = {rate:,.0f} ops/s")
    return results
