"""Runtime micro-benchmarks — the reference's serviceBenchmarks family.

``src/serviceBenchmarks/source`` times four substrate pieces in
isolation: allocator throughput (``AllocationTest.cc``), int- and
string-keyed hash-map inserts under different allocators
(``HashMapTest.cc``, ``StringHashMapTest.cc``), and the shuffle write
path (``ShuffleTest.cc``). These exist to size the runtime's building
blocks, not the queries. The equivalents here time OUR building blocks:
the native arena (pagestore), host group-by (what hash aggregation
became), device segment-sum (what keyed aggregation becomes on TPU),
and the all-to-all resharding collective (what the shuffle became).

Each benchmark returns ``(ops, seconds, ops_per_sec)``; ``run_all``
prints one line per benchmark. Used by the CLI (``micro-bench``
subcommand) and smoke-tested in ``tests/test_micro_bench.py``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Tuple

import numpy as np

Result = Tuple[int, float, float]


def _timed(n_ops: int, fn: Callable[[], None]) -> Result:
    t0 = time.perf_counter()
    fn()
    dt = max(time.perf_counter() - t0, 1e-9)
    return n_ops, dt, n_ops / dt


def bench_arena_alloc(n: int = 20_000, size: int = 4096,
                      pool_mb: int = 64) -> Result:
    """Native arena page write/free churn — ``AllocationTest.cc`` /
    ``SlabAllocator`` role. Falls back to a host bytearray pool if the
    native library is unavailable."""
    import tempfile

    from netsdb_tpu.native.pagestore import NativePageStore, native_available

    payload = bytes(size)
    if native_available():
        with tempfile.TemporaryDirectory() as d:
            store = NativePageStore(pool_bytes=pool_mb << 20, spill_dir=d)
            store.create_set(1)

            def run():
                live: List[int] = []
                for i in range(n):
                    live.append(store.write_page(1, payload))
                    if len(live) > 64:  # bounded live set → free-list churn
                        store.free_page(live.pop(0))
                for h in live:
                    store.free_page(h)

            res = _timed(n, run)
            store.close()
            return res

    def run():
        live: List[bytearray] = []
        for i in range(n):
            live.append(bytearray(size))
            if len(live) > 64:
                live.pop(0)

    return _timed(n, run)


def bench_int_groupby(n: int = 1_000_000, keys: int = 10_000) -> Result:
    """Int-keyed hash aggregation on the host — ``HashMapTest.cc``'s
    unordered_map insert loop (what CombinerProcessor did per page)."""
    ks = np.random.default_rng(0).integers(0, keys, n).tolist()

    def run():
        acc: Dict[int, int] = {}
        for k in ks:
            acc[k] = acc.get(k, 0) + 1

    return _timed(n, run)


def bench_string_groupby(n: int = 300_000, keys: int = 10_000) -> Result:
    """String-keyed variant — ``StringHashMapTest.cc``."""
    ks = [str(x) for x in
          np.random.default_rng(1).integers(0, keys, n).tolist()]

    def run():
        acc: Dict[str, int] = {}
        for k in ks:
            acc[k] = acc.get(k, 0) + 1

    return _timed(n, run)


def bench_segment_sum(n: int = 1_000_000, keys: int = 10_000) -> Result:
    """The same keyed aggregation where it actually runs in this
    framework: ``jax.ops.segment_sum`` on the device — the TPU path
    that replaces the host hash map for tensor aggregations."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    seg = jnp.asarray(rng.integers(0, keys, n))
    val = jnp.asarray(rng.standard_normal(n), jnp.float32)
    f = jax.jit(lambda s, v: jax.ops.segment_sum(v, s, num_segments=keys))
    float(jnp.sum(f(seg, val)))  # compile + sync

    def run():
        float(jnp.sum(f(seg, val)))

    return _timed(n, run)


def bench_shuffle(elems_per_dev: int = 1 << 16) -> Result:
    """All-to-all resharding over the device mesh — ``ShuffleTest.cc``'s
    role (the ShuffleSink/combiner/snappy/TCP path became one XLA
    collective)."""
    import jax
    import jax.numpy as jnp

    from netsdb_tpu.parallel.collectives import all_to_all_resharding
    from netsdb_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev,), ("data",))
    # (n_dev, elems) sharded on dim 0 → resharded onto dim 1
    x = jnp.arange(n_dev * elems_per_dev, dtype=jnp.float32
                   ).reshape(n_dev, elems_per_dev)
    f = jax.jit(lambda v: all_to_all_resharding(v, mesh, "data",
                                                from_dim=0, to_dim=1))
    float(jnp.sum(f(x)))  # compile + sync
    total = n_dev * elems_per_dev

    def run():
        float(jnp.sum(f(x)))

    return _timed(total, run)


def bench_planner(n: int = 2_000) -> Result:
    """Plan build + textual dump + re-parse round-trips on a
    selection⋈join DAG — the reference's ``src/optimizerBenchmark``
    (MovieStar⋈StarsIn TCAP generation/optimization experiments). Times
    the planner substrate itself, not query execution."""
    from netsdb_tpu.plan.computations import (Aggregate, Filter, Join,
                                              ScanSet, WriteSet)
    from netsdb_tpu.plan.parser import parse_plan
    from netsdb_tpu.plan.planner import plan_from_sinks

    def build():
        movies = ScanSet("mdb", "movies")
        stars = ScanSet("mdb", "starsin")
        sel = Filter(movies, lambda m: True, label="SimpleMovieSelection")
        j = Join(sel, stars, left_key=lambda m: m["title"],
                 right_key=lambda s: s["movie"], label="SimpleMovieJoin")
        agg = Aggregate(j, key=lambda p: p[0]["title"], value=lambda p: 1,
                        combine=lambda a, b: a + b, label="countStars")
        return WriteSet(agg, "mdb", "out")

    def run():
        for _ in range(n):
            plan = plan_from_sinks([build()])
            parse_plan(plan.to_plan_string())

    return _timed(n, run)


def bench_staging(rows: int = 65_536, cols: int = 1024,
                  rhs_cols: int = 256, page_rows: int = 4096,
                  pool_mb: int = 32, fold_rows: int = 2_000_000,
                  repeats: int = 3) -> Dict[str, object]:
    """Overlapped vs synchronous device staging on the two out-of-core
    hot paths (the ``--staging`` mode of the CLI):

    * **blocked matmul** — ``PagedTensorStore.matmul_streamed`` with
      the matrix spilling (pool < matrix), ``stage_depth=0`` (every
      ``device_put`` synchronous, prefetch off — the pre-staging
      executor) vs the configured staged pipeline (host read-ahead +
      background device stage). Warms the compile once, then times the
      best of ``repeats`` runs — pure steady-state overlap.
    * **fold stream** — a masked segment-sum fold over a sequence of
      paged relations with differing row counts. DELIBERATELY timed
      cold per run (a fresh ``jax.jit`` per configuration, like a
      fresh daemon's step cache): the exact-shape baseline re-traces
      once per ingest size inside the timed region while the bucketed
      path traces once — recompile churn is the cost being measured,
      alongside the staging overlap. Best of ``repeats`` whole rounds.

    ``*_speedup`` is sync/staged."""
    import shutil
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.storage.paged import PagedTensorStore

    rng = np.random.default_rng(0)
    root = tempfile.mkdtemp(prefix="staging_bench_")
    out: Dict[str, object] = {"rows": rows, "cols": cols,
                              "rhs_cols": rhs_cols,
                              "fold_rows": fold_rows}
    cfg = Configuration(root_dir=root,
                        page_size_bytes=page_rows * cols * 4)
    store = PagedTensorStore(cfg, pool_bytes=pool_mb << 20)
    try:
        m = rng.standard_normal((rows, cols)).astype(np.float32)
        rhs = rng.standard_normal((cols, rhs_cols)).astype(np.float32)
        store.put("m", m, row_block=page_rows)
        out["matrix_mb"] = m.nbytes >> 20
        out["pool_mb"] = pool_mb
        del m

        def timed_mm(depth: int, prefetch: int) -> float:
            cfg.stream_prefetch_pages = prefetch
            store.matmul_streamed("m", rhs, stage_depth=depth)  # warm
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                store.matmul_streamed("m", rhs, stage_depth=depth)
                best = min(best, time.perf_counter() - t0)
            return best

        out["matmul_sync_s"] = round(timed_mm(0, 0), 4)
        out["matmul_staged_s"] = round(timed_mm(2, 2), 4)
        out["matmul_speedup"] = round(
            out["matmul_sync_s"] / out["matmul_staged_s"], 2)

        # --- fold stream: a q01-shaped multi-aggregate chunk step
        # (five weighted segment-sums + a count) folded over a SEQUENCE
        # of paged relations with DIFFERING row counts — the serve
        # scenario the shape buckets exist for: every `EXECUTE` over a
        # freshly ingested set used to present a new chunk shape to the
        # one cached step (row_block = min(row_block, num_rows)), so
        # the old pipeline recompiled per ingest size while the device
        # idled through every synchronous upload. The baseline runs
        # with bucketing off + stage/prefetch 0 (the pre-staging
        # executor); the staged run with the defaults. ``*_traces``
        # reports how many times XLA traced the shared step — the
        # recompile-churn metric (bucketed: constant; exact shapes:
        # one per distinct row count).
        n_keys = 4096
        from netsdb_tpu.plan.staging import bucket_rows

        # 12 distinct ingest sizes spread ±8% around a base chosen so
        # they all land in ONE bucket (the common serve case: traffic
        # varies around a working size) — the exact-shape baseline
        # traces once PER SIZE, the bucketed path once total
        base = int(fold_rows * 0.1125)
        bucket = bucket_rows(base)
        sizes = sorted({min(int(base * (0.92 + 0.15 * i / 11)), bucket)
                        for i in range(12)})
        rels = []
        for i, n in enumerate(sizes):
            fc = {
                "k": rng.integers(0, n_keys, n, dtype=np.int32),
                "qty": rng.uniform(1.0, 50.0, n).astype(np.float32),
                "price": rng.uniform(1.0, 100.0, n).astype(np.float32),
                "disc": rng.uniform(0.0, 0.1, n).astype(np.float32),
                "tax": rng.uniform(0.0, 0.08, n).astype(np.float32),
            }
            rels.append(PagedColumns.ingest(store, f"fold{i}", fc))
        out["fold_sizes"] = sizes

        def timed_fold(bucketing: bool, depth: int,
                       prefetch: int) -> Tuple[float, int]:
            import contextlib

            cfg.shape_bucketing = bucketing
            cfg.stage_depth = depth
            cfg.stream_prefetch_pages = prefetch
            traces = [0]

            def raw_step(acc, k, qty, price, disc, tax, valid):
                traces[0] += 1  # body runs only when XLA (re)traces
                seg = jnp.where(valid, k, 0)
                rev = price * (1.0 - disc)
                vals = jnp.stack([qty, price, rev, rev * (1.0 + tax),
                                  disc, jnp.ones_like(price)], axis=1)
                vals = jnp.where(valid[:, None], vals, 0.0)
                return acc + jax.ops.segment_sum(vals, seg,
                                                 num_segments=n_keys)

            step = jax.jit(raw_step)  # ONE cached step, like the
            # executor's _cached_jit across serve EXECUTEs
            t0 = time.perf_counter()
            for pc in rels:
                acc = jnp.zeros((n_keys, 6), jnp.float32)
                with contextlib.closing(pc.stream()) as chunks:
                    for ccols, valid, _start in chunks:
                        acc = step(acc, ccols["k"], ccols["qty"],
                                   ccols["price"], ccols["disc"],
                                   ccols["tax"], valid)
                np.asarray(acc)
            return time.perf_counter() - t0, traces[0]

        best_sync, best_staged = float("inf"), float("inf")
        for _ in range(repeats):
            s, tr_s = timed_fold(False, 0, 0)
            g, tr_g = timed_fold(True, 2, 2)
            best_sync, best_staged = min(best_sync, s), min(best_staged, g)
        out["fold_sync_s"] = round(best_sync, 4)
        out["fold_staged_s"] = round(best_staged, 4)
        out["fold_sync_traces"] = tr_s
        out["fold_staged_traces"] = tr_g
        out["fold_speedup"] = round(
            out["fold_sync_s"] / out["fold_staged_s"], 2)
        out["store_stats"] = store.stats()
        out["native"] = store.native
    finally:
        store.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_bucket_sweep(base: int = 45_000, spread: float = 0.6,
                       samples: int = 48, seed: int = 0,
                       densities: Tuple[int, ...] = (2, 4)
                       ) -> Dict[str, object]:
    """Pad-waste vs trace-count per shape-ladder density — the ROADMAP
    bucket-ladder tuning item, runnable as ``micro-bench
    --bucket-sweep``.

    Draws ``samples`` serve-style ingest sizes log-uniformly across
    ±``spread`` octaves around ``base`` (traffic varying around a
    working size — the scenario the buckets exist for), then for each
    ``bucket_density``:

    * **pad_waste_pct** — padded rows beyond the valid rows, as a
      fraction of total valid rows (what every fold step wastes on
      masked lanes);
    * **buckets** — distinct bucket shapes the sizes land in;
    * **traces** — ACTUAL XLA traces of one shared jitted step fed
      each bucketed shape (must equal ``buckets``: one compile per
      bucket, the cost a denser ladder pays for its smaller pad).

    Density 2 is the default ladder {2^k, 3·2^(k-1)}; density 4 adds
    the 1.25×/1.75× rungs (<25% worst-case pad, ~2× the compiles)."""
    import jax
    import jax.numpy as jnp

    from netsdb_tpu.plan.staging import bucket_rows

    rng = np.random.default_rng(seed)
    sizes = sorted(int(base * (2.0 ** e)) for e in
                   rng.uniform(-spread, spread, samples))
    out: Dict[str, object] = {"base": base, "samples": samples,
                              "spread_octaves": spread,
                              "size_min": sizes[0], "size_max": sizes[-1]}
    for d in densities:
        buckets = [bucket_rows(n, d) for n in sizes]
        valid = sum(sizes)
        padded = sum(buckets)
        distinct = sorted(set(buckets))
        traces = [0]

        def step(x):
            traces[0] += 1  # body runs only when XLA (re)traces
            return jnp.sum(x)

        jstep = jax.jit(step)
        for b in buckets:
            # tiny 1-D probes with the REAL bucketed lengths: the trace
            # count is shape-driven, not data-size-driven
            float(jstep(jnp.zeros((b,), jnp.float32)))
        out[f"density{d}"] = {
            "buckets": len(distinct),
            "traces": traces[0],
            "pad_waste_pct": round(100.0 * (padded - valid) / valid, 2),
            "bucket_shapes": distinct,
        }
    return out


def bench_obs_overhead(rows: int = 2_000_000, page_rows: int = 65_536,
                       repeats: int = 15) -> Dict[str, object]:
    """Cost of always-on query tracing on the staged fold stream — the
    ``--obs-overhead`` mode. Runs the SAME warmed fold (a q01-shaped
    masked segment-sum over a paged relation, chunks staged through
    ``plan/staging.stage_stream``) with no trace installed vs inside
    an ``obs.trace`` (every chunk then pays the span/counter
    accounting: stage wait, bytes staged, devcache ticks).

    Two readings, because shared-CPU scheduling noise (routinely ±20%
    per run) dwarfs a true cost well under 1%:

    * ``overhead_pct``/``noise_pct`` — END-TO-END paired A/B: the arms
      alternate within each repeat, ``overhead_pct`` is the median of
      per-pair deltas over the median untraced time, ``noise_pct`` the
      deltas' IQR. Drift hits both arms of a pair and cancels; an
      overhead within the noise band reads as "indistinguishable from
      zero" (verified against an A/A null run).
    * ``accounting_overhead_pct`` — DETERMINISTIC bound: the exact
      per-chunk accounting a trace adds on the CONSUMER's critical
      path (three trace-counter adds; the chunk byte-count is measured
      on the staging worker where it overlaps compute), timed in
      isolation and scaled to this stream's chunk count. This is the
      number the < 3% budget is pinned on — it cannot be confounded by
      the scheduler.

    ``sampled`` section (this PR's 1-in-N qid minting,
    ``obs.sample_qid`` / ``config.obs_trace_sample``): every request
    pays only the mint DECISION (``sample_qid_us`` — a lock-guarded
    counter increment); the full per-chunk accounting lands on 1 in
    ``sample`` queries, so the amortized deterministic bound is
    ``decision + accounting/sample`` — strictly below the sample=1
    bound whenever sample > 1."""
    import contextlib
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from netsdb_tpu import obs
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.storage.paged import PagedTensorStore

    rng = np.random.default_rng(0)
    n_keys = 4096
    root = tempfile.mkdtemp(prefix="obs_bench_")
    cfg = Configuration(root_dir=root)
    store = PagedTensorStore(cfg, pool_bytes=256 << 20)
    out: Dict[str, object] = {"rows": rows, "page_rows": page_rows,
                              "repeats": repeats}
    try:
        fc = {
            "k": rng.integers(0, n_keys, rows, dtype=np.int32),
            "qty": rng.uniform(1.0, 50.0, rows).astype(np.float32),
            "price": rng.uniform(1.0, 100.0, rows).astype(np.float32),
        }
        pc = PagedColumns.ingest(store, "obsbench", fc,
                                 row_block=page_rows)
        out["chunks"] = pc.num_pages()

        def raw_step(acc, k, qty, price, valid):
            seg = jnp.where(valid, k, 0)
            vals = jnp.stack([qty, price, jnp.ones_like(price)], axis=1)
            vals = jnp.where(valid[:, None], vals, 0.0)
            return acc + jax.ops.segment_sum(vals, seg,
                                             num_segments=n_keys)

        step = jax.jit(raw_step)

        def run_once():
            acc = jnp.zeros((n_keys, 3), jnp.float32)
            with contextlib.closing(pc.stream()) as chunks:
                for ccols, valid, _start in chunks:
                    acc = step(acc, ccols["k"], ccols["qty"],
                               ccols["price"], valid)
            np.asarray(acc)

        run_once()  # compile
        run_once()  # warm the page cache / spill state

        def one(traced: bool) -> float:
            t0 = time.perf_counter()
            if traced:
                with obs.trace(origin="bench"):
                    run_once()
            else:
                run_once()
            return time.perf_counter() - t0

        pairs = []
        for i in range(repeats):
            # alternate which arm runs first within the pair, so a
            # monotone drift (thermal, cache) can't bias the deltas
            if i % 2 == 0:
                u = one(False)
                t = one(True)
            else:
                t = one(True)
                u = one(False)
            pairs.append((u, t))

        def med(vals):
            s = sorted(vals)
            n = len(s)
            return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2

        untraced = med([u for u, _ in pairs])
        deltas = sorted(t - u for u, t in pairs)
        d_med = med(deltas)
        q1 = med(deltas[:len(deltas) // 2 + 1])
        q3 = med(deltas[len(deltas) // 2:])
        out["untraced_s"] = round(untraced, 4)
        out["traced_s"] = round(untraced + d_med, 4)
        out["overhead_pct"] = round(100.0 * d_med / untraced, 2)
        out["noise_pct"] = round(
            100.0 * abs(q3 - q1) / untraced, 2)
        prof = obs.DEFAULT_RING.last(1)  # the last TRACED fold run
        if prof:
            out["trace_counters"] = prof[-1].get("counters", {})

        # deterministic bound: the EXACT accounting StagedStream adds
        # per chunk on the consumer thread under a trace
        # (plan/staging._account — the byte-count itself is measured
        # on the staging worker, overlapped with compute, so it is NOT
        # on this path), isolated from scheduler noise and scaled to
        # this stream's chunk count
        n_acct = 5_000
        trials = []
        with obs.trace(origin="bench") as tr:
            for _ in range(8):  # best-of-trials: the DETERMINISTIC
                # cost is the floor; scheduler preemption only adds
                t0 = time.perf_counter()
                for _ in range(n_acct):
                    tr.add("stage.chunks")
                    tr.add("stage.bytes", 851968)
                    tr.add("stage.wait_s", 1e-4)
                trials.append((time.perf_counter() - t0) / n_acct)
        per_chunk = min(trials)
        out["accounting_us_per_chunk"] = round(per_chunk * 1e6, 3)
        out["accounting_overhead_pct"] = round(
            100.0 * per_chunk * int(out["chunks"]) / untraced, 4)

        # sampled minting (obs.sample_qid, config.obs_trace_sample):
        # the per-request decision cost every query pays, then the
        # full accounting amortized over 1-in-N traced queries
        sample = 16
        n_mint = 5_000
        mint_trials = []
        for _ in range(8):
            t0 = time.perf_counter()
            for _ in range(n_mint):
                obs.sample_qid(sample)
            mint_trials.append((time.perf_counter() - t0) / n_mint)
        decision_s = min(mint_trials)
        acct_s = per_chunk * int(out["chunks"])
        out["sampled"] = {
            "sample": sample,
            "sample_qid_us": round(decision_s * 1e6, 3),
            # deterministic amortized bounds per query, by sample rate
            "accounting_overhead_pct_sample1": round(
                100.0 * (decision_s + acct_s) / untraced, 4),
            f"accounting_overhead_pct_sample{sample}": round(
                100.0 * (decision_s + acct_s / sample) / untraced, 4),
        }
    finally:
        store.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_explain_overhead(rows: int = 2_000_000,
                           page_rows: int = 65_536,
                           repeats: int = 15) -> Dict[str, object]:
    """Cost of PER-NODE attribution (obs/operators.py) on the staged
    fold stream — the ``--explain-overhead`` mode, structured exactly
    like ``--obs-overhead``: the same warmed q01-shaped fold runs with
    an operator record installed (every staged chunk then ticks
    chunk/byte/wait counters on the current node — the explain-on arm)
    vs bare (explain off).

    * ``overhead_pct``/``noise_pct`` — END-TO-END paired A/B, arms
      alternating within each repeat so drift cancels;
    * ``accounting_overhead_pct`` — DETERMINISTIC bound: the exact
      three ``OpRecord.add`` calls ``plan/staging._account`` pays per
      chunk with an op captured, timed in isolation and scaled to this
      stream's chunk count. The < 1% budget is pinned on this number.
    * ``off_path_ns`` — what EVERY uninstrumented query pays per
      ``op_add`` call when no recorder is installed: one context-var
      read + an ``is None`` check (the "~0 when off" claim)."""
    import contextlib
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from netsdb_tpu import obs
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.storage.paged import PagedTensorStore

    rng = np.random.default_rng(0)
    n_keys = 4096
    root = tempfile.mkdtemp(prefix="explain_bench_")
    cfg = Configuration(root_dir=root)
    store = PagedTensorStore(cfg, pool_bytes=256 << 20)
    out: Dict[str, object] = {"rows": rows, "page_rows": page_rows,
                              "repeats": repeats}

    class _BenchNode:
        op_kind = "Apply"
        label = "explain-bench"

        def plan_atom(self):
            return "bench <= APPLY(scan, 'explain-bench')"

    try:
        fc = {
            "k": rng.integers(0, n_keys, rows, dtype=np.int32),
            "qty": rng.uniform(1.0, 50.0, rows).astype(np.float32),
            "price": rng.uniform(1.0, 100.0, rows).astype(np.float32),
        }
        pc = PagedColumns.ingest(store, "explbench", fc,
                                 row_block=page_rows)
        out["chunks"] = pc.num_pages()

        def raw_step(acc, k, qty, price, valid):
            seg = jnp.where(valid, k, 0)
            vals = jnp.stack([qty, price, jnp.ones_like(price)], axis=1)
            vals = jnp.where(valid[:, None], vals, 0.0)
            return acc + jax.ops.segment_sum(vals, seg,
                                             num_segments=n_keys)

        step = jax.jit(raw_step)

        def run_once():
            acc = jnp.zeros((n_keys, 3), jnp.float32)
            with contextlib.closing(pc.stream()) as chunks:
                for ccols, valid, _start in chunks:
                    acc = step(acc, ccols["k"], ccols["qty"],
                               ccols["price"], valid)
            np.asarray(acc)

        run_once()  # compile
        run_once()  # warm the page cache / spill state

        def one(explained: bool) -> float:
            t0 = time.perf_counter()
            if explained:
                rec = obs.operators.OperatorRecorder("explain-bench")
                with rec.op(0, _BenchNode(), []):
                    run_once()
            else:
                run_once()
            return time.perf_counter() - t0

        pairs = []
        for i in range(repeats):
            if i % 2 == 0:
                off = one(False)
                on = one(True)
            else:
                on = one(True)
                off = one(False)
            pairs.append((off, on))

        def med(vals):
            s = sorted(vals)
            n = len(s)
            return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2

        off_med = med([u for u, _ in pairs])
        deltas = sorted(t - u for u, t in pairs)
        d_med = med(deltas)
        q1 = med(deltas[:len(deltas) // 2 + 1])
        q3 = med(deltas[len(deltas) // 2:])
        out["explain_off_s"] = round(off_med, 4)
        out["explain_on_s"] = round(off_med + d_med, 4)
        out["overhead_pct"] = round(100.0 * d_med / off_med, 2)
        out["noise_pct"] = round(100.0 * abs(q3 - q1) / off_med, 2)

        # deterministic bound: the exact per-chunk op ticks
        # staging._account adds with an op record captured
        n_acct = 5_000
        trials = []
        rec = obs.operators.OperatorRecorder("explain-bench")
        with rec.op(1, _BenchNode(), []) as opr:
            for _ in range(8):
                t0 = time.perf_counter()
                for _ in range(n_acct):
                    opr.add("stage.chunks")
                    opr.add("stage.bytes", 851968)
                    opr.add("stage.wait_s", 1e-4)
                trials.append((time.perf_counter() - t0) / n_acct)
        per_chunk = min(trials)
        out["accounting_us_per_chunk"] = round(per_chunk * 1e6, 3)
        out["accounting_overhead_pct"] = round(
            100.0 * per_chunk * int(out["chunks"]) / off_med, 4)

        # the off path: op_add with NO recorder — one context-var read
        off_trials = []
        for _ in range(8):
            t0 = time.perf_counter()
            for _ in range(n_acct):
                obs.operators.op_add("stage.chunks")
            off_trials.append((time.perf_counter() - t0) / n_acct)
        out["off_path_ns"] = round(min(off_trials) * 1e9, 1)
        out["off_path_overhead_pct"] = round(
            100.0 * min(off_trials) * int(out["chunks"]) / off_med, 6)
    finally:
        store.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_lint_overhead(rows: int = 2_000_000, page_rows: int = 65_536,
                        repeats: int = 15) -> Dict[str, object]:
    """Cost of the runtime lock-order witness on the staged fold
    stream — the ``--lint-overhead`` mode, structured exactly like
    ``--obs-overhead``: the same warmed q01-shaped fold runs with the
    witness installed (every TrackedLock / named-RWLock acquisition
    then pays stack + edge bookkeeping) vs bare.

    * ``overhead_pct``/``noise_pct`` — END-TO-END paired A/B, arms
      alternating within each repeat so drift cancels; the < 2%
      acceptance budget reads against this (and against the
      deterministic bound below, which scheduler noise can't touch).
    * ``accounting_overhead_pct`` — DETERMINISTIC bound: the exact
      enabled-path cost of one acquire+release pair (site capture,
      held-stack push/pop, edge-set consult), timed in isolation and
      scaled by the stream's MEASURED acquisition count.
    * ``off_path_ns`` — what every acquisition pays with the witness
      disabled: one module-global read + an is-None check (the "~0
      when off" claim)."""
    import contextlib
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.storage.paged import PagedTensorStore
    from netsdb_tpu.utils import locks

    rng = np.random.default_rng(0)
    n_keys = 4096
    root = tempfile.mkdtemp(prefix="lint_bench_")
    cfg = Configuration(root_dir=root)
    store = PagedTensorStore(cfg, pool_bytes=256 << 20)
    out: Dict[str, object] = {"rows": rows, "page_rows": page_rows,
                              "repeats": repeats}
    prev_witness = locks.witness()
    locks.disable_witness()
    try:
        fc = {
            "k": rng.integers(0, n_keys, rows, dtype=np.int32),
            "qty": rng.uniform(1.0, 50.0, rows).astype(np.float32),
            "price": rng.uniform(1.0, 100.0, rows).astype(np.float32),
        }
        pc = PagedColumns.ingest(store, "lintbench", fc,
                                 row_block=page_rows)
        out["chunks"] = pc.num_pages()

        def raw_step(acc, k, qty, price, valid):
            seg = jnp.where(valid, k, 0)
            vals = jnp.stack([qty, price, jnp.ones_like(price)], axis=1)
            vals = jnp.where(valid[:, None], vals, 0.0)
            return acc + jax.ops.segment_sum(vals, seg,
                                             num_segments=n_keys)

        step = jax.jit(raw_step)

        def run_once():
            acc = jnp.zeros((n_keys, 3), jnp.float32)
            with contextlib.closing(pc.stream()) as chunks:
                for ccols, valid, _start in chunks:
                    acc = step(acc, ccols["k"], ccols["qty"],
                               ccols["price"], valid)
            np.asarray(acc)

        run_once()  # compile
        run_once()  # warm the page cache / spill state

        def one(witnessed: bool) -> float:
            if witnessed:
                with locks.witness_scope():
                    t0 = time.perf_counter()
                    run_once()
                    return time.perf_counter() - t0
            t0 = time.perf_counter()
            run_once()
            return time.perf_counter() - t0

        pairs = []
        for i in range(repeats):
            if i % 2 == 0:
                u = one(False)
                t = one(True)
            else:
                t = one(True)
                u = one(False)
            pairs.append((u, t))

        def med(vals):
            s = sorted(vals)
            n = len(s)
            return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2

        off_med = med([u for u, _ in pairs])
        deltas = sorted(t - u for u, t in pairs)
        d_med = med(deltas)
        q1 = med(deltas[:len(deltas) // 2 + 1])
        q3 = med(deltas[len(deltas) // 2:])
        out["witness_off_s"] = round(off_med, 4)
        out["witness_on_s"] = round(off_med + d_med, 4)
        out["overhead_pct"] = round(100.0 * d_med / off_med, 2)
        out["noise_pct"] = round(100.0 * abs(q3 - q1) / off_med, 2)

        # the stream's tracked-acquisition count (one witnessed run)
        with locks.witness_scope() as w:
            run_once()
            out["acquisitions_per_run"] = int(w.report()["acquisitions"])
            out["rank_edges"] = int(w.report()["edges"])

        # deterministic bound: one enabled acquire+release pair in
        # isolation (a held outer lock so the edge path runs), scaled
        # by the measured acquisition count
        n_acct = 5_000
        trials = []
        with locks.witness_scope():
            outer = locks.TrackedLock("lintbench.outer")
            inner = locks.TrackedLock("lintbench.inner")
            with outer:
                for _ in range(8):
                    t0 = time.perf_counter()
                    for _ in range(n_acct):
                        with inner:
                            pass
                    trials.append((time.perf_counter() - t0) / n_acct)
        per_acq = min(trials)
        out["enabled_us_per_acquire"] = round(per_acq * 1e6, 3)
        out["accounting_overhead_pct"] = round(
            100.0 * per_acq * int(out["acquisitions_per_run"])
            / off_med, 4)

        # the off path: the same pair with NO witness installed, minus
        # the raw threading.Lock floor = the is-None check cost
        bare = threading.Lock()
        off_trials, floor_trials = [], []
        probe = locks.TrackedLock("lintbench.off")
        for _ in range(8):
            t0 = time.perf_counter()
            for _ in range(n_acct):
                with probe:
                    pass
            off_trials.append((time.perf_counter() - t0) / n_acct)
            t0 = time.perf_counter()
            for _ in range(n_acct):
                with bare:
                    pass
            floor_trials.append((time.perf_counter() - t0) / n_acct)
        off_ns = max(0.0, (min(off_trials) - min(floor_trials)) * 1e9)
        out["off_path_ns"] = round(off_ns, 1)
        out["off_path_overhead_pct"] = round(
            100.0 * (off_ns / 1e9)
            * int(out["acquisitions_per_run"]) / off_med, 6)
    finally:
        if prev_witness is not None:
            locks._WITNESS = prev_witness
        store.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_fusion(spine: int = 12, dim_rows: int = 65_536,
                 fact_rows: int = 8_000, fold_rows: int = 2_000_000,
                 page_rows: int = 65_536, repeats: int = 9,
                 inner: int = 3) -> Dict[str, object]:
    """Fusion-aware plan compilation paired A/B — the ``--fusion``
    mode (ISSUE 11 acceptance bench). Two workloads, each executed
    through the REAL executor with ``plan_fusion`` on vs off (arms
    alternating within every repeat so machine drift cancels; best-of
    medians like the other paired benches):

    * **resident spine** (``plan_fusion_speedup``, the headline) — a
      TPC-H-style mixed plan: a small paged q06 fold joined against a
      ``spine``-node traceable Apply chain over a resident dimension
      table. Per-node, the spine pays ``spine+1`` jit dispatches and
      cache entries per execution; fused it is ONE region program
      (``N nodes → 1``, pinned by the reported trace counts).
    * **staged fold stream** (``fold_stream_speedup``) — a 2M-row
      paged fact scanned through a declared-``rowwise`` chunk
      transform into a segment-sum fold with a 2-node traceable
      epilogue. Per-node, the transform DEMOTES the whole set to a
      host table (the materialization fusion deletes); fused, the
      chunk is transformed and reduced in one compiled step and the
      epilogue is one program over the merged state.

    Numbers from a CPU container measure dispatch/materialization
    overhead, not TPU compute overlap — same caveat as BENCH_r06."""
    import contextlib as _ctx
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.plan import executor
    from netsdb_tpu.plan.computations import (Apply, Join, ScanSet,
                                              WriteSet)
    from netsdb_tpu.plan.fold import single_pass
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable

    del _ctx  # imported for parity with sibling benches; unused
    rng = np.random.default_rng(0)
    root = tempfile.mkdtemp(prefix="fusion_bench_")
    out: Dict[str, object] = {"spine_nodes": spine,
                              "fact_rows": fact_rows,
                              "fold_rows": fold_rows,
                              "repeats": repeats}
    # devcache OFF: the A/B measures the two COMPILATION strategies on
    # the cold-serve path (every execution re-streams or
    # re-materializes) — with the cache on, both arms would mostly
    # measure warm cache replay instead of the executor
    cfg = Configuration(root_dir=root, fusion_cost_source="static",
                        device_cache_bytes=0)
    c = Client(cfg)
    try:
        c.create_database("fz")
        c.create_set("fz", "lineitem", type_name="table",
                     storage="paged")
        c.send_table("fz", "lineitem", ColumnTable({
            "l_shipdate": rng.integers(19940101, 19950101, fact_rows,
                                       dtype=np.int32),
            "l_discount": np.full(fact_rows, 0.06, np.float32),
            "l_quantity": np.full(fact_rows, 10.0, np.float32),
            "l_extendedprice": rng.uniform(1000, 2000, fact_rows
                                           ).astype(np.float32)}, {}))
        c.create_set("fz", "dim", type_name="table")
        c.send_table("fz", "dim", ColumnTable(
            {"x": rng.standard_normal(dim_rows).astype(np.float32)}, {}))

        def spine_sink():
            node = ScanSet("fz", "dim")
            for i in range(spine):
                node = Apply(node, lambda t, _i=i: ColumnTable(
                    {"x": t["x"] * (1.0 + 1e-7 * _i) + 1e-6},
                    t.dicts, t.valid), label=f"spine{i}")
            z = Apply(node, lambda t: jnp.sum(t["x"]) * 1e-9,
                      label="zsum")
            q06 = rdag.q06_sink("fz")
            j = Join(q06.inputs[0], z, fn=lambda rev, v: ColumnTable(
                {"revenue": rev["revenue"] + v}, rev.dicts, rev.valid),
                label="combine")
            return WriteSet(j, "fz", "spine_out")

        def run_spine_once():
            # ``inner`` serve-style executions per timed sample: the
            # per-execution dispatch overhead is the measurand and a
            # single ~5 ms execution sits inside scheduler noise
            for _ in range(inner):
                res = c.execute_computations(spine_sink(),
                                             job_name="fusion-spine",
                                             materialize=False)
                jax.block_until_ready(
                    next(iter(res.values()))["revenue"])

        def med(vals):
            s = sorted(vals)
            n = len(s)
            return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2

        def paired(run_once) -> Dict[str, float]:
            # cold compiles per arm (unrecorded), then alternating
            # timed pairs — trace counts read off compile_stats deltas
            stats = {}
            for arm, fused in (("fused", True), ("per_node", False)):
                cfg.plan_fusion = fused
                t0 = executor.compile_stats()
                run_once()
                t1 = executor.compile_stats()
                stats[f"{arm}_traces"] = t1["traces"] - t0["traces"]
                stats[f"{arm}_programs"] = t1["misses"] - t0["misses"]
            pairs = []
            for i in range(repeats):
                order = ((True, False) if i % 2 == 0 else (False, True))
                tm = {}
                for fused in order:
                    cfg.plan_fusion = fused
                    t0 = time.perf_counter()
                    run_once()
                    tm[fused] = time.perf_counter() - t0
                pairs.append(tm)
            on = med([p[True] for p in pairs])
            off = med([p[False] for p in pairs])
            stats["fused_s"] = round(on, 4)
            stats["per_node_s"] = round(off, 4)
            stats["speedup"] = round(off / on, 2)
            return stats

        out["spine"] = paired(run_spine_once)
        out["plan_fusion_speedup"] = out["spine"]["speedup"]

        # --- 2M-row staged fold stream with rowwise pre + epilogue --
        nk = 4096
        c.create_set("fz", "fact", type_name="table", storage="paged")
        c.send_table("fz", "fact", ColumnTable({
            "k": rng.integers(0, nk, fold_rows, dtype=np.int32),
            "v": rng.uniform(0.0, 10.0, fold_rows
                             ).astype(np.float32)}, {}))

        def fold_sink():
            s = ScanSet("fz", "fact")
            pre = Apply(s, lambda t: ColumnTable(
                {"k": t["k"], "v": t["v"] * 1.5 + 0.25},
                t.dicts, t.valid), label="pre:affine")
            # rowwise derives from the label: "pre:affine" is in the
            # audited ROWWISE_SAFE_LABELS registry (a manual
            # rowwise=True here would trip the rowwise-shadow rule)

            def init(prev, src):
                return jnp.zeros((nk,), jnp.float32)

            def step(state, chunk):
                seg = jnp.where(chunk.mask(), chunk["k"], 0)
                vals = jnp.where(chunk.mask(), chunk["v"], 0.0)
                return state + jax.ops.segment_sum(
                    vals, seg, num_segments=nk)

            agg = Apply(pre, fold=single_pass(
                init, step, lambda st, src: st), label="segsum")
            e1 = Apply(agg, lambda v: v * 0.5, label="epi:half")
            e2 = Apply(e1, lambda v: v + 1.0, label="epi:shift")
            return WriteSet(e2, "fz", "fold_out")

        def run_fold_once():
            res = c.execute_computations(fold_sink(),
                                         job_name="fusion-fold",
                                         materialize=False)
            jax.block_until_ready(next(iter(res.values())))

        out["fold_stream"] = paired(run_fold_once)
        out["fold_stream_speedup"] = out["fold_stream"]["speedup"]
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_summa(rows: int = 65_536, k: int = 512, cols: int = 256,
                row_block: int = 4096, participants: int = 4,
                table_rows: int = 200_000,
                repeats: int = 3) -> Dict[str, object]:
    """Distributed linear algebra paired A/B — the ``--summa`` mode
    (ISSUE 15 acceptance bench). Two arms:

    * **SUMMA panels vs replicated operands** — ``M @ rhs`` with M
      paged, on an N-device virtual mesh. The baseline places every
      operand REPLICATED (each participant stages the full bytes —
      the broadcast-join default the engine replaces); SUMMA stages
      1/N per participant and broadcasts B panels per step. The
      headline is the per-host STAGED-BYTE reduction (deterministic —
      a CPU container's wall times for 4 virtual devices on 2 cores
      measure contention, not a pod); byte-equality between arms is a
      gate, integer-valued f32 operands make it exact.
    * **reshard via collectives vs re-stage from the arena** — a warm
      placed 2-column set moves sharded → replicated through
      ``parallel/reshard.reshard_set`` (device-to-device, ZERO arena
      reads — proven by the page counter) vs dropping the cache and
      re-staging the whole set under the new layout. Reports the
      wall-time ratio plus the structural proof bits the bench.py
      record is gated on.

    CPU-container caveat: the "device" is host RAM, so transfer
    savings understate HBM; the staged-byte fractions are exact
    either way. TPU-rig re-measure is the ROADMAP follow-on."""
    import contextlib
    import shutil
    import tempfile
    import time as _time

    import jax

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.parallel.placement import Placement
    from netsdb_tpu.parallel.reshard import reshard_set
    from netsdb_tpu.parallel.summa import summa_matmul_streamed
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.storage.devcache import to_device
    from netsdb_tpu.storage.paged import PagedTensorStore
    from netsdb_tpu.storage.store import SetIdentifier

    devices = jax.devices()[:participants]
    out: Dict[str, object] = {"participants": len(devices),
                              "rows": rows, "k": k, "cols": cols}
    if len(devices) < 2:
        out["error"] = (f"needs >= 2 devices (have {len(devices)}; "
                        f"set xla_force_host_platform_device_count)")
        return out
    n = len(devices)
    root = tempfile.mkdtemp(prefix="summa_bench_")
    try:
        rng = np.random.default_rng(0)
        cfg = Configuration(root_dir=root,
                            page_size_bytes=row_block * k * 4)
        pts = PagedTensorStore(cfg, force_python=True)
        m = rng.integers(-8, 8, (rows, k)).astype(np.float32)
        rhs = rng.integers(-8, 8, (k, cols)).astype(np.float32)
        pts.put("m", m, row_block=row_block)
        operand_bytes = m.nbytes + rhs.nbytes

        # --- replicated-operand baseline: every participant stages
        # every byte (the broadcast-join placement), one jitted
        # block-matmul over replicated chunks
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(devices), ("data",))
        repl = NamedSharding(mesh, P(None, None))

        @jax.jit
        def block_mm(a, b):
            return jax.lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)

        def replicated_arm():
            t0 = _time.perf_counter()
            rhs_dev = to_device(rhs, repl)
            outs = []
            staged = 0
            with contextlib.closing(pts.stream_blocks("m")) as blocks:
                for _s0, block in blocks:
                    dev = to_device(np.ascontiguousarray(block), repl)
                    staged += block.nbytes * n  # a replica per host
                    outs.append(np.asarray(block_mm(dev, rhs_dev)))
            res = np.concatenate(outs, axis=0)
            return res, _time.perf_counter() - t0, \
                staged // n + rhs.nbytes  # per-host staged bytes

        def summa_arm():
            stats: Dict[str, object] = {}
            t0 = _time.perf_counter()
            res = summa_matmul_streamed(pts, "m", rhs, devices=devices,
                                        stats_out=stats)
            dt = _time.perf_counter() - t0
            per_host = max(
                stats["staged_bytes_per_participant"].values())
            return res, dt, per_host

        base_res = summa_res = None
        base_t = summa_t = float("inf")
        base_bytes = summa_bytes = 0
        for _ in range(repeats):  # alternate arms; best-of
            r, t, by = replicated_arm()
            base_res, base_bytes = r, by
            base_t = min(base_t, t)
            r, t, by = summa_arm()
            summa_res, summa_bytes = r, by
            summa_t = min(summa_t, t)
        byte_equal = base_res.tobytes() == summa_res.tobytes()
        out.update({
            "byte_equal": byte_equal,
            "replicated_s": round(base_t, 4),
            "summa_s": round(summa_t, 4),
            "replicated_per_host_staged_bytes": int(base_bytes),
            "summa_per_host_staged_bytes": int(summa_bytes),
            "per_host_staged_frac": round(summa_bytes / operand_bytes,
                                          4),
            "summa_staging_reduction_x": round(base_bytes / summa_bytes,
                                               2) if summa_bytes else 0,
        })

        # --- reshard via collectives vs re-stage from the arena ------
        c = Client(Configuration(root_dir=root + "_rs",
                                 page_size_bytes=64 * 1024))
        c.create_database("d")
        src = Placement((("data", n),), ("data",))
        dst = Placement((("data", n),), (None,))
        ident = SetIdentifier("d", "t")
        c.create_set("d", "t", type_name="table", storage="paged",
                     placement=src)
        c.send_table("d", "t", ColumnTable({
            "k": rng.integers(0, 100, table_rows).astype(np.int32),
            "v": rng.uniform(0, 1, table_rows).astype(np.float32)}, {}))
        pc = next(i for i in c.store.get_items(ident)
                  if isinstance(i, PagedColumns))

        def consume(placement):
            with contextlib.closing(
                    pc.stream_tables(placement=placement)) as s:
                for _t in s:
                    pass

        consume(src)  # warm the cache under the source layout
        # alternating cycles: reshard src<->dst via collectives, then
        # the baseline (drop cache + swap placement + re-stage from
        # the arena) the other way — best-of per arm so the first
        # cycle's XLA compiles (one program per step shape) don't
        # masquerade as data-movement cost
        reshard_s = restage_s = float("inf")
        zero_arena = True
        rep = None
        for _i in range(max(int(repeats), 2)):
            # each cycle starts warm under src: reshard src -> dst via
            # collectives, then the baseline restages back to src
            pages0 = pc.pages_streamed
            t0 = _time.perf_counter()
            rep = reshard_set(c.store, ident, dst)
            consume(dst)  # the warm re-query under the new layout
            reshard_s = min(reshard_s, _time.perf_counter() - t0)
            zero_arena = zero_arena and pc.pages_streamed == pages0
            # baseline back: the pre-reshard world — drop the cache,
            # swap the placement, re-stage everything from the arena
            t0 = _time.perf_counter()
            c.store.device_cache().invalidate(str(ident))
            c.store.set_placement(ident, src)
            consume(src)
            restage_s = min(restage_s, _time.perf_counter() - t0)
        out.update({
            "table_rows": table_rows,
            "reshard_blocks_moved": rep.blocks_moved,
            "reshard_steps": rep.labels(),
            "reshard_s": round(reshard_s, 4),
            "restage_s": round(restage_s, 4),
            "reshard_zero_arena_reads": zero_arena,
            "reshard_collective_speedup": round(restage_s / reshard_s,
                                                2) if reshard_s else 0,
        })
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(root + "_rs", ignore_errors=True)


BENCHMARKS: Dict[str, Callable[[], Result]] = {
    "arena_alloc": bench_arena_alloc,
    "int_groupby": bench_int_groupby,
    "string_groupby": bench_string_groupby,
    "segment_sum": bench_segment_sum,
    "shuffle": bench_shuffle,
    "planner": bench_planner,
}


def run_all(names=None, out=print) -> Dict[str, Result]:
    results = {}
    for name in (names or BENCHMARKS):
        ops, secs, rate = BENCHMARKS[name]()
        results[name] = (ops, secs, rate)
        out(f"{name}: {ops} ops in {secs:.3f}s = {rate:,.0f} ops/s")
    return results
