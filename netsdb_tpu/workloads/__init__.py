"""Analytics workloads — the reference's shared-library UDF families
(``src/sharedLibraries/headers``: KMeans*, GMM/, LDA*,
RankUpdateAggregation/PageRank, TopK) re-expressed as jit-compiled
algorithms over the framework's sets."""

from netsdb_tpu.workloads.kmeans import kmeans, kmeans_on_set
from netsdb_tpu.workloads.gmm import gmm_em
from netsdb_tpu.workloads.lda import lda_em
from netsdb_tpu.workloads.pagerank import pagerank, pagerank_on_set
from netsdb_tpu.workloads.topk import top_k, top_k_on_set

__all__ = ["kmeans", "kmeans_on_set", "gmm_em", "lda_em", "pagerank",
           "pagerank_on_set", "top_k", "top_k_on_set"]
