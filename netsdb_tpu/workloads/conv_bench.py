"""Conv2D batch-latency benchmark — the second north-star metric
(BASELINE.md: "conv2d batch latency p50").

Shapes default to the reference conv2d workload's documented inputs
(112x112x3 images, 64 7x7x3 filters — reference
``model-inference/convolutional-neural-network/README.md:8-16``).
The reference executes this by calling ATen ``at::conv2d`` on CPU per
image inside a Selection UDF (``src/conv2d_proj/headers/
Conv2DSelect.h:13-216``); torch is available here, so the baseline is
the reference's own op measured on this host — batched, which is
GENEROUS to the reference (its per-object calls cannot batch across
images).

Both TPU modes are measured: direct (``lax.conv_general_dilated``, one
XLA conv on the MXU) and im2col (patch matrix + blocked matmul — the
reference's conv2d_memory_fusion rewrite).

Timing protocol (axon tunnel): per-dispatch wall times over the
controller tunnel carry tens-to-hundreds of ms of NOISY overhead, so
device time is measured as the slope between two on-device ``lax.scan``
loop lengths (each iteration's input depends on the previous output, so
XLA cannot hoist or elide iterations); p50/p90 are over the slope
estimates. Wall p50 (including the tunnel round-trip) is also reported
as the honest interactive-latency upper bound.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.ops.conv import conv2d_direct, conv2d_im2col


def _percentiles(times: Sequence[float]) -> Dict[str, float]:
    arr = np.asarray(sorted(times))
    return {"p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 4),
            "p90_ms": round(float(np.percentile(arr, 90)) * 1e3, 4)}


def torch_cpu_baseline(images: np.ndarray, kernels: np.ndarray,
                       iters: int = 10) -> Dict[str, float]:
    """The reference-equivalent path: ATen conv2d on host CPU."""
    import torch

    x = torch.from_numpy(images)
    w = torch.from_numpy(kernels)
    with torch.no_grad():
        torch.conv2d(x, w)  # warm
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            torch.conv2d(x, w)
            times.append(time.perf_counter() - t0)
    return _percentiles(times)


def run_conv_bench(batch: int = 64, hw: int = 112, cin: int = 3,
                   cout: int = 64, k: int = 7, iters: int = 20,
                   compute_dtype: Optional[str] = None,
                   seed: int = 0) -> Dict[str, object]:
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((batch, cin, hw, hw)).astype(np.float32)
    kernels = rng.standard_normal((cout, cin, k, k)).astype(np.float32)

    xd = jnp.asarray(images)
    wd = jnp.asarray(kernels)
    jax.block_until_ready(xd)

    modes = {
        "direct": lambda a, b: conv2d_direct(
            a, b, compute_dtype=compute_dtype),
        "im2col": lambda a, b: conv2d_im2col(
            a, b, compute_dtype=compute_dtype),
    }
    out: Dict[str, object] = {
        "batch": batch, "hw": hw, "cin": cin, "cout": cout, "k": k,
        "backend": jax.default_backend(),
    }
    cpu = torch_cpu_baseline(images, kernels, iters=max(iters // 2, 3))
    out["torch_cpu_reference"] = cpu
    repeats = max(min(iters // 4, 5), 3)
    for name, conv_fn in modes.items():
        @partial(jax.jit, static_argnums=2)
        def loop(a, b, n, conv_fn=conv_fn):
            def step(carry, _):
                o = conv_fn(a + carry, b)
                # reduce over the WHOLE output: a single-element carry
                # would let XLA slice-push through the conv and compute
                # only one output pixel's receptive field
                return jnp.sum(o).astype(a.dtype) * 1e-20, None
            c, _ = jax.lax.scan(step, jnp.zeros((), a.dtype), None, length=n)
            return c

        from netsdb_tpu.utils.timing import scan_slope_seconds

        res = scan_slope_seconds(lambda n: float(loop(xd, wd, n)),
                                 lo=2, hi=8, repeats=repeats)

        fn = jax.jit(conv_fn)
        float(jnp.sum(fn(xd, wd)))  # compile single-dispatch form
        wall = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(jnp.sum(fn(xd, wd)))
            wall.append(time.perf_counter() - t0)
        p50_wall = float(np.percentile(np.asarray(sorted(wall)), 50))

        if res["below_noise"]:
            # device time unresolvable under controller noise even after
            # escalating loop lengths: wall (incl. tunnel RTT) is the
            # honest upper bound for the speedup
            stats = {"p50_ms": round(p50_wall * 1e3, 4),
                     "p90_ms": round(max(wall) * 1e3, 4),
                     "below_device_noise": True}
            p50_dev_ms = p50_wall * 1e3
        else:
            stats = _percentiles([max(s, 0.0) for s in res["slopes"]])
            p50_dev_ms = res["seconds_per_iter"] * 1e3
        stats["p50_wall_ms"] = round(p50_wall * 1e3, 3)
        stats["speedup_vs_torch_cpu_p50"] = round(cpu["p50_ms"] / p50_dev_ms, 3)
        out[name] = stats
    return out
