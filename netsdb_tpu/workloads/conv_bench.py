"""Conv2D batch-latency benchmark — the second north-star metric
(BASELINE.md: "conv2d batch latency p50").

Shapes default to the reference conv2d workload's documented inputs
(112x112x3 images, 64 7x7x3 filters — reference
``model-inference/convolutional-neural-network/README.md:8-16``).
The reference executes this by calling ATen ``at::conv2d`` on CPU per
image inside a Selection UDF (``src/conv2d_proj/headers/
Conv2DSelect.h:13-216``); torch is available here, so the baseline is
the reference's own op measured on this host — batched, which is
GENEROUS to the reference (its per-object calls cannot batch across
images).

Both TPU modes are measured: direct (``lax.conv_general_dilated``, one
XLA conv on the MXU) and im2col (patch matrix + blocked matmul — the
reference's conv2d_memory_fusion rewrite).

Timing protocol (axon tunnel): scalar-pull sync with the controller
round-trip subtracted; p50/p90 over per-iteration wall times.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.ops.conv import conv2d_direct, conv2d_im2col


def _percentiles(times: Sequence[float]) -> Dict[str, float]:
    arr = np.asarray(sorted(times))
    return {"p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 4),
            "p90_ms": round(float(np.percentile(arr, 90)) * 1e3, 4)}


def torch_cpu_baseline(images: np.ndarray, kernels: np.ndarray,
                       iters: int = 10) -> Dict[str, float]:
    """The reference-equivalent path: ATen conv2d on host CPU."""
    import torch

    x = torch.from_numpy(images)
    w = torch.from_numpy(kernels)
    with torch.no_grad():
        torch.conv2d(x, w)  # warm
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            torch.conv2d(x, w)
            times.append(time.perf_counter() - t0)
    return _percentiles(times)


def run_conv_bench(batch: int = 64, hw: int = 112, cin: int = 3,
                   cout: int = 64, k: int = 7, iters: int = 20,
                   compute_dtype: Optional[str] = None,
                   seed: int = 0) -> Dict[str, object]:
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((batch, cin, hw, hw)).astype(np.float32)
    kernels = rng.standard_normal((cout, cin, k, k)).astype(np.float32)

    xd = jnp.asarray(images)
    wd = jnp.asarray(kernels)
    jax.block_until_ready(xd)

    # controller round-trip to subtract from device timings
    g = jax.jit(lambda v: v + 1)
    float(g(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(5):
        float(g(jnp.float32(0)))
    rtt = (time.perf_counter() - t0) / 5

    modes = {
        "direct": jax.jit(lambda a, b: conv2d_direct(
            a, b, compute_dtype=compute_dtype)),
        "im2col": jax.jit(lambda a, b: conv2d_im2col(
            a, b, compute_dtype=compute_dtype)),
    }
    out: Dict[str, object] = {
        "batch": batch, "hw": hw, "cin": cin, "cout": cout, "k": k,
        "backend": jax.default_backend(),
        "controller_rtt_ms": round(rtt * 1e3, 2),
    }
    cpu = torch_cpu_baseline(images, kernels, iters=max(iters // 2, 3))
    out["torch_cpu_reference"] = cpu
    for name, fn in modes.items():
        float(jnp.sum(fn(xd, wd)))  # compile + sync
        wall = []
        for _ in range(iters):
            t0 = time.perf_counter()
            float(jnp.sum(fn(xd, wd)))
            wall.append(time.perf_counter() - t0)
        p50_wall = float(np.percentile(np.asarray(sorted(wall)), 50))
        device = [t - rtt for t in wall]
        p50_dev = float(np.percentile(np.asarray(sorted(device)), 50))
        stats = _percentiles([max(t, 0.0) for t in device])
        if p50_dev <= 0.2 * rtt:
            # device time unresolvable under the controller round-trip;
            # wall time (incl. RTT) is the honest upper bound
            stats["below_controller_rtt"] = True
            p50_for_speedup = p50_wall
        else:
            p50_for_speedup = p50_dev
        stats["p50_wall_ms"] = round(p50_wall * 1e3, 3)
        stats["speedup_vs_torch_cpu_p50"] = round(
            cpu["p50_ms"] / (p50_for_speedup * 1e3), 3)
        out[name] = stats
    return out
