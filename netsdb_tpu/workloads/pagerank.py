"""PageRank — reference ``src/sharedLibraries/headers/
RankUpdateAggregation.h``, ``URLURLsRank.h``, ``JoinRankedUrlWithLink.h``
(driver ``src/tests/source/TestPageRank*.cc``).

The reference joins a ranked-URL set with the link set and aggregates
contributions per target URL each round. Here the edge list becomes
(src, dst) index arrays and each round is one gather + segment-sum under
a jitted loop — the same join+aggregate, minus the shuffle.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.storage.store import SetIdentifier


def pagerank(src: jax.Array, dst: jax.Array, num_nodes: int,
             damping: float = 0.85, iters: int = 20) -> jax.Array:
    """→ rank vector (num_nodes,). ``src``/``dst``: edge endpoint ids."""
    out_degree = jax.ops.segment_sum(jnp.ones_like(src, jnp.float32), src,
                                     num_segments=num_nodes)
    safe_deg = jnp.maximum(out_degree, 1.0)

    def body(_, rank):
        contrib = rank[src] / safe_deg[src]
        incoming = jax.ops.segment_sum(contrib, dst, num_segments=num_nodes)
        # dangling nodes redistribute uniformly (reference drops them;
        # we keep total mass = 1 so ranks are comparable across graphs)
        dangling = jnp.sum(jnp.where(out_degree == 0, rank, 0.0))
        return (1 - damping) / num_nodes + damping * (
            incoming + dangling / num_nodes)

    rank0 = jnp.full((num_nodes,), 1.0 / num_nodes)
    return jax.lax.fori_loop(0, iters, body, rank0)


def pagerank_on_set(client, db: str, links_set: str, num_nodes: int,
                    damping: float = 0.85, iters: int = 20,
                    out_set: str = "ranks") -> np.ndarray:
    """Set driver: links set holds (src, dst) pairs (the reference's
    ``Link`` objects); ranks written to a set of (url, rank) pairs."""
    edges = list(client.get_set_iterator(db, links_set))
    src = jnp.asarray([e[0] for e in edges], jnp.int32)
    dst = jnp.asarray([e[1] for e in edges], jnp.int32)
    ranks = np.asarray(jax.jit(
        lambda s, d: pagerank(s, d, num_nodes, damping, iters))(src, dst))
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="object")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, [(int(i), float(r))
                                   for i, r in enumerate(ranks)])
    return ranks
