"""PageRank — reference ``src/sharedLibraries/headers/
RankUpdateAggregation.h``, ``URLURLsRank.h``, ``JoinRankedUrlWithLink.h``
(driver ``src/tests/source/TestPageRank*.cc``).

The reference joins a ranked-URL set with the link set and aggregates
contributions per target URL each round. Here the edge list becomes
(src, dst) index arrays and each round is one gather + segment-sum under
a jitted loop — the same join+aggregate, minus the shuffle.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.storage.store import SetIdentifier


def pagerank(src: jax.Array, dst: jax.Array, num_nodes: int,
             damping: float = 0.85, iters: int = 20) -> jax.Array:
    """→ rank vector (num_nodes,). ``src``/``dst``: edge endpoint ids."""
    out_degree = jax.ops.segment_sum(jnp.ones_like(src, jnp.float32), src,
                                     num_segments=num_nodes)
    safe_deg = jnp.maximum(out_degree, 1.0)

    def body(_, rank):
        contrib = rank[src] / safe_deg[src]
        incoming = jax.ops.segment_sum(contrib, dst, num_segments=num_nodes)
        # dangling nodes redistribute uniformly (reference drops them;
        # we keep total mass = 1 so ranks are comparable across graphs)
        dangling = jnp.sum(jnp.where(out_degree == 0, rank, 0.0))
        return (1 - damping) / num_nodes + damping * (
            incoming + dangling / num_nodes)

    rank0 = jnp.full((num_nodes,), 1.0 / num_nodes)
    return jax.lax.fori_loop(0, iters, body, rank0)


def pagerank_on_set(client, db: str, links_set: str, num_nodes: int,
                    damping: float = 0.85, iters: int = 20,
                    out_set: str = "ranks") -> np.ndarray:
    """Set driver: links set holds (src, dst) pairs (the reference's
    ``Link`` objects); ranks written to a set of (url, rank) pairs."""
    edges = list(client.get_set_iterator(db, links_set))
    src = jnp.asarray([e[0] for e in edges], jnp.int32)
    dst = jnp.asarray([e[1] for e in edges], jnp.int32)
    ranks = np.asarray(jax.jit(
        lambda s, d: pagerank(s, d, num_nodes, damping, iters))(src, dst))
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="object")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, [(int(i), float(r))
                                   for i, r in enumerate(ranks)])
    return ranks


def pagerank_on_table_set(client, db: str, links_set: str, num_nodes: int,
                          damping: float = 0.85, iters: int = 20,
                          out_set: str = "ranks") -> np.ndarray:
    """Placed-set driver: the link relation is a stored ColumnTable
    {src, dst}; a ``create_set(placement=...)``-sharded links set runs
    every round's gather + segment-sum distributed (XLA psums the rank
    contributions across shards — the reference's per-round
    join+aggregate over partitioned link sets). Invalid (placement
    padding) rows carry -1 endpoints and are dropped by the kernels'
    orphan rule."""
    import jax.numpy as jnp

    from netsdb_tpu.relational.dag import _fold_mask

    t = _fold_mask(client.get_table(db, links_set))
    src, dst = t["src"], t["dst"]

    def run(s, d):
        ok = (s >= 0) & (d >= 0)
        sc = jnp.where(ok, s, 0)
        deg = jax.ops.segment_sum(ok.astype(jnp.float32), sc,
                                  num_segments=num_nodes)
        safe = jnp.maximum(deg, 1.0)

        def body(_, rank):
            contrib = jnp.where(ok, rank[sc] / safe[sc], 0.0)
            agg = jax.ops.segment_sum(
                contrib, jnp.where(ok, d, 0), num_segments=num_nodes)
            return (1.0 - damping) / num_nodes + damping * agg

        return jax.lax.fori_loop(0, iters,  body,
                                 jnp.full((num_nodes,), 1.0 / num_nodes))

    ranks = np.asarray(jax.jit(run)(src, dst))
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set, type_name="object")
    client.clear_set(db, out_set)
    client.send_data(db, out_set, [(int(i), float(r))
                                   for i, r in enumerate(ranks)])
    return ranks
