"""Gaussian mixture model via EM — reference ``src/sharedLibraries/
headers/GMM/`` (GmmAggregate etc.; driver ``src/tests/source/TestGmm.cc``).

The reference's E-step is a selection computing per-point
responsibilities and its M-step an aggregation of weighted sums; here
both steps are one jitted loop with diagonal covariances (the
reference's GMM is diagonal too — ``GmmModel.h`` stores per-dim vars).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class GMMState(NamedTuple):
    means: jax.Array    # (k, d)
    variances: jax.Array  # (k, d)
    weights: jax.Array  # (k,)


def _log_prob(points, state: GMMState) -> jax.Array:
    """(n, k) log N(x; mu_k, diag var_k) + log w_k."""
    diff = points[:, None, :] - state.means[None, :, :]
    var = jnp.maximum(state.variances, 1e-6)
    ll = -0.5 * jnp.sum(diff * diff / var[None], axis=-1)
    ll = ll - 0.5 * jnp.sum(jnp.log(2 * jnp.pi * var), axis=-1)[None]
    return ll + jnp.log(jnp.maximum(state.weights, 1e-12))[None]


def gmm_em(points: jax.Array, k: int, iters: int = 20,
           seed: int = 0) -> Tuple[GMMState, jax.Array]:
    """→ (final state, responsibilities (n,k)). Whole EM under jit."""
    n, d = points.shape
    # k-means init (a few Lloyd rounds) — random point picks collapse
    # components when two seeds land in one cluster
    from netsdb_tpu.workloads.kmeans import kmeans

    init_means, _ = kmeans(points, k, iters=5, seed=seed)
    init = GMMState(
        means=init_means,
        variances=jnp.ones((k, d), points.dtype) * jnp.var(points, axis=0)[None],
        weights=jnp.full((k,), 1.0 / k, points.dtype),
    )

    def step(_, state):
        logp = _log_prob(points, state)                    # E
        resp = jax.nn.softmax(logp, axis=1)
        nk = jnp.maximum(resp.sum(0), 1e-8)                # M
        means = (resp.T @ points) / nk[:, None]
        ex2 = (resp.T @ (points * points)) / nk[:, None]
        return GMMState(means=means,
                        variances=jnp.maximum(ex2 - means * means, 1e-6),
                        weights=nk / n)

    state = jax.lax.fori_loop(0, iters, step, init)
    resp = jax.nn.softmax(_log_prob(points, state), axis=1)
    return state, resp


def gmm_log_likelihood(points: jax.Array, state: GMMState) -> jax.Array:
    return jnp.mean(jax.scipy.special.logsumexp(_log_prob(points, state),
                                                axis=1))


def gmm_on_set(client, db: str, set_name: str, k: int, iters: int = 20,
               out_set: str = "gmm_state", seed: int = 0
               ) -> Tuple[GMMState, jax.Array]:
    """Set-oriented driver: points come from a stored tensor set, so a
    ``create_set(placement=...)``-sharded points set runs the whole EM
    distributed — jit sees the stored sharding and XLA psums the
    responsibilities (the reference runs every workload against
    partitioned sets by construction, ``QuerySchedulerServer.cc:216-330``).
    Means/variances/weights are written back stacked as one tensor set."""
    import numpy as np

    from netsdb_tpu.core.blocked import BlockedTensor
    from netsdb_tpu.storage.store import SetIdentifier

    pts = client.get_tensor(db, set_name)
    state, resp = jax.jit(
        lambda p: gmm_em(p, k, iters, seed=seed))(pts.to_dense())
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set)
    packed = jnp.concatenate(
        [state.means, state.variances, state.weights[:, None]], axis=1)
    client.store.put_tensor(
        SetIdentifier(db, out_set),
        BlockedTensor.from_dense(np.asarray(packed), pts.meta.block_shape))
    return state, resp
