"""Gaussian mixture model via EM — reference ``src/sharedLibraries/
headers/GMM/`` (GmmAggregate etc.; driver ``src/tests/source/TestGmm.cc``).

The reference's E-step is a selection computing per-point
responsibilities and its M-step an aggregation of weighted sums; here
both steps are one jitted loop with diagonal covariances (the
reference's GMM is diagonal too — ``GmmModel.h`` stores per-dim vars).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class GMMState(NamedTuple):
    means: jax.Array    # (k, d)
    variances: jax.Array  # (k, d)
    weights: jax.Array  # (k,)


def _log_prob(points, state: GMMState) -> jax.Array:
    """(n, k) log N(x; mu_k, diag var_k) + log w_k."""
    diff = points[:, None, :] - state.means[None, :, :]
    var = jnp.maximum(state.variances, 1e-6)
    ll = -0.5 * jnp.sum(diff * diff / var[None], axis=-1)
    ll = ll - 0.5 * jnp.sum(jnp.log(2 * jnp.pi * var), axis=-1)[None]
    return ll + jnp.log(jnp.maximum(state.weights, 1e-12))[None]


def gmm_em(points: jax.Array, k: int, iters: int = 20,
           seed: int = 0) -> Tuple[GMMState, jax.Array]:
    """→ (final state, responsibilities (n,k)). Whole EM under jit."""
    n, d = points.shape
    # k-means init (a few Lloyd rounds) — random point picks collapse
    # components when two seeds land in one cluster
    from netsdb_tpu.workloads.kmeans import kmeans

    init_means, _ = kmeans(points, k, iters=5, seed=seed)
    init = GMMState(
        means=init_means,
        variances=jnp.ones((k, d), points.dtype) * jnp.var(points, axis=0)[None],
        weights=jnp.full((k,), 1.0 / k, points.dtype),
    )

    def step(_, state):
        logp = _log_prob(points, state)                    # E
        resp = jax.nn.softmax(logp, axis=1)
        nk = jnp.maximum(resp.sum(0), 1e-8)                # M
        means = (resp.T @ points) / nk[:, None]
        ex2 = (resp.T @ (points * points)) / nk[:, None]
        return GMMState(means=means,
                        variances=jnp.maximum(ex2 - means * means, 1e-6),
                        weights=nk / n)

    state = jax.lax.fori_loop(0, iters, step, init)
    resp = jax.nn.softmax(_log_prob(points, state), axis=1)
    return state, resp


def gmm_log_likelihood(points: jax.Array, state: GMMState) -> jax.Array:
    return jnp.mean(jax.scipy.special.logsumexp(_log_prob(points, state),
                                                axis=1))
