"""Columnar tpchBench — the nested Customer⋈Order⋈LineItem micro-family
on the device engine.

Round 1 ran this family (``src/tpchBench``) over host dataclasses
through the interpreter plan path (``workloads/tpch_bench.py``). Here
the nested object graph columnarizes at ingest — customers as one
table, the orders→lineItems nesting FLATTENED into a triples table
(customer, supplier, part), which is exactly what the reference's
``CustomerMultiSelection`` → ``CustomerSupplierPartFlat`` computes per
query — and each query shape becomes one jitted kernel:

- int/string selections → masks (``CustomerIntegerSelection[Not].h``,
  ``CustomerStringSelection[Not].h``);
- group-by supplier → segment counts over (supplier, customer) pairs
  (``CustomerSupplierPartGroupBy.h``);
- count aggregation → one reduction (``CountAggregation.h``);
- top-K Jaccard (``TopJaccard.h:17``) → the TPU-native form: the
  customer×part membership matrix is built ONCE with a scatter, then
  every query part-set is a MATVEC on the MXU — intersection sizes for
  all customers in one pass, |union| by inclusion-exclusion, one
  ``lax.top_k``. Set similarity as matmul is the same collapse that
  turned the reference's matmul-as-join into ``dot_general``.

Cross-checked against the host-object pipeline on identical data
(tests/test_tpch_bench_columnar.py).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational.table import ColumnTable
from netsdb_tpu.workloads.tpch_bench import Customer


# ------------------------------------------------------------- ingest
def columnarize(customers: Sequence[Customer]
                ) -> Dict[str, ColumnTable]:
    """Nested customers → flat columnar tables. The orders→lineItems
    graph flattens into one triples row per line item (the reference
    re-derives these triples inside every query; materializing them
    once at ingest is the columnar engine's scan set)."""
    segs = sorted({c.mktsegment for c in customers})
    seg_code = {s: i for i, s in enumerate(segs)}
    n = len(customers)
    cust = ColumnTable({
        "custKey": jnp.asarray(np.fromiter((c.custKey for c in customers),
                                           np.int32, n)),
        "nationKey": jnp.asarray(np.fromiter(
            (c.nationKey for c in customers), np.int32, n)),
        "mktsegment": jnp.asarray(np.fromiter(
            (seg_code[c.mktsegment] for c in customers), np.int32, n)),
        "accbal": jnp.asarray(np.fromiter(
            (c.accbal for c in customers), np.float32, n)),
    }, dicts={"mktsegment": segs})

    sup_names = sorted({li.supplierName for c in customers
                        for o in c.orders for li in o.lineItems})
    sup_code = {s: i for i, s in enumerate(sup_names)}
    ck, sup, part = [], [], []
    for c in customers:
        for o in c.orders:
            for li in o.lineItems:
                ck.append(c.custKey)
                sup.append(sup_code[li.supplierName])
                part.append(li.partKey)
    triples = ColumnTable({
        "custKey": jnp.asarray(np.asarray(ck, np.int32)),
        "supplier": jnp.asarray(np.asarray(sup, np.int32)),
        "partKey": jnp.asarray(np.asarray(part, np.int32)),
    }, dicts={"supplier": sup_names})
    from netsdb_tpu.relational.stats import analyze_table

    analyze_table(cust)
    analyze_table(triples)
    return {"customers": cust, "triples": triples}


# --------------------------------------------------------- selections
@jax.jit
def _selection_masks(custKey, mktsegment, threshold, seg_code):
    int_sel = custKey > threshold
    str_sel = mktsegment == seg_code
    return int_sel, ~int_sel, str_sel, ~str_sel


def selections(tables: Dict[str, ColumnTable], threshold: int = 0,
               segment: str = "BUILDING"):
    """All four selection variants (int/string × plain/negated) in one
    kernel — masks, the columnar engine's selected sets."""
    cust = tables["customers"]
    return _selection_masks(cust["custKey"], cust["mktsegment"],
                            threshold, cust.code("mktsegment", segment))


# --------------------------------------------------- group-by supplier
@functools.partial(jax.jit, static_argnums=(0, 1))
def _supplier_group_core(n_sup: int, n_cust: int, supplier, custKey):
    pair = supplier * n_cust + custKey
    pair_counts = K.segment_count(pair, n_sup * n_cust)
    per_supplier = K.segment_count(supplier, n_sup)
    return pair_counts, per_supplier


def group_by_supplier(tables: Dict[str, ColumnTable]):
    """supplier → (per-(supplier,customer) part counts, per-supplier
    totals): the fixed-shape aggregate backing ``SupplierInfo`` (the
    variable-length part lists stay derivable from the triples by the
    pair mask; the counts are what the benchmark's checks consume)."""
    from netsdb_tpu.relational.stats import key_space

    t = tables["triples"]
    n_sup = len(t.dicts["supplier"])
    n_cust = key_space(tables["customers"], "custKey")
    pair, per = _supplier_group_core(n_sup, n_cust, t["supplier"],
                                     t["custKey"])
    return pair.reshape(n_sup, n_cust), per


def count_customers(tables: Dict[str, ColumnTable]) -> int:
    return tables["customers"].num_rows


# ------------------------------------------------------ top-K jaccard
@functools.partial(jax.jit, static_argnums=(0, 1))
def _membership_matrix(n_cust: int, n_parts: int, custKey, partKey):
    """(n_cust, n_parts) 0/1 membership — built once, amortized over
    every Jaccard query."""
    flat = custKey * n_parts + jnp.clip(partKey, 0, n_parts - 1)
    m = jnp.zeros((n_cust * n_parts,), jnp.float32).at[flat].max(
        jnp.ones_like(flat, jnp.float32))
    return m.reshape(n_cust, n_parts)


@functools.partial(jax.jit, static_argnums=(2,))
def _jaccard_core(member, query_vec, k: int):
    sizes = member.sum(axis=1)
    inter = member @ query_vec  # MXU matvec: all intersections at once
    union = sizes + query_vec.sum() - inter
    j = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    vals, idx = jax.lax.top_k(j, k)
    return vals, idx


def top_jaccard(tables: Dict[str, ColumnTable],
                query_parts: Sequence[int], k: int = 5
                ) -> List[Tuple[float, int]]:
    """Top-k customers by Jaccard similarity against ``query_parts`` —
    returns [(score, custKey)] best-first (ties broken by custKey
    ascending, matching the host heap's ordering)."""
    from netsdb_tpu.relational.stats import key_space

    t = tables["triples"]
    n_cust = key_space(tables["customers"], "custKey")
    n_parts = max(key_space(t, "partKey"),
                  max(query_parts, default=0) + 1)
    member = _membership_matrix(n_cust, n_parts, t["custKey"],
                                t["partKey"])
    q = np.zeros((n_parts,), np.float32)
    for p in set(query_parts):
        q[p] = 1.0
    vals, idx = _jaccard_core(member, jnp.asarray(q), k)
    out = sorted(zip(np.asarray(vals).tolist(),
                     np.asarray(idx).tolist()),
                 key=lambda si: (-si[0], si[1]))
    return [(float(s), int(i)) for s, i in out]


# ----------------------------------------------------------- bench
def bench_tpch_bench(n_customers: int = 100_000, max_orders: int = 4,
                     max_items: int = 5, n_parts: int = 2048,
                     n_suppliers: int = 64, k: int = 10,
                     seed: int = 0) -> Dict[str, object]:
    """Device-timed columnar run of the family at a scale the
    host-object path cannot touch (~1M triples)."""
    from netsdb_tpu.utils.timing import scan_slope_seconds

    rng = np.random.default_rng(seed)
    n_rows = n_customers * ((max_orders + 1) // 2) * ((max_items + 1) // 2)
    ck = np.repeat(np.arange(n_customers, dtype=np.int32),
                   n_rows // n_customers)
    triples = ColumnTable({
        "custKey": jnp.asarray(ck),
        "supplier": jnp.asarray(rng.integers(0, n_suppliers,
                                             len(ck)).astype(np.int32)),
        "partKey": jnp.asarray(rng.integers(0, n_parts,
                                            len(ck)).astype(np.int32)),
    }, dicts={"supplier": [f"Supplier{i}" for i in range(n_suppliers)]})
    member = _membership_matrix(n_customers, n_parts,
                                triples["custKey"], triples["partKey"])
    q = jnp.asarray((rng.random(n_parts) < 0.05).astype(np.float32))

    @functools.partial(jax.jit, static_argnums=(2,))
    def loop(member, q, n):
        def step(carry, _):
            vals, idx = _jaccard_core(member + carry, q, k)
            return vals.sum() * 1e-9, None

        c, _ = jax.lax.scan(step, jnp.zeros(()), None, length=n)
        return c

    res = scan_slope_seconds(lambda n: float(loop(member, q, n)),
                             lo=2, hi=8)
    dt = res["seconds_per_iter"]
    return {"triples": int(len(ck)), "customers": n_customers,
            "parts": n_parts,
            "jaccard_ms": None if dt is None else round(dt * 1e3, 3),
            "below_noise": dt is None}


def queries_on_sets(client, db: str = "tpchbench", threshold: int = 0,
                    segment: str = "BUILDING",
                    query_parts: Sequence[int] = (0,), k: int = 5):
    """Placed-set entry point: the whole micro-family against STORED
    sets — with ``customers``/``triples`` created under a row-sharding
    Placement the same kernels run distributed (XLA inserts the
    segment-psums; placement padding folds to -1 keys and drops by the
    orphan rule). Returns {selections, pair_counts, per_supplier,
    count, top_jaccard} — the shapes the benchmark's checks consume."""
    from netsdb_tpu.relational.dag import _fold_mask
    from netsdb_tpu.relational.stats import analyze_table, inject_stats

    raw = {n: client.get_table(db, n) for n in ("customers", "triples")}
    cust_mask = raw["customers"].mask()
    tables = {n: inject_stats(_fold_mask(t), analyze_table(t))
              for n, t in raw.items()}
    sels = tuple(m & cust_mask
                 for m in selections(tables, threshold, segment))
    pair, per = group_by_supplier(tables)
    return {
        "selections": sels,
        "pair_counts": pair,
        "per_supplier": per,
        "count": int(jnp.sum(cust_mask)),
        "top_jaccard": top_jaccard(tables, list(query_parts), k),
    }
