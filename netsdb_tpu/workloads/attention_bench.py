"""Long-context attention benchmark: pallas flash vs naive softmax.

The reference has NO attention anywhere in its tree (SURVEY §5
long-context note) — this is the beyond-reference long-context
capability, so the comparison here is internal: the naive formulation
(materializes the (S, S) score matrix in HBM, ``ops.attention``)
against the pallas flash kernel (online-softmax accumulators in VMEM,
``ops.pallas_kernels.flash_attention``), both causal bf16.

Timing via ``utils.timing.scan_slope_seconds``; reports tokens/s and
the achieved fraction of the attention-FLOP roofline (4*S^2*D*B*H
causal-halved matmul FLOPs per forward).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.ops.attention import attention
from netsdb_tpu.ops.pallas_kernels import flash_attention
from netsdb_tpu.utils.timing import scan_slope_seconds


def bench_attention(seq_lens: Sequence[int] = (1024, 2048, 4096, 8192),
                    batch: int = 2, heads: int = 8, head_dim: int = 128,
                    seed: int = 0) -> Dict[str, Dict]:
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict] = {}
    for s in seq_lens:
        q, k, v = (jnp.asarray(rng.standard_normal((batch, heads, s, head_dim)),
                               jnp.bfloat16) for _ in range(3))
        entry: Dict[str, object] = {"batch": batch, "heads": heads,
                                    "head_dim": head_dim}
        # causal: half the S^2 logits are live; 2 matmuls (QK^T, PV)
        flops = 2 * 2 * batch * heads * s * s * head_dim / 2

        for name, fn in (("naive", attention), ("flash", flash_attention)):
            @partial(jax.jit, static_argnums=3)
            def loop(qq, kk, vv, n, fn=fn):
                def step(carry, _):
                    o = fn(qq + carry, kk, vv, True)
                    return (jnp.sum(o) * 1e-20).astype(qq.dtype), None
                c, _ = jax.lax.scan(step, jnp.zeros((), qq.dtype), None,
                                    length=n)
                return c

            try:
                res = scan_slope_seconds(
                    lambda n: float(loop(q, k, v, n)), lo=4, hi=16)
            except Exception as e:  # naive path OOMs at long seq
                entry[name] = {"error": str(e)[:200]}
                continue
            if res["below_noise"]:
                entry[name] = {"below_device_noise": True}
                continue
            dt = res["seconds_per_iter"]
            entry[name] = {
                "ms": round(dt * 1e3, 3),
                "tokens_per_sec": round(batch * s / dt, 1),
                "tflops": round(flops / dt / 1e12, 1),
            }
        n_ms = entry.get("naive", {}).get("ms")
        f_ms = entry.get("flash", {}).get("ms")
        if n_ms and f_ms:
            entry["flash_speedup"] = round(n_ms / f_ms, 2)
        out[f"seq_{s}"] = entry
    return out


def bench_ring_fold(n_chunks: int = 8, s_local: int = 1024,
                    batch: int = 2, heads: int = 8, head_dim: int = 128,
                    seed: int = 0) -> Dict[str, object]:
    """Per-device ring-attention compute: chain ``n_chunks`` flash-carry
    folds (``ops.pallas_kernels.flash_attention_step``) — the causal
    worst-case device's work at S = n_chunks * s_local over n_chunks
    shards, minus the ICI rotation (unmeasurable single-chip). Reports
    actual (un-halved) FLOP throughput, comparable against the flash
    single-chip number times (live_blocks/total_halved_blocks)."""
    from netsdb_tpu.ops.pallas_kernels import NEG_INF, flash_attention_step

    rng = np.random.default_rng(seed)
    bh = batch * heads
    q = jnp.asarray(rng.standard_normal((bh, s_local, head_dim)),
                    jnp.bfloat16)
    ks = jnp.asarray(rng.standard_normal((bh, n_chunks * s_local,
                                          head_dim)), jnp.bfloat16)
    vs = jnp.asarray(rng.standard_normal((bh, n_chunks * s_local,
                                          head_dim)), jnp.bfloat16)

    @jax.jit
    def folded(q, ks, vs):
        acc = jnp.zeros(q.shape, jnp.float32)
        l = jnp.zeros((bh, s_local, 128), jnp.float32)
        m = jnp.full((bh, s_local, 128), NEG_INF, jnp.float32)
        for i in range(n_chunks):
            acc, l, m = flash_attention_step(
                q, ks[:, i * s_local:(i + 1) * s_local],
                vs[:, i * s_local:(i + 1) * s_local], acc, l, m,
                q_offset=(n_chunks - 1) * s_local, k_offset=i * s_local)
        return (acc / jnp.maximum(l[:, :, :1], 1e-30)).astype(q.dtype)

    @partial(jax.jit, static_argnums=1)
    def loop(qq, n):
        def step(c, _):
            o = folded(qq + c, ks, vs)
            return (jnp.sum(o) * 1e-20).astype(qq.dtype), None
        c, _ = jax.lax.scan(step, jnp.zeros((), qq.dtype), None, length=n)
        return c

    res = scan_slope_seconds(lambda n: float(loop(q, n)), lo=4, hi=16)
    flops = n_chunks * 2 * 2 * bh * s_local * s_local * head_dim
    dt = res["seconds_per_iter"]
    return {"n_chunks": n_chunks, "s_local": s_local,
            "ms": round(dt * 1e3, 3),
            "tflops_actual": round(flops / dt / 1e12, 1)}
