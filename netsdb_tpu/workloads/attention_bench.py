"""Long-context attention benchmark: pallas flash vs naive softmax.

The reference has NO attention anywhere in its tree (SURVEY §5
long-context note) — this is the beyond-reference long-context
capability, so the comparison here is internal: the naive formulation
(materializes the (S, S) score matrix in HBM, ``ops.attention``)
against the pallas flash kernel (online-softmax accumulators in VMEM,
``ops.pallas_kernels.flash_attention``), both causal bf16.

Timing via ``utils.timing.scan_slope_seconds``; reports tokens/s and
the achieved fraction of the attention-FLOP roofline (4*S^2*D*B*H
causal-halved matmul FLOPs per forward).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.ops.attention import attention
from netsdb_tpu.ops.pallas_kernels import flash_attention
from netsdb_tpu.utils.timing import scan_slope_seconds


def bench_attention(seq_lens: Sequence[int] = (1024, 2048, 4096, 8192),
                    batch: int = 2, heads: int = 8, head_dim: int = 128,
                    seed: int = 0) -> Dict[str, Dict]:
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict] = {}
    for s in seq_lens:
        q, k, v = (jnp.asarray(rng.standard_normal((batch, heads, s, head_dim)),
                               jnp.bfloat16) for _ in range(3))
        entry: Dict[str, object] = {"batch": batch, "heads": heads,
                                    "head_dim": head_dim}
        # causal: half the S^2 logits are live; 2 matmuls (QK^T, PV)
        flops = 2 * 2 * batch * heads * s * s * head_dim / 2

        for name, fn in (("naive", attention), ("flash", flash_attention)):
            @partial(jax.jit, static_argnums=3)
            def loop(qq, kk, vv, n, fn=fn):
                def step(carry, _):
                    o = fn(qq + carry, kk, vv, True)
                    return (jnp.sum(o) * 1e-20).astype(qq.dtype), None
                c, _ = jax.lax.scan(step, jnp.zeros((), qq.dtype), None,
                                    length=n)
                return c

            try:
                res = scan_slope_seconds(
                    lambda n: float(loop(q, k, v, n)), lo=4, hi=16)
            except Exception as e:  # naive path OOMs at long seq
                entry[name] = {"error": str(e)[:200]}
                continue
            if res["below_noise"]:
                entry[name] = {"below_device_noise": True}
                continue
            dt = res["seconds_per_iter"]
            entry[name] = {
                "ms": round(dt * 1e3, 3),
                "tokens_per_sec": round(batch * s / dt, 1),
                "tflops": round(flops / dt / 1e12, 1),
            }
        n_ms = entry.get("naive", {}).get("ms")
        f_ms = entry.get("flash", {}).get("ms")
        if n_ms and f_ms:
            entry["flash_speedup"] = round(n_ms / f_ms, 2)
        out[f"seq_{s}"] = entry
    return out
