"""Long-context attention benchmark: pallas flash vs naive softmax.

The reference has NO attention anywhere in its tree (SURVEY §5
long-context note) — this is the beyond-reference long-context
capability, so the comparison here is internal: the naive formulation
(materializes the (S, S) score matrix in HBM, ``ops.attention``)
against the pallas flash kernel (online-softmax accumulators in VMEM,
``ops.pallas_kernels.flash_attention``), both causal bf16.

Timing via ``utils.timing.scan_slope_seconds``; reports tokens/s and
the achieved fraction of the attention-FLOP roofline (4*S^2*D*B*H
causal-halved matmul FLOPs per forward).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.ops.attention import attention
from netsdb_tpu.ops.pallas_kernels import flash_attention
from netsdb_tpu.utils.timing import scan_slope_seconds


def _jax_reference_kernel():
    """jax's own TPU flash kernel — the independent yardstick for the
    'structural ceiling' claim at ``ops/pallas_kernels.py`` (~57% MFU
    at 8k causal is the hardware's, not this kernel's). None when the
    module is unavailable (CPU tests, jax version drift)."""
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes, flash_attention as jref)
    except Exception:
        return None

    def run(q, k, v, causal):
        s = q.shape[2]
        bq = bk = min(1024, s)  # same tuned blocks as our kernel —
        # jref's defaults (128×128) leave it ~7× under its own best
        bs = BlockSizes(block_q=bq, block_k_major=bk, block_k=bk,
                        block_b=1)
        return jref(q, k, v, causal=causal,
                    sm_scale=1.0 / float(q.shape[3]) ** 0.5,
                    block_sizes=bs)

    return run


# the guarded claim: our flash must stay within this fraction of jax's
# reference kernel wall time at the headline shape (VERDICT r2 weak #7)
CEILING_RATIO = 0.92
CEILING_SEQ = 8192


def bench_attention(seq_lens: Sequence[int] = (1024, 2048, 4096, 8192),
                    batch: int = 2, heads: int = 8, head_dim: int = 128,
                    seed: int = 0,
                    assert_ceiling: bool = True) -> Dict[str, Dict]:
    rng = np.random.default_rng(seed)
    jref = _jax_reference_kernel() if jax.devices()[0].platform == "tpu" \
        else None
    out: Dict[str, Dict] = {}
    for s in seq_lens:
        q, k, v = (jnp.asarray(rng.standard_normal((batch, heads, s, head_dim)),
                               jnp.bfloat16) for _ in range(3))
        entry: Dict[str, object] = {"batch": batch, "heads": heads,
                                    "head_dim": head_dim}
        # causal: half the S^2 logits are live; 2 matmuls (QK^T, PV)
        flops = 2 * 2 * batch * heads * s * s * head_dim / 2

        kernels = [("naive", attention), ("flash", flash_attention)]
        if jref is not None:
            kernels.append(("jax_ref", jref))
        for name, fn in kernels:
            @partial(jax.jit, static_argnums=3)
            def loop(qq, kk, vv, n, fn=fn):
                def step(carry, _):
                    o = fn(qq + carry, kk, vv, True)
                    return (jnp.sum(o) * 1e-20).astype(qq.dtype), None
                c, _ = jax.lax.scan(step, jnp.zeros((), qq.dtype), None,
                                    length=n)
                return c

            try:
                res = scan_slope_seconds(
                    lambda n: float(loop(q, k, v, n)), lo=4, hi=16)
            except Exception as e:  # naive path OOMs at long seq
                entry[name] = {"error": str(e)[:200]}
                continue
            if res["below_noise"]:
                entry[name] = {"below_device_noise": True}
                continue
            dt = res["seconds_per_iter"]
            entry[name] = {
                "ms": round(dt * 1e3, 3),
                "tokens_per_sec": round(batch * s / dt, 1),
                "tflops": round(flops / dt / 1e12, 1),
            }
        n_ms = entry.get("naive", {}).get("ms")
        f_ms = entry.get("flash", {}).get("ms")
        if n_ms and f_ms:
            entry["flash_speedup"] = round(n_ms / f_ms, 2)
        r_ms = entry.get("jax_ref", {}).get("ms")
        if r_ms and f_ms:
            # >1 means our kernel is FASTER than jax's reference
            entry["flash_vs_jax_ref"] = round(r_ms / f_ms, 3)
        out[f"seq_{s}"] = entry

    # the asserted ceiling guard: if our flash regresses below
    # CEILING_RATIO of jax's reference kernel at the headline shape,
    # the BASELINE "structural ceiling" claim is no longer earned —
    # fail loudly instead of silently re-printing the stale claim
    if assert_ceiling and jref is not None:
        key = f"seq_{CEILING_SEQ}"
        ratio = out.get(key, {}).get("flash_vs_jax_ref")
        if ratio is not None and ratio < CEILING_RATIO:
            raise AssertionError(
                f"flash kernel at seq={CEILING_SEQ} runs at {ratio:.3f}× "
                f"of jax's reference kernel (< {CEILING_RATIO}); the "
                f"attention-ceiling claim in BASELINE.md/"
                f"ops/pallas_kernels.py must be re-validated")
    return out


def bench_ring_fold(n_chunks: int = 8, s_local: int = 1024,
                    batch: int = 2, heads: int = 8, head_dim: int = 128,
                    seed: int = 0) -> Dict[str, object]:
    """Per-device ring-attention compute: chain ``n_chunks`` flash-carry
    folds (``ops.pallas_kernels.flash_attention_step``) — the causal
    worst-case device's work at S = n_chunks * s_local over n_chunks
    shards, minus the ICI rotation (unmeasurable single-chip). Reports
    actual (un-halved) FLOP throughput, comparable against the flash
    single-chip number times (live_blocks/total_halved_blocks)."""
    from netsdb_tpu.ops.pallas_kernels import NEG_INF, flash_attention_step

    rng = np.random.default_rng(seed)
    bh = batch * heads
    q = jnp.asarray(rng.standard_normal((bh, s_local, head_dim)),
                    jnp.bfloat16)
    ks = jnp.asarray(rng.standard_normal((bh, n_chunks * s_local,
                                          head_dim)), jnp.bfloat16)
    vs = jnp.asarray(rng.standard_normal((bh, n_chunks * s_local,
                                          head_dim)), jnp.bfloat16)

    @jax.jit
    def folded(q, ks, vs):
        acc = jnp.zeros(q.shape, jnp.float32)
        l = jnp.zeros((bh, s_local, 128), jnp.float32)
        m = jnp.full((bh, s_local, 128), NEG_INF, jnp.float32)
        for i in range(n_chunks):
            acc, l, m = flash_attention_step(
                q, ks[:, i * s_local:(i + 1) * s_local],
                vs[:, i * s_local:(i + 1) * s_local], acc, l, m,
                q_offset=(n_chunks - 1) * s_local, k_offset=i * s_local)
        return (acc / jnp.maximum(l[:, :, :1], 1e-30)).astype(q.dtype)

    @partial(jax.jit, static_argnums=1)
    def loop(qq, n):
        def step(c, _):
            o = folded(qq + c, ks, vs)
            return (jnp.sum(o) * 1e-20).astype(qq.dtype), None
        c, _ = jax.lax.scan(step, jnp.zeros((), qq.dtype), None, length=n)
        return c

    res = scan_slope_seconds(lambda n: float(loop(q, n)), lo=4, hi=16)
    flops = n_chunks * 2 * 2 * bh * s_local * s_local * head_dim
    dt = res["seconds_per_iter"]
    return {"n_chunks": n_chunks, "s_local": s_local,
            "ms": round(dt * 1e3, 3),
            "tflops_actual": round(flops / dt / 1e12, 1)}
