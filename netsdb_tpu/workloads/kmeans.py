"""KMeans — reference ``KMeansAggregate.h``/``KMeansQuery.h`` family.

The reference runs Lloyd's algorithm as repeated executeComputations:
a selection computes each point's nearest centroid, an aggregation
groups points by centroid id summing vectors and counts
(``src/sharedLibraries/headers/KMeansAggregate.h``,
``KMeansDataCountAggregate.h``; driver ``src/tests/source/TestKMeans.cc``).
Here one jitted ``lax.fori_loop`` does all iterations on-device: assign =
argmin pairwise distance (one matmul on the MXU), update = segment-sum.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from netsdb_tpu.storage.store import SetIdentifier


def _assign(points: jax.Array, centroids: jax.Array) -> jax.Array:
    # ||p-c||² = ||p||² - 2 p·c + ||c||²; argmin over c (‖p‖² constant)
    dots = points @ centroids.T
    c2 = jnp.sum(centroids * centroids, axis=1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=1)


def kmeans(points: jax.Array, k: int, iters: int = 10,
           init_centroids: Optional[jax.Array] = None,
           seed: int = 0, init: str = "random") -> Tuple[jax.Array, jax.Array]:
    """→ (centroids (k,d), assignments (n,)). Whole loop under jit.

    ``init="sample"`` uses the reference's MLLib-compliant Bernoulli
    sampling init (``Sampler::computeFractionForSampleSize`` +
    shuffle + distinct — ``TestKMeansMLLibCompliant.cc:462-530``); k may
    shrink if the sample has duplicate points, as there.
    """
    if init not in ("random", "sample"):
        raise ValueError(f"init must be 'random' or 'sample', got {init!r}")
    n, d = points.shape
    if init_centroids is None:
        if init == "sample":
            if isinstance(points, jax.core.Tracer):
                raise ValueError(
                    "init='sample' is host-only (Bernoulli sampling has a "
                    "data-dependent size): call kmeans outside jit, or "
                    "pass init_centroids explicitly")
            import numpy as np

            from netsdb_tpu.utils.sampler import sample_k_distinct

            init_centroids = jnp.asarray(
                sample_k_distinct(np.asarray(points), k, seed=seed))
            k = int(init_centroids.shape[0])
        else:
            idx = jax.random.choice(jax.random.key(seed), n, (k,),
                                    replace=False)
            init_centroids = points[idx]

    def body(_, cents):
        assign = _assign(points, cents)
        sums = jax.ops.segment_sum(points, assign, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((n,), points.dtype), assign,
                                     num_segments=k)
        # empty cluster keeps its old centroid (reference keeps stale agg)
        return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None],
                         cents)

    cents = jax.lax.fori_loop(0, iters, body, init_centroids)
    return cents, _assign(points, cents)


def kmeans_on_set(client, db: str, set_name: str, k: int, iters: int = 10,
                  out_set: str = "kmeans_centroids", seed: int = 0):
    """Set-oriented driver (TestKMeans shape): points from a tensor set
    (n x d), centroids written back as a set."""
    pts = client.get_tensor(db, set_name)
    points = pts.to_dense()
    cents, assign = jax.jit(lambda p: kmeans(p, k, iters, seed=seed))(points)
    if not client.set_exists(db, out_set):
        client.create_set(db, out_set)
    from netsdb_tpu.core.blocked import BlockedTensor

    client.store.put_tensor(SetIdentifier(db, out_set),
                            BlockedTensor.from_dense(cents,
                                                     pts.meta.block_shape))
    return cents, assign
