"""NN elementwise/reduction ops — the FF UDF family, TPU-native.

Each function here replaces one reference join/aggregation UDF over
``FFMatrixBlock`` sets (citations per function). They are plain traced
functions, so XLA fuses them into the producing matmul — the fusion the
reference approximates with hand-written "memory fusion" UDF variants.

All ops maintain the zero-padded-margin invariant (see
``netsdb_tpu.ops.common``); shapes in comments use the reference's
layout convention for FF inference: activations are (features x batch).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockMeta, BlockedTensor
from netsdb_tpu.ops.common import neutral_fill, remask


def _broadcast_bias(x: BlockedTensor, bias: BlockedTensor) -> jax.Array:
    """Bias (n,) or (n,1) broadcast along columns of x (n,m), on padded
    arrays — bias blocks join x blocks by row-block index in the reference
    (``FFReluBiasSum.h`` join condition)."""
    b = bias.data
    if b.ndim == 1:
        b = b[:, None]
    if b.shape[0] != x.data.shape[0]:
        raise ValueError(
            f"bias rows {b.shape[0]} != x padded rows {x.data.shape[0]} "
            f"(bias must share x's row blocking)"
        )
    # compute in the activation's dtype: when the caller opted into
    # bf16 activations (matmul accum_dtype), a f32 bias must not
    # promote the whole elementwise chain back to f32
    return b.astype(x.data.dtype)


def relu(x: BlockedTensor) -> BlockedTensor:
    return x.with_data(jax.nn.relu(x.data))


def bias_relu(x: BlockedTensor, bias: BlockedTensor,
              dropout_rate: float = 0.0,
              key: Optional[jax.Array] = None) -> BlockedTensor:
    """relu(x + bias) with optional inverted dropout — reference
    ``FFReluBiasSum`` join (``src/FF/headers/FFReluBiasSum.h``)."""
    y = jax.nn.relu(x.data + _broadcast_bias(x, bias))
    if dropout_rate > 0.0:
        if key is None:
            raise ValueError("dropout requires a PRNG key")
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, y.shape)
        y = jnp.where(keep, y / (1.0 - dropout_rate), 0.0)
    # the bias broadcasts into padded batch columns → relu(bias) garbage
    # in the margin unless re-masked
    return remask(x.with_data(y))


def bias_sigmoid(x: BlockedTensor, bias: BlockedTensor) -> BlockedTensor:
    """sigmoid(x + bias) — reference ``FFTransposeBiasSumSigmoid`` (logistic
    regression head, ``SimpleFF.cc:428-499``). sigmoid(0)=0.5, so remask."""
    y = jax.nn.sigmoid(x.data + _broadcast_bias(x, bias))
    return remask(x.with_data(y))


def bias_exp(x: BlockedTensor, bias: BlockedTensor) -> BlockedTensor:
    """exp(x + bias) — reference ``FFTransposeBiasSum`` (softmax numerator
    stage of ``SimpleFF.cc:292-329``). exp(0)=1, so remask."""
    y = jnp.exp(x.data + _broadcast_bias(x, bias))
    return remask(x.with_data(y))


def row_sum(x: BlockedTensor) -> BlockedTensor:
    """Per-row sum → (n,1) — reference ``FFRowAggregate``. Single
    implementation shared with the LA op set."""
    from netsdb_tpu.ops import linalg

    return linalg.row_sum(x)


def col_sum(x: BlockedTensor) -> BlockedTensor:
    from netsdb_tpu.ops import linalg

    return linalg.col_sum(x)


def softmax(x: BlockedTensor, axis: int = 0) -> BlockedTensor:
    """Masked softmax along ``axis`` — reference ``FFOutputLayer`` join of
    the exp-matrix with row-sums (``SimpleFF.cc:292-329``); fused into one
    op with -inf padding masking (netsDB never pads, we must)."""
    logits = neutral_fill(x, -jnp.inf)
    y = jax.nn.softmax(logits, axis=axis)
    # rows/cols that are ALL padding produce NaN (softmax of all -inf)
    y = jnp.nan_to_num(y, nan=0.0, posinf=0.0, neginf=0.0)
    return remask(x.with_data(y.astype(x.data.dtype)))


def ff_output_layer(y: BlockedTensor, bias: BlockedTensor,
                    axis: int = 0) -> BlockedTensor:
    """exp(y+b) normalized along ``axis`` — the complete reference
    inference tail (``FFTransposeBiasSum`` → ``FFRowAggregate`` →
    ``FFOutputLayer``), one fused op. Uses the max-subtracted stable form
    rather than the reference's raw exp."""
    z = y.data + _broadcast_bias(y, bias)
    masked = neutral_fill(y.with_data(z), -jnp.inf)
    out = jax.nn.softmax(masked, axis=axis)
    out = jnp.nan_to_num(out, nan=0.0, posinf=0.0, neginf=0.0)
    return remask(y.with_data(out.astype(y.data.dtype)))
