"""Shared op helpers: dtype policy and padding-mask maintenance.

Invariant maintained by every op in this package: a ``BlockedTensor``'s
padded margin is ZERO. Ops whose elementwise function does not map 0→0
(sigmoid, exp, softmax) re-mask their output; masked reductions use ±inf
neutral fills. This replaces the reference's ragged last blocks
(``src/FF/headers/FFMatrixBlock.h:79-87``) — XLA needs static shapes, so
we pad and mask instead.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockedTensor


def on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def mxu_dot(a: jax.Array, b: jax.Array, compute_dtype: Optional[str] = None,
            accum_dtype=jnp.float32) -> jax.Array:
    """Matmul routed onto the MXU, accumulating in ``accum_dtype``
    (f32 unless the caller overrides it — e.g. the FF inference chain
    keeps hidden activations in bf16 to halve their HBM traffic).

    ``compute_dtype=None`` means full input-dtype accuracy: on TPU the
    MXU's DEFAULT precision decomposes f32 into single-pass bfloat16,
    which loses ~3 decimal digits — far from the reference's f64 Eigen
    results — so we force HIGHEST (multi-pass) unless the caller opts
    into reduced precision by setting ``compute_dtype='bfloat16'``."""
    if compute_dtype is not None:
        a = a.astype(compute_dtype)
        b = b.astype(compute_dtype)
        precision = jax.lax.Precision.DEFAULT
    else:
        precision = jax.lax.Precision.HIGHEST
    return jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=accum_dtype,
        precision=precision,
    )


def remask(t: BlockedTensor) -> BlockedTensor:
    """Zero the padded margin (needed after non-zero-preserving ops)."""
    if not t.meta.is_padded:
        return t
    return t.with_data(t.data * t.mask(t.data.dtype))


def neutral_fill(t: BlockedTensor, fill: float) -> jax.Array:
    """Padded data with the margin replaced by ``fill`` (for max/min
    reductions where zero is not neutral)."""
    if not t.meta.is_padded:
        return t.data
    m = t.mask(jnp.bool_)
    return jnp.where(m, t.data, jnp.asarray(fill, t.data.dtype))
