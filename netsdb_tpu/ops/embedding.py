"""Embedding lookup — the word2vec workload family, TPU-native.

The reference expresses embedding lookup as a blocked matmul of the
weight matrix against one-hot input columns (``src/word2vec/source/
Word2Vec.cc:19-80``: scan weights x scan one-hot inputs →
``FFTransposeMult`` → ``FFAggMatrix``), plus a sparse variant
``EmbeddingLookupSparse``/``EmbeddingSegment`` that averages per-segment
rows. On TPU the idiomatic lookup is a gather (``jnp.take``); the matmul
formulation is kept because (a) it is what the relational planner
produces and (b) for small vocabularies one-hot matmul on the MXU beats
gather. ``SemanticClassifier`` — a whole FC layer inside one UDF
(``src/word2vec/headers/SemanticClassifier.h``) — lives in
``netsdb_tpu.models.text_classifier``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops.matmul import matmul_t


def one_hot_matrix(ids: jax.Array, vocab: int, dtype=jnp.float32) -> jax.Array:
    """(batch, vocab) one-hot rows — the generated input sets of the
    reference word2vec test."""
    return jax.nn.one_hot(ids, vocab, dtype=dtype)


def embedding_matmul(weights: BlockedTensor, onehot: BlockedTensor,
                     compute_dtype: Optional[str] = None) -> BlockedTensor:
    """Lookup as W·onehotᵀ-style blocked matmul (reference Word2Vec.cc
    path). ``weights``: (vocab x dim) blocked; ``onehot``: (batch x vocab)
    blocked. Result: (batch x dim). The transpose is re-materialized per
    call — prefer :func:`embedding_lookup` for serving loops."""
    return matmul_t(onehot, _transpose_weights(weights), compute_dtype)


def _transpose_weights(weights: BlockedTensor) -> BlockedTensor:
    # onehot (batch x vocab) · (dim x vocab)ᵀ ≡ gather of weight rows
    from netsdb_tpu.ops.linalg import transpose

    return transpose(weights)


def embedding_lookup(weights: BlockedTensor, ids: jax.Array) -> jax.Array:
    """Gather path: rows of (vocab x dim) weights by id — the TPU-native
    formulation (XLA dynamic-gather), numerically identical to the
    one-hot matmul. Returns logical (ids..., dim) — padded weight
    columns are sliced off."""
    table = weights.to_dense()
    return jnp.take(table, ids, axis=0)


def embedding_lookup_sparse(
    weights: BlockedTensor,
    ids: jax.Array,  # (nnz,) flat token ids
    segment_ids: jax.Array,  # (nnz,) ascending example ids
    num_segments: int,
    combiner: str = "mean",
) -> jax.Array:
    """Segment-combined sparse lookup — reference
    ``EmbeddingLookupSparse.h``/``EmbeddingSegment.h`` (bag-of-words text
    classification front end). Returns (num_segments, dim)."""
    rows = jnp.take(weights.to_dense(), ids, axis=0)  # (nnz, dim)
    summed = jax.ops.segment_sum(rows, segment_ids, num_segments)
    if combiner == "sum":
        return summed
    counts = jax.ops.segment_sum(jnp.ones_like(segment_ids, dtype=rows.dtype),
                                 segment_ids, num_segments)
    if combiner == "mean":
        return summed / jnp.maximum(counts, 1.0)[:, None]
    if combiner == "sqrtn":
        return summed / jnp.sqrt(jnp.maximum(counts, 1.0))[:, None]
    raise ValueError(combiner)
