"""Linear-algebra op set — every operator of the reference LA DSL.

One function per ``LASilly*`` UDF library (reference
``src/sharedLibraries/headers/LASilly*.h``, built as per-op .so files by
``SConstruct:393-700``) and per PDML grammar production
(``src/linearAlgebraDSL/source/LALexer.l``, ``LAParser.y``; operator
inventory demonstrated by ``DSLSamples/sample00_Parser.pdml``):

    + - * '* %*% ^T ^-1  max min rowMax rowMin rowSum colMax colMin colSum
    duplicateRow duplicateCol  load zeros ones identity

In the reference each op is a join or aggregation over blocks (e.g. add =
equi-join on (rowIdx,colIdx) + elementwise Eigen add); here each is one
traced jnp op on the padded array, masked where zero-padding is not
neutral.
"""

from __future__ import annotations

import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockMeta, BlockedTensor
from netsdb_tpu.ops.common import neutral_fill
from netsdb_tpu.ops.matmul import matmul, matmul_t, t_matmul  # noqa: F401  (re-export)


def _aligned(a: BlockedTensor, b: BlockedTensor) -> None:
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    if a.meta.block_shape != b.meta.block_shape:
        raise ValueError(
            f"block mismatch {a.meta.block_shape} vs {b.meta.block_shape}; reblock first"
        )


def add(a: BlockedTensor, b: BlockedTensor) -> BlockedTensor:
    """A + B — ref ``LASillyAddJoin.h``."""
    _aligned(a, b)
    return a.with_data(a.data + b.data)


def subtract(a: BlockedTensor, b: BlockedTensor) -> BlockedTensor:
    """A - B — ref ``LASillySubstractJoin.h``."""
    _aligned(a, b)
    return a.with_data(a.data - b.data)


def scale_multiply(a: BlockedTensor, b: BlockedTensor) -> BlockedTensor:
    """Elementwise A * B (the DSL ``*``) — ref ``LASillyScaleMultiplyJoin.h``."""
    _aligned(a, b)
    return a.with_data(a.data * b.data)


def scalar_multiply(a: BlockedTensor, s: float) -> BlockedTensor:
    return a.with_data(a.data * s)


def transpose(a: BlockedTensor) -> BlockedTensor:
    """Aᵀ — ref ``LASillyTransposeSelection.h`` (swaps block indices)."""
    meta = BlockMeta(a.shape[::-1], a.meta.block_shape[::-1])
    return BlockedTensor(jnp.swapaxes(a.data, 0, 1), meta)


def max_element(a: BlockedTensor) -> jnp.ndarray:
    """Global max — ref ``LASillyMaxElementAggregate.h``. Scalar result
    (the reference writes an ``LAMaxElementOutputType`` set)."""
    return jnp.max(neutral_fill(a, -jnp.inf))


def min_element(a: BlockedTensor) -> jnp.ndarray:
    """Global min — ref ``LASillyMinElementAggregate.h``."""
    return jnp.min(neutral_fill(a, jnp.inf))


def row_max(a: BlockedTensor) -> BlockedTensor:
    """Per-row max → (n,1) — ref ``LASillyRowMaxAggregate.h``."""
    return _row_reduce(a, jnp.max, -jnp.inf)


def row_min(a: BlockedTensor) -> BlockedTensor:
    return _row_reduce(a, jnp.min, jnp.inf)


def row_sum(a: BlockedTensor) -> BlockedTensor:
    return _row_reduce(a, jnp.sum, 0.0)


def col_max(a: BlockedTensor) -> BlockedTensor:
    """Per-col max → (1,m) — ref ``LASillyColMaxAggregate.h``."""
    return _col_reduce(a, jnp.max, -jnp.inf)


def col_min(a: BlockedTensor) -> BlockedTensor:
    return _col_reduce(a, jnp.min, jnp.inf)


def col_sum(a: BlockedTensor) -> BlockedTensor:
    return _col_reduce(a, jnp.sum, 0.0)


def _row_reduce(a, fn, fill) -> BlockedTensor:
    data = neutral_fill(a, fill) if fill != 0.0 else a.data
    r = fn(data, axis=1, keepdims=True)
    # rows that are pure padding: neutralize to 0 for the margin invariant
    if a.meta.is_padded:
        rows = jnp.arange(a.meta.padded_shape[0])[:, None] < a.shape[0]
        r = jnp.where(rows, r, 0.0).astype(a.data.dtype)
    return BlockedTensor(r, BlockMeta((a.shape[0], 1), (a.meta.block_shape[0], 1)))


def _col_reduce(a, fn, fill) -> BlockedTensor:
    data = neutral_fill(a, fill) if fill != 0.0 else a.data
    r = fn(data, axis=0, keepdims=True)
    if a.meta.is_padded:
        cols = jnp.arange(a.meta.padded_shape[1])[None, :] < a.shape[1]
        r = jnp.where(cols, r, 0.0).astype(a.data.dtype)
    return BlockedTensor(r, BlockMeta((1, a.shape[1]), (1, a.meta.block_shape[1])))


def duplicate_row(v: BlockedTensor, n_rows: int, block_rows: int) -> BlockedTensor:
    """Tile a (1,m) row vector to (n_rows, m) — ref
    ``LASillyDuplicateRowMultiSelection.h`` (used by sample03_NN:
    ``X - duplicateRow(t,100,10)``)."""
    row = v.to_dense().reshape(1, -1)
    return BlockedTensor.from_dense(
        jnp.broadcast_to(row, (n_rows, row.shape[1])),
        (block_rows, v.meta.block_shape[1]),
    )


def duplicate_col(v: BlockedTensor, n_cols: int, block_cols: int) -> BlockedTensor:
    """Tile a (n,1) col vector to (n, n_cols) — ref
    ``LASillyDuplicateColMultiSelection.h``."""
    col = v.to_dense().reshape(-1, 1)
    return BlockedTensor.from_dense(
        jnp.broadcast_to(col, (col.shape[0], n_cols)),
        (v.meta.block_shape[0], block_cols),
    )


def identity(n: int, block: int, dtype=jnp.float32) -> BlockedTensor:
    """identity(n, block) — ref DSL TOKEN_IDENTITY."""
    return BlockedTensor.from_dense(jnp.eye(n, dtype=dtype), (block, block))


def zeros(rows: int, cols: int, brows: int, bcols: int, dtype=jnp.float32):
    return BlockedTensor.zeros((rows, cols), (brows, bcols), dtype)


def ones(rows: int, cols: int, brows: int, bcols: int, dtype=jnp.float32):
    return BlockedTensor.from_dense(
        jnp.ones((rows, cols), dtype=dtype), (brows, bcols)
    )


def inverse(a: BlockedTensor) -> BlockedTensor:
    """A⁻¹ (DSL ``^-1``). The reference restricts inversion to
    single-block matrices (``LASillyInverse1Aggregate.h`` gathers all
    blocks into one, Eigen-inverts, re-splits via Inverse2/Inverse3) —
    we invert the dense logical matrix (any blocking) which strictly
    subsumes that."""
    if a.shape[0] != a.shape[1]:
        raise ValueError(f"inverse of non-square {a.shape}")
    inv = jnp.linalg.inv(a.to_dense().astype(jnp.float32))
    return BlockedTensor.from_dense(inv.astype(a.data.dtype), a.meta.block_shape)
