"""LSTM cell — the reference's recurrent workload, TPU-native.

The reference expresses ONE LSTM cell as a computation DAG over
``FFMatrixBlock`` sets: 8 blocked matmuls (x and h against the 4 gate
weights, each ``FFInputLayerJoin``+``FFAggMatrix``), gate fusion
``LSTMThreeWaySum`` (gate = act(xW + hU + b)), cell-state update
``LSTMTwoSum``/``LSTMHiddenState`` (c' = f⊙c + i⊙g, h' = o⊙tanh c')
(reference ``src/LSTM/headers/LSTMThreeWaySum.h``, ``LSTMHiddenState.h``;
driver ``src/tests/source/LSTMTest.cc``). Here the whole cell is one
traced function — XLA fuses the gate elementwise chain into the matmuls,
and the 8 matmuls ride the MXU.

Layout follows the reference: activations are (features x batch); weight
W_* is (hidden x input), U_* is (hidden x hidden), biases (hidden x 1).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops.matmul import matmul


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LSTMParams:
    """The 12 weight sets the reference LSTMTest creates
    (w_{i,f,c,o}, u_{i,f,c,o}, b_{i,f,c,o})."""

    w_i: BlockedTensor
    w_f: BlockedTensor
    w_c: BlockedTensor
    w_o: BlockedTensor
    u_i: BlockedTensor
    u_f: BlockedTensor
    u_c: BlockedTensor
    u_o: BlockedTensor
    b_i: BlockedTensor
    b_f: BlockedTensor
    b_c: BlockedTensor
    b_o: BlockedTensor


def three_way_sum(wx: BlockedTensor, uh: BlockedTensor, b: BlockedTensor,
                  activation: str) -> jax.Array:
    """gate = act(wx + uh + b) — reference ``LSTMThreeWaySum`` join."""
    z = wx.data + uh.data + (b.data if b.data.ndim == 2 else b.data[:, None])
    if activation == "sigmoid":
        return jax.nn.sigmoid(z)
    if activation == "tanh":
        return jnp.tanh(z)
    raise ValueError(activation)


def lstm_cell(
    params: LSTMParams,
    x: BlockedTensor,  # (input x batch)
    h: BlockedTensor,  # (hidden x batch)
    c: BlockedTensor,  # (hidden x batch)
    compute_dtype: Optional[str] = None,
) -> Tuple[BlockedTensor, BlockedTensor]:
    """One cell step → (h', c'). Biases broadcast into padded batch
    columns (g=tanh(b_c)≠0 times i=sigmoid(b_i)≠0), so the states are
    re-masked to keep the zero-margin invariant — it would otherwise
    compound across scan steps."""
    from netsdb_tpu.ops.common import remask

    mm = lambda w, v: matmul(w, v, compute_dtype)
    i = three_way_sum(mm(params.w_i, x), mm(params.u_i, h), params.b_i, "sigmoid")
    f = three_way_sum(mm(params.w_f, x), mm(params.u_f, h), params.b_f, "sigmoid")
    g = three_way_sum(mm(params.w_c, x), mm(params.u_c, h), params.b_c, "tanh")
    o = three_way_sum(mm(params.w_o, x), mm(params.u_o, h), params.b_o, "sigmoid")
    c_new = f * c.data + i * g  # reference LSTMTwoSum + LSTMHiddenState
    h_new = o * jnp.tanh(c_new)
    return (
        remask(h.with_data(h_new.astype(h.data.dtype))),
        remask(c.with_data(c_new.astype(c.data.dtype))),
    )


def lstm_unroll(params: LSTMParams, xs, h0: BlockedTensor, c0: BlockedTensor,
                compute_dtype: Optional[str] = None):
    """Run the cell over a sequence with ``lax.scan`` (compiler-friendly
    loop; the reference re-runs its DAG per step from the driver).
    ``xs``: array (T, input_padded, batch_padded) sharing x's blocking."""
    from netsdb_tpu.core.blocked import BlockMeta

    x_meta = BlockMeta(
        (params.w_i.shape[1], h0.shape[1]),
        (params.w_i.meta.block_shape[1], h0.meta.block_shape[1]),
    )

    def step(carry, x_t):
        h, c = carry
        h2, c2 = lstm_cell(params, BlockedTensor(x_t, x_meta), h, c,
                           compute_dtype)
        return (h2, c2), h2.data

    (h, c), hs = jax.lax.scan(step, (h0, c0), xs)
    return h, c, hs
