"""Conv2D — both reference execution modes, TPU-native.

Mode 1, "UDF-encapsulated": the reference wraps a whole conv in one
``Conv2DSelect`` SelectionComp that calls ATen ``at::conv2d`` (or a
hand-rolled Eigen spatial loop) per ``TensorData`` object
(``src/conv2d_proj/headers/Conv2DSelect.h:13-216``). TPU equivalent:
``lax.conv_general_dilated``, which XLA lowers straight onto the MXU.

Mode 2, "memory fusion" / relational rewrite: conv as matmul via im2col —
MultiSelections ``ImageToChunks``/``ImageBlockToMatrix`` flatten image
patches to a matrix, ``KernelToChunks`` flattens filters, then the
standard FFTransposeMult+FFAggMatrix blocked matmul, then
``ConvChunksToImage`` reassembles (``src/conv2d_memory_fusion``; driver
``src/tests/source/PipelinedConv2dMemFuseTest.cc:137-299``). TPU
equivalent below: an explicit patch-extraction + one ``dot_general`` —
kept because it exercises the blocked-matmul path and is the shape the
framework's relational planner produces.

Layouts: images NCHW, kernels OIHW (reference conv2d README defaults:
112x112x3 images, 64 7x7x3 filters).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

Padding = Union[str, Tuple[int, int]]


def _pad_pair(padding: Padding, k: int, in_size: int, stride: int) -> Tuple[int, int]:
    if padding == "SAME":
        # stride-aware SAME: output ceil(in/s) positions
        total = max((-(-in_size // stride) - 1) * stride + k - in_size, 0)
        return (total // 2, total - total // 2)
    if padding == "VALID":
        return (0, 0)
    return tuple(padding)


def conv2d_direct(
    images: jax.Array,  # (N, C, H, W)
    kernels: jax.Array,  # (O, I, KH, KW)
    bias: Optional[jax.Array] = None,  # (O,)
    stride: Tuple[int, int] = (1, 1),
    padding: Padding = "VALID",
    activation: Optional[str] = None,
    compute_dtype: Optional[str] = None,
) -> jax.Array:
    """Reference mode 1 (``Conv2DSelect::computeConvOpATen``), one XLA conv."""
    if compute_dtype is not None:
        images = images.astype(compute_dtype)
        kernels = kernels.astype(compute_dtype)
        precision = jax.lax.Precision.DEFAULT
    else:
        precision = jax.lax.Precision.HIGHEST  # see ops.common.mxu_dot
    pads = (
        _pad_pair(padding, kernels.shape[2], images.shape[2], stride[0]),
        _pad_pair(padding, kernels.shape[3], images.shape[3], stride[1]),
    )
    out = jax.lax.conv_general_dilated(
        images, kernels, window_strides=stride, padding=pads,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    if activation == "relu":
        out = jax.nn.relu(out)
    elif activation == "sigmoid":
        out = jax.nn.sigmoid(out)
    return out


def im2col(
    images: jax.Array,  # (N, C, H, W)
    kh: int, kw: int,
    stride: Tuple[int, int] = (1, 1),
    padding: Padding = "VALID",
) -> Tuple[jax.Array, Tuple[int, int]]:
    """Patch matrix (N*OH*OW, C*KH*KW) — the ``ImageToChunks`` →
    ``ImageBlockToMatrix`` rewrite (``src/conv2d_memory_fusion/headers/
    ImageBlockToMatrix.h``). Returns (matrix, (OH, OW))."""
    n, c, h, w = images.shape
    sh, sw = stride
    ph = _pad_pair(padding, kh, h, sh)
    pw = _pad_pair(padding, kw, w, sw)
    x = jnp.pad(images, ((0, 0), (0, 0), ph, pw))
    oh = (x.shape[2] - kh) // sh + 1
    ow = (x.shape[3] - kw) // sw + 1
    # Patch extraction as KH*KW strided slices + stack. This row-major
    # (positions, features) matrix is what the staged relational
    # pipeline (workloads/conv_fusion.py) blocks into sets; the fused
    # conv2d_im2col below uses conv_general_dilated_patches instead,
    # whose (features, positions) layout feeds the contraction without
    # the transpose this layout needs.
    cols = jnp.stack(
        [x[:, :, di:di + (oh - 1) * sh + 1:sh, dj:dj + (ow - 1) * sw + 1:sw]
         for di in range(kh) for dj in range(kw)],
        axis=2)  # (N, C, KH*KW, OH, OW), feature order (C, KH, KW)
    mat = cols.transpose(0, 3, 4, 1, 2).reshape(n * oh * ow, c * kh * kw)
    return mat, (oh, ow)


def conv2d_im2col(
    images: jax.Array,  # (N, C, H, W)
    kernels: jax.Array,  # (O, I, KH, KW)
    bias: Optional[jax.Array] = None,
    stride: Tuple[int, int] = (1, 1),
    padding: Padding = "VALID",
    activation: Optional[str] = None,
    block_shape: Tuple[int, int] = (256, 256),
    compute_dtype: Optional[str] = None,
) -> jax.Array:
    """Reference mode 2: im2col + matmul + fold
    (``PipelinedConv2dMemFuseTest.cc:137-299`` pipeline as one function:
    ImageToChunks→ImageBlockToMatrix→KernelBiasJoin→FFTransposeMult→
    FFAggMatrix→ConvChunksToImage). ``block_shape`` is accepted for API
    symmetry with the staged pipeline (``workloads/conv_fusion.py``,
    which materializes actual blocked sets) but the fused op contracts
    the patch axis directly — the K dim is tiny (C*KH*KW), so routing
    through BlockedTensor would zero-pad it to the block size and waste
    most of the MXU contraction.

    Patch extraction uses ``lax.conv_general_dilated_patches`` at
    HIGHEST precision — exact (identity 0/1 kernel) and it emits
    patches in (N, C*KH*KW, OH*OW) layout, features ready to contract
    and positions minor, so no giant permute follows. Measured 7.07 ms
    device p50 at the reference shapes (256x112x112x3, 64 7x7 filters)
    vs 11.8 ms for round-1's slice-stack + transpose and 3.64 ms for
    mode-1 direct conv; the remaining gap to direct is the 1.7 GB
    patch-matrix HBM round-trip this mode exists to materialize (exact
    f32 floor ~5 ms at full bandwidth; pallas fusion attempts hit
    Mosaic limits — offset-concat unimplemented, VMEM overflow)."""
    n = images.shape[0]
    o, i, kh, kw = kernels.shape
    sh, sw = stride
    pads = (_pad_pair(padding, kh, images.shape[2], sh),
            _pad_pair(padding, kw, images.shape[3], sw))
    kmat = kernels.reshape(o, i * kh * kw)
    if compute_dtype is not None:
        images = images.astype(compute_dtype)
        kmat = kmat.astype(compute_dtype)
        precision = jax.lax.Precision.DEFAULT
    else:
        precision = jax.lax.Precision.HIGHEST
    patches = jax.lax.conv_general_dilated_patches(
        images, (kh, kw), stride, pads,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision)  # (N, C*KH*KW, OH, OW), feature order (C, KH, KW)
    oh, ow = patches.shape[2], patches.shape[3]
    mat = patches.reshape(n, i * kh * kw, oh * ow)
    out = jax.lax.dot_general(
        mat, kmat, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )  # (N, OH*OW, O)
    if bias is not None:
        out = out + bias[None, None, :]
    if activation == "relu":
        out = jax.nn.relu(out)
    elif activation == "sigmoid":
        out = jax.nn.sigmoid(out)
    return out.transpose(0, 2, 1).reshape(n, o, oh, ow)
