"""Op layer — one traced TPU-native function per reference UDF family.

Reference → here:

- ``FFTransposeMult``/``FFInputLayerJoin`` + ``FFAggMatrix`` → :mod:`.matmul`
- ``FFReluBiasSum``/``FFTransposeBiasSum*``/``FFRowAggregate``/``FFOutputLayer``
  → :mod:`.nn`
- the 25 ``LASilly*`` DSL ops → :mod:`.linalg`
- ``Conv2DSelect`` (ATen) and ``conv2d_memory_fusion`` (im2col) → :mod:`.conv`
- ``LSTMThreeWaySum``/``LSTMHiddenState`` → :mod:`.lstm`
- ``Word2Vec``/``EmbeddingLookupSparse`` → :mod:`.embedding`
"""

from netsdb_tpu.ops import conv, embedding, linalg, lstm, nn
from netsdb_tpu.ops.matmul import gram, matmul, matmul_t, t_matmul

__all__ = [
    "conv", "embedding", "linalg", "lstm", "nn",
    "gram", "matmul", "matmul_t", "t_matmul",
]
