"""Pallas TPU kernels for the hot ops.

The reference hand-writes its per-block math in Eigen inside join UDFs
(``FFTransposeMult.h:80-92``); the TPU analogue of "hand-tuned inner
loop" is a pallas kernel. XLA already fuses the elementwise chains this
framework emits, so pallas is reserved for the patterns XLA cannot
schedule optimally by itself — above all attention, where the online-
softmax accumulator must live in VMEM across k-blocks instead of
round-tripping (S x S) logits through HBM.

``flash_attention`` follows the standard TPU flash pattern: grid
(batch*heads, q_blocks, k_blocks) with the k-block dimension innermost
(sequential on TPU), accumulators (m, l, acc) in VMEM scratch carried
across k iterations, causal blocks skipped entirely when fully masked.
Falls back to interpret mode off-TPU so tests run on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, causal: bool, scale: float,
                  num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    # causal: the block is fully masked iff its first key position is
    # beyond the last query position — skip all compute
    live = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(live)
    def _compute():
        # dtype policy matches ops.common.mxu_dot: f32 inputs run the MXU
        # multi-pass (HIGHEST, exact); bf16 inputs are the reduced-
        # precision opt-in and ride the native bf16 path
        precision = (jax.lax.Precision.HIGHEST
                     if q_ref.dtype == jnp.float32
                     else jax.lax.Precision.DEFAULT)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision) * scale  # (block_q, block_k) f32
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_prev = m_ref[:]
        block_max = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(logits - m_new)
        correction = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)
        m_ref[:] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(
            o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    out_vma=None) -> jax.Array:
    """Fused attention: q/k/v (B, H, S, D) → (B, H, S, D). Numerically
    equivalent to ``ops.attention.attention``; never materializes the
    (S, S) score matrix in HBM.

    Default 1024x1024 blocks measured fastest on v5e at D=128 (95
    TFLOP/s vs 32 at 256x256 — bigger tiles amortize the scratch
    read-modify-write per k-step; 2048-square tiles exceed VMEM)."""
    import math

    b, h, s, d = q.shape
    # shrink defaulted blocks to divisors of s (gcd keeps the largest
    # power-of-two factor, so e.g. s=2560 with the 1024 default runs
    # 512-blocks); an explicitly tuned block that does not divide s is
    # a caller mistake — warn rather than silently run a slower tile
    explicit_q, explicit_k = block_q is not None, block_k is not None
    block_q = block_q if explicit_q else 1024
    block_k = block_k if explicit_k else 1024
    gq, gk = math.gcd(min(block_q, s), s), math.gcd(min(block_k, s), s)
    changed = [f"block_q {block_q}->{gq}"] if explicit_q and gq != block_q else []
    if explicit_k and gk != block_k:
        changed.append(f"block_k {block_k}->{gk}")
    if changed:
        import warnings

        warnings.warn(
            f"flash_attention: explicitly requested block size does not "
            f"divide seq {s}; falling back ({', '.join(changed)})",
            stacklevel=2)
    block_q, block_k = gq, gk
    if block_q < 8 or block_k < 8:
        raise ValueError(
            f"seq {s} shares no usable block size with requested blocks "
            f"(gcd gives {block_q}, {block_k}; need >= 8 sublanes)")
    if interpret is None:
        from netsdb_tpu.ops.common import on_tpu

        interpret = not on_tpu()
    scale = scale if scale is not None else d ** -0.5
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    num_q = s // block_q
    num_k = s // block_k

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, num_k_blocks=num_k)

    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, qi, ki: (b_, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, qi, ki: (b_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, qi, ki: (b_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, qi, ki: (b_, qi, 0)),
        # inside a shard_map manual region, shard_map's vma check needs
        # to know which mesh axes the output varies over — callers there
        # pass out_vma={axis_name} (see parallel.ring ulysses path)
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), q.dtype,
                                        vma=frozenset(out_vma))
                   if out_vma else
                   jax.ShapeDtypeStruct((bh, s, d), q.dtype)),
        scratch_shapes=[
            _vmem((block_q, 1), jnp.float32),   # running max m
            _vmem((block_q, 1), jnp.float32),   # running denom l
            _vmem((block_q, d), jnp.float32),   # running numerator acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)
