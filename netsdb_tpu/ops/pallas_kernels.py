"""Pallas TPU kernels for the hot ops.

The reference hand-writes its per-block math in Eigen inside join UDFs
(``FFTransposeMult.h:80-92``); the TPU analogue of "hand-tuned inner
loop" is a pallas kernel. XLA already fuses the elementwise chains this
framework emits, so pallas is reserved for the patterns XLA cannot
schedule optimally by itself — above all attention, where the online-
softmax accumulator must live in VMEM across k-blocks instead of
round-tripping (S x S) logits through HBM.

``flash_attention`` follows the standard TPU flash pattern: grid
(batch*heads, q_blocks, k_blocks) with the k-block dimension innermost
(sequential on TPU), accumulators (m, l, acc) in VMEM scratch carried
across k iterations, causal blocks skipped entirely when fully masked.
Falls back to interpret mode off-TPU so tests run on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
_LOG2E = 1.4426950408889634  # softmax runs in the exp2 domain: one VPU
# exp2 replaces exp (which lowers to exp2 * extra multiply per element)


def _prescale_q(q, scale):
    """Fold (softmax_scale * log2 e) into q ONCE per element — outside
    the kernel, where XLA fuses it into the producing op. The fold in
    `_fold_block` then emits logits directly in the exp2 domain with no
    per-logit multiply (s^2/2 VPU ops saved; +4.6 TFLOP/s at 8k causal
    bf16 on v5e). Numerics: f32 inputs scale exactly as before (one f32
    multiply, just hoisted). bf16 inputs pay ONE extra bf16 rounding of
    the scaled q per element (~2^-9 relative) that the old post-dot f32
    multiply did not have — within the bf16 path's existing oracle
    tolerances, traded for the per-logit multiply."""
    return (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)


def _fold_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                q_start, k_start, block_q: int, block_k: int,
                causal: bool):
    """The shared online-softmax fold: combine one (q-block, k-block)
    pair into the VMEM accumulators (m, l, acc) — used verbatim by both
    the single-chip kernel and the ring-step carry kernel so their
    numerics cannot diverge. ``q_start``/``k_start`` are GLOBAL
    positions (ints or traced scalars). q must arrive PRE-SCALED by
    (softmax_scale * log2 e) — see `_prescale_q`."""

    def _compute(masked: bool):
        # dtype policy matches ops.common.mxu_dot: f32 inputs run the MXU
        # multi-pass (HIGHEST, exact); bf16 inputs are the reduced-
        # precision opt-in and ride the native bf16 path
        precision = (jax.lax.Precision.HIGHEST
                     if q_ref.dtype == jnp.float32
                     else jax.lax.Precision.DEFAULT)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        # logits arrive directly in the exp2 domain: the WRAPPERS
        # pre-multiply q by (scale * log2 e) once per q element, so the
        # per-logit scalar multiply that used to follow this dot is
        # gone — s^2/2 VPU multiplies saved, measured +4.6 TFLOP/s at
        # 8k causal bf16 on v5e (112.2 -> 116.8)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_prev = m_ref[:]
        block_max = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp2(logits - m_new)
        correction = jnp.exp2(m_prev - m_new)
        l_ref[:] = l_ref[:] * correction + jnp.sum(p, axis=1, keepdims=True)
        # the P·V dot rides the MXU in the input dtype (bf16 inputs →
        # native bf16 pass; f32 inputs keep the exact path)
        pv = p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)
        m_ref[:] = m_new

    if not causal:
        _compute(False)
        return
    # fully-masked blocks (first key beyond the last query) skip all
    # compute; only DIAGONAL blocks are partially masked — the bulk of
    # the lower triangle runs the unmasked path, skipping the iota
    # compare + select (measured +2.5 TFLOP/s at 8k on v5e)
    live = q_start + block_q - 1 >= k_start
    diag = live & (q_start < k_start + block_k - 1)

    @pl.when(diag)
    def _compute_diag():
        _compute(True)

    @pl.when(live & ~diag)
    def _compute_full():
        _compute(False)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, causal: bool,
                  num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _fold_block(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                qi * block_q, ki * block_k, block_q, block_k, causal)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(
            o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    out_vma=None) -> jax.Array:
    """Fused attention: q/k/v (B, H, S, D) → (B, H, S, D). Numerically
    equivalent to ``ops.attention.attention``; never materializes the
    (S, S) score matrix in HBM.

    Default 1024x1024 blocks measured fastest on v5e at D=128: 116.4
    TFLOP/s useful (causal-halved) @8k bf16 after the exp2-domain
    softmax with q PRE-SCALED by (scale*log2e) outside the kernel
    (r3: kills the per-logit scalar multiply, +4.6 TFLOP/s),
    native-bf16 P·V pass, and diagonal-only masking — a full sweep of
    other block shapes all measured slower (512x1024: 97.7, 2048x512:
    65.2; 2048-square exceeds VMEM). jax's own reference TPU flash
    kernel (TUNED BlockSizes — its defaults are ~7x slower) measures
    119.6 at 8k and 99.6 at 4k, where this kernel now reads 100.6 —
    parity to +1%: the remaining 8k gap (~3%) and the ~60% MFU cap are
    the v5e VPU softmax chain that cannot overlap the two MXU passes.
    A triangular-grid variant that schedules only lower-triangle
    blocks measured the same — dead blocks were already free — and was
    removed. The `attention-bench` guard asserts flash >= 0.92x of the
    tuned jax kernel at 8k so these claims stay earned."""
    import math

    b, h, s, d = q.shape
    # shrink defaulted blocks to divisors of s (gcd keeps the largest
    # power-of-two factor, so e.g. s=2560 with the 1024 default runs
    # 512-blocks); an explicitly tuned block that does not divide s is
    # a caller mistake — warn rather than silently run a slower tile
    explicit_q, explicit_k = block_q is not None, block_k is not None
    block_q = block_q if explicit_q else 1024
    block_k = block_k if explicit_k else 1024
    gq, gk = math.gcd(min(block_q, s), s), math.gcd(min(block_k, s), s)
    changed = [f"block_q {block_q}->{gq}"] if explicit_q and gq != block_q else []
    if explicit_k and gk != block_k:
        changed.append(f"block_k {block_k}->{gk}")
    if changed:
        import warnings

        warnings.warn(
            f"flash_attention: explicitly requested block size does not "
            f"divide seq {s}; falling back ({', '.join(changed)})",
            stacklevel=2)
    block_q, block_k = gq, gk
    if block_q < 8 or block_k < 8:
        raise ValueError(
            f"seq {s} shares no usable block size with requested blocks "
            f"(gcd gives {block_q}, {block_k}; need >= 8 sublanes)")
    if interpret is None:
        from netsdb_tpu.ops.common import on_tpu

        interpret = not on_tpu()
    scale = scale if scale is not None else d ** -0.5
    bh = b * h
    qf = _prescale_q(q.reshape(bh, s, d), scale)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    num_q = s // block_q
    num_k = s // block_k

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal,
        num_k_blocks=num_k)

    out_shape = (jax.ShapeDtypeStruct((bh, s, d), q.dtype,
                                      vma=frozenset(out_vma))
                 if out_vma else
                 jax.ShapeDtypeStruct((bh, s, d), q.dtype))
    scratch = [
        _vmem((block_q, 1), jnp.float32),   # running max m
        _vmem((block_q, 1), jnp.float32),   # running denom l
        _vmem((block_q, d), jnp.float32),   # running numerator acc
    ]
    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, qi, ki: (b_, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, qi, ki: (b_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, qi, ki: (b_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, qi, ki: (b_, qi, 0)),
        # inside a shard_map manual region, shard_map's vma check needs
        # to know which mesh axes the output varies over — callers there
        # pass out_vma={axis_name} (see parallel.ring ulysses path)
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        # bh and q-blocks are independent; only the k dimension carries
        # the online-softmax state — tell Mosaic so it can pipeline
        compiler_params=None if interpret else _tpu_params(
            ("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _tpu_params(semantics):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(dimension_semantics=semantics)


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


# ------------------------------------------------------- ring-step kernel

def _flash_carry_kernel(off_ref, q_ref, k_ref, v_ref,
                        acc_in_ref, l_in_ref, m_in_ref,
                        acc_out_ref, l_out_ref, m_out_ref,
                        m_s, l_s, acc_s, *,
                        block_q: int, block_k: int, causal: bool,
                        num_k_blocks: int):
    """One ring-attention step: fold a rotating k/v chunk into the
    online-softmax carry (acc, l, m), all in VMEM across this chunk's
    k-blocks. Positions are GLOBAL: ``off_ref`` holds (q_offset,
    k_offset) — traced per-device values inside shard_map, which is why
    they arrive as an operand instead of compile-time constants.

    Carry convention: m and l live in the exp2 domain (pre-scaled by
    log2 e), matching :func:`_flash_kernel`; the caller finalizes with
    ``acc / l`` after the last step. m/l arrays are lane-padded to 128
    with only lane 0 meaningful (TPU blocks need a full lane dim)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_off = off_ref[0, 0]
    k_off = off_ref[0, 1]

    @pl.when(ki == 0)
    def _init():
        m_s[:] = m_in_ref[0][:, :1]
        l_s[:] = l_in_ref[0][:, :1]
        acc_s[:] = acc_in_ref[0]

    _fold_block(q_ref, k_ref, v_ref, m_s, l_s, acc_s,
                q_off + qi * block_q, k_off + ki * block_k,
                block_q, block_k, causal)

    @pl.when(ki == num_k_blocks - 1)
    def _write():
        acc_out_ref[0] = acc_s[:]
        l_out_ref[0] = jnp.broadcast_to(l_s[:], l_out_ref[0].shape)
        m_out_ref[0] = jnp.broadcast_to(m_s[:], m_out_ref[0].shape)


def flash_attention_step(q: jax.Array, k: jax.Array, v: jax.Array,
                         acc: jax.Array, l: jax.Array, m: jax.Array,
                         q_offset, k_offset,
                         causal: bool = True,
                         scale: Optional[float] = None,
                         interpret: Optional[bool] = None,
                         out_vma=None):
    """Fold one k/v chunk into a running flash accumulator — the pallas
    ring-attention step (:mod:`netsdb_tpu.parallel.ring` rotates k/v
    with ppermute and calls this per arriving chunk, replacing the
    naive ``_block_attn`` fold the round-1 ring used).

    q (bh, s_q, d); k/v (bh, s_k, d); acc (bh, s_q, d) f32;
    l/m (bh, s_q, 128) f32 lane-padded (lane 0 meaningful).
    Returns updated (acc, l, m). Finalize with
    ``acc / max(l[..., :1], tiny)`` after the last chunk.
    """
    import math

    bh, s_q, d = q.shape
    s_k = k.shape[1]
    if interpret is None:
        from netsdb_tpu.ops.common import on_tpu

        interpret = not on_tpu()
    scale = scale if scale is not None else d ** -0.5
    q = _prescale_q(q, scale)
    block_q = math.gcd(1024, s_q)
    block_k = math.gcd(1024, s_k)
    num_q = s_q // block_q
    num_k = s_k // block_k
    off = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                     jnp.asarray(k_offset, jnp.int32)]).reshape(1, 2)

    kernel = functools.partial(
        _flash_carry_kernel, block_q=block_q, block_k=block_k,
        causal=causal, num_k_blocks=num_k)

    def shp(arr):
        if out_vma:
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                        vma=frozenset(out_vma))
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    qspec = pl.BlockSpec((1, block_q, d), lambda b_, qi, ki: (b_, qi, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b_, qi, ki: (b_, ki, 0))
    lspec = pl.BlockSpec((1, block_q, 128), lambda b_, qi, ki: (b_, qi, 0))
    acc2, l2, m2 = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[pl.BlockSpec((1, 2), lambda b_, qi, ki: (0, 0)),
                  qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=(qspec, lspec, lspec),
        out_shape=(shp(acc), shp(l), shp(m)),
        scratch_shapes=[
            _vmem((block_q, 1), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
            _vmem((block_q, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _tpu_params(
            ("parallel", "parallel", "arbitrary")),
    )(off, q, k, v, acc, l, m)
    return acc2, l2, m2
