"""Blocked matmul — the heart of netsDB's in-database inference, TPU-native.

The reference computes C = A·Bᵀ as a relational plan: equi-join
``FFMatrixBlock``s on the contraction block index, per-pair Eigen GEMM in
the join projection, then ``FFAggMatrix`` cluster-aggregation summing
partial products by output block index (reference
``src/FF/headers/FFTransposeMult.h:38-92``, ``FFInputLayerJoin.h``,
``FFAggMatrix.h:11-30``) — SUMMA expressed as join+groupby, shuffled over
TCP. On TPU the whole join+aggregate collapses into ONE
``lax.dot_general`` on the padded arrays: XLA tiles it onto the MXU and,
under a sharded mesh (see ``netsdb_tpu.parallel``), inserts the
psum-over-contraction collective that the reference's shuffle performed
by hand.

Zero padding is safe under contraction, so no masking is needed here;
output metadata keeps the logical (unpadded) shape.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from netsdb_tpu.core.blocked import BlockMeta, BlockedTensor
from netsdb_tpu.ops.common import mxu_dot


def _contract(ad, bd, a_pad_k, b_pad_k, k, compute_dtype, accum_dtype=None):
    # Align contraction extents when block granularities differ.
    if a_pad_k != b_pad_k:
        ad = ad[..., :k]
        bd = bd[:k, :]
    return mxu_dot(ad, bd, compute_dtype,
                   accum_dtype=accum_dtype or jnp.float32)


def matmul(a: BlockedTensor, b: BlockedTensor,
           compute_dtype: Optional[str] = None,
           accum_dtype: Optional[str] = None,
           distributed: Optional[bool] = None) -> BlockedTensor:
    """C = A·B (reference ``FFInputLayerJoin`` + ``FFAggMatrix``).

    ``accum_dtype`` sets the output dtype (default f32). Passing
    ``"bfloat16"`` keeps the activation in HBM at half width — on v5e
    this is the difference between ~73% and ~94% MXU utilization for
    inference chains, at the precision the caller already opted into
    via ``compute_dtype``.

    ``distributed`` routes the contraction through the SUMMA panel
    engine (``parallel/summa.py``: A rows mesh-sharded, B contraction
    panels broadcast per step, C tiles accumulated in place) — None
    reads the ``config.distributed_matmul`` knob; the route engages
    only when >1 device is visible. Single-device behavior is
    byte-for-byte the one ``dot_general`` below.
    """
    (m, ka), (kb, n) = a.shape, b.shape
    if ka != kb:
        raise ValueError(f"matmul contraction mismatch {a.shape} x {b.shape}")
    from netsdb_tpu.config import DEFAULT_CONFIG

    if distributed is None:
        distributed = getattr(DEFAULT_CONFIG, "distributed_matmul",
                              False)
    # the SUMMA engine accumulates f32 (the default contract); a
    # caller opting into reduced-precision compute or a non-f32
    # accumulator keeps the single-device path that honors both
    if distributed and compute_dtype is None and accum_dtype is None:
        import jax

        cap = getattr(DEFAULT_CONFIG, "summa_participants", None)
        devices = jax.devices()[:int(cap)] if cap else jax.devices()
        if len(devices) >= 2:
            from netsdb_tpu.parallel import summa

            out = summa.summa_matmul_resident(a.data[:m, :ka],
                                              b.data[:kb, :n],
                                              devices=devices)
            meta = BlockMeta((m, n), (a.meta.block_shape[0],
                                      b.meta.block_shape[1]))
            pad = [(0, p - s) for s, p in zip((m, n),
                                              meta.padded_shape)]
            if any(p for _, p in pad):
                out = jnp.pad(out, pad)
            return BlockedTensor(out, meta)
    out = _contract(a.data, b.data, a.meta.padded_shape[1],
                    b.meta.padded_shape[0], ka, compute_dtype, accum_dtype)
    meta = BlockMeta((m, n), (a.meta.block_shape[0], b.meta.block_shape[1]))
    return BlockedTensor(out, meta)


def matmul_t(a: BlockedTensor, b: BlockedTensor,
             compute_dtype: Optional[str] = None,
             accum_dtype: Optional[str] = None) -> BlockedTensor:
    """C = A·Bᵀ (reference ``FFTransposeMult``: join on matching block
    col-index of both inputs)."""
    (m, ka), (n, kb) = a.shape, b.shape
    if ka != kb:
        raise ValueError(f"matmul_t contraction mismatch {a.shape} x {b.shape}")
    bd = jnp.swapaxes(b.data, 0, 1)
    out = _contract(a.data, bd, a.meta.padded_shape[1],
                    b.meta.padded_shape[1], ka, compute_dtype, accum_dtype)
    meta = BlockMeta((m, n), (a.meta.block_shape[0], b.meta.block_shape[0]))
    return BlockedTensor(out, meta)


def t_matmul(a: BlockedTensor, b: BlockedTensor,
             compute_dtype: Optional[str] = None) -> BlockedTensor:
    """C = Aᵀ·B (the LA DSL ``'*`` transpose-multiply, reference
    ``LASillyTransposeMultiply1Join.h``; Gram matrix = X '* X)."""
    (ka, m), (kb, n) = a.shape, b.shape
    if ka != kb:
        raise ValueError(f"t_matmul contraction mismatch {a.shape} x {b.shape}")
    ad = jnp.swapaxes(a.data, 0, 1)
    out = _contract(ad, b.data, a.meta.padded_shape[0],
                    b.meta.padded_shape[0], ka, compute_dtype)
    meta = BlockMeta((m, n), (a.meta.block_shape[1], b.meta.block_shape[1]))
    return BlockedTensor(out, meta)


def gram(x: BlockedTensor, compute_dtype: Optional[str] = None) -> BlockedTensor:
    """Xᵀ·X — the reference's flagship self-learning benchmark task
    (``documentation.md:5-10``)."""
    return t_matmul(x, x, compute_dtype)
