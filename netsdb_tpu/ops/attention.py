"""Attention ops — the long-context compute core.

The reference has no attention anywhere (SURVEY §5: no sequence
dimension exists in netsDB), but this framework treats long-context as
first-class: serving modern models through the same set/computation API
requires attention plus sequence parallelism. This module provides the
single-device formulations; :mod:`netsdb_tpu.parallel.ring` distributes
them over the mesh.

Layouts: q/k/v are (batch, heads, seq, head_dim) — B H S D.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True,
              scale: Optional[float] = None) -> jax.Array:
    """Plain softmax attention (the reference formulation everything
    else must match numerically)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        precision=jax.lax.Precision.HIGHEST) * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_), k=s_k - s_q)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                      precision=jax.lax.Precision.HIGHEST)


def _block_attn(q, k, v, carry_num, carry_den, carry_max, mask):
    """One online-softmax accumulation step (the flash-attention update
    rule): combine the running (num, den, max) with a new k/v block."""
    scale_logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                              precision=jax.lax.Precision.HIGHEST)
    scale_logits = jnp.where(mask, scale_logits, NEG_INF)
    block_max = jnp.max(scale_logits, axis=-1, keepdims=True)
    new_max = jnp.maximum(carry_max, block_max)
    correction = jnp.exp(carry_max - new_max)
    p = jnp.exp(scale_logits - new_max)
    new_den = carry_den * correction + p.sum(-1, keepdims=True)
    new_num = carry_num * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, precision=jax.lax.Precision.HIGHEST)
    return new_num, new_den, new_max


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_size: int, causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Attention with k/v processed in blocks via online softmax —
    O(block) memory in the sequence dim, the single-device form of ring
    attention. Numerically identical to :func:`attention`."""
    b, h, s, d = q.shape
    if s % block_size != 0:
        raise ValueError(f"seq {s} not divisible by block {block_size}")
    scale = scale if scale is not None else d ** -0.5
    q = q * scale
    n_blocks = s // block_size
    kb = k.reshape(b, h, n_blocks, block_size, d)
    vb = v.reshape(b, h, n_blocks, block_size, d)
    q_pos = jnp.arange(s)[:, None]

    def body(i, carry):
        num, den, mx = carry
        k_i = kb[:, :, i]
        v_i = vb[:, :, i]
        if causal:
            k_pos = i * block_size + jnp.arange(block_size)[None, :]
            mask = q_pos >= k_pos
        else:
            mask = jnp.ones((s, block_size), jnp.bool_)
        return _block_attn(q, k_i, v_i, num, den, mx, mask)

    num0 = jnp.zeros_like(q)
    den0 = jnp.zeros((b, h, s, 1), q.dtype)
    max0 = jnp.full((b, h, s, 1), NEG_INF, q.dtype)
    num, den, _ = jax.lax.fori_loop(0, n_blocks, body, (num0, den0, max0))
    return num / jnp.maximum(den, 1e-30)


def split_qkv_heads(qkv: jax.Array, num_heads: int):
    """Packed (B,S,3E) projection → q/k/v (B,H,S,D) — THE layout
    convention (split into thirds, then head reshape/transpose); every
    consumer (fused forward, staged DAGs) must share it or the paths
    silently diverge."""
    b, s, f = qkv.shape
    e = f // 3
    d = e // num_heads
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, d).transpose(0, 2, 1, 3)

    return heads(q), heads(k), heads(v)


def merge_heads(out: jax.Array) -> jax.Array:
    """(B,H,S,D) attention output → (B,S,E), the inverse of
    :func:`split_qkv_heads`'s layout."""
    b, h, s, d = out.shape
    return out.transpose(0, 2, 1, 3).reshape(b, s, h * d)


def qkv_project(x: jax.Array, w_qkv: jax.Array, num_heads: int):
    """x (B,S,E) → q/k/v (B,H,S,D) — shared by local and
    sequence-parallel layers."""
    qkv = jnp.einsum("bse,ef->bsf", x, w_qkv,
                     precision=jax.lax.Precision.HIGHEST)
    return split_qkv_heads(qkv, num_heads)


def merge_project(out: jax.Array, w_out: jax.Array) -> jax.Array:
    """(B,H,S,D) attention output → (B,S,E) through the out projection."""
    return jnp.einsum("bse,ef->bsf", merge_heads(out), w_out,
                      precision=jax.lax.Precision.HIGHEST)


def attention_dispatch(q: jax.Array, k: jax.Array, v: jax.Array,
                       causal: bool = True, scale: Optional[float] = None,
                       impl: Optional[str] = None,
                       block_size: Optional[int] = None,
                       out_vma=None) -> jax.Array:
    """Pick the attention implementation: 'full', 'blockwise', or
    'flash' (pallas kernel). ``impl=None`` auto-selects: flash on TPU
    when the sequence divides its blocks, else blockwise when a
    block_size is given, else full."""
    from netsdb_tpu.ops.common import on_tpu

    s = q.shape[2]
    if impl is None:
        # flash only when the sequence is a whole number of pallas blocks
        # AND the block the kernel would use is lane-aligned — an explicit
        # caller block_size that Mosaic can't tile (not a multiple of 128)
        # must keep the exact blockwise path, not be silently overridden
        blk = block_size or min(256, s)
        if on_tpu() and s % 256 == 0 and blk % 128 == 0 and s % blk == 0:
            impl = "flash"
        elif block_size:
            impl = "blockwise"
        else:
            impl = "full"
    if impl == "flash":
        from netsdb_tpu.ops.pallas_kernels import flash_attention

        if block_size:
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   block_q=block_size, block_k=block_size,
                                   out_vma=out_vma)
        # no explicit block: use the kernel's tuned defaults (1024^2,
        # ~3x the throughput of 256^2 at long seq — see flash_attention)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               out_vma=out_vma)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, block_size or min(256, s),
                                   causal, scale)
    if impl == "full":
        return attention(q, k, v, causal, scale)
    raise ValueError(f"unknown attention impl {impl!r}")


def mha_forward(x: jax.Array, w_qkv: jax.Array, w_out: jax.Array,
                num_heads: int, causal: bool = True,
                block_size: Optional[int] = None,
                impl: Optional[str] = None) -> jax.Array:
    """Full multi-head attention layer: x (B, S, E), w_qkv (E, 3E),
    w_out (E, E) — the flagship long-context layer the parallel plans
    shard."""
    q, k, v = qkv_project(x, w_qkv, num_heads)
    out = attention_dispatch(q, k, v, causal=causal, impl=impl,
                             block_size=block_size)
    return merge_project(out, w_out)
