import os
import sys

try:
    from netsdb_tpu.cli import main
except ModuleNotFoundError:  # pragma: no cover
    # PATH python in this image has an empty site-packages; the real
    # environment lives in /opt/venv — re-exec the CLI there (env-flag
    # loop guard: both interpreters resolve to the same binary)
    _venv = "/opt/venv/bin/python"
    if os.path.exists(_venv) and not os.environ.get("NETSDB_CLI_REEXEC"):
        os.environ["NETSDB_CLI_REEXEC"] = "1"
        os.execv(_venv, [_venv, "-m", "netsdb_tpu"] + sys.argv[1:])
    raise

sys.exit(main())
