import sys

try:
    from netsdb_tpu.cli import main
except ModuleNotFoundError:  # pragma: no cover
    # second line of defense: the package import probe (see
    # __init__.py) handles missing jax; this catches a partially
    # broken environment discovered later in the CLI's own imports
    from netsdb_tpu import _reexec

    _reexec.maybe_reexec("NETSDB_CLI_REEXEC",
                         require_module_prefix="netsdb_tpu")
    raise

sys.exit(main())
