import sys

from netsdb_tpu.cli import main

sys.exit(main())
