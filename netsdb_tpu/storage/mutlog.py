"""Durable per-store mutation log — the log-shipping half of HA.

The serve layer's mirror path (serve/server.py ``_mirror_once``)
forwards every mutating frame to its followers over ordered FIFO
links; this module gives that same path a DISK tail. Two consumers:

* **Log-replay resync** — when a follower is evicted, the leader
  remembers the byte offset of the last frame that follower acked.
  On reattach it replays ``replay(from_offset)`` — only the frames
  the follower missed — instead of streaming a whole-store snapshot
  (the PR 2 resync stays as the fallback when no offset is known or
  the log was truncated past it).
* **Durable handoff spill** — the PR 13 degraded-slot handoff buffer
  (serve/shard.py) appends every buffered batch (and a tombstone per
  drain/purge) so buffered ingest survives a leader RESTART; replay
  at startup rebuilds exactly the still-pending batches.

Record framing: ``u64 length | u32 crc32(payload) | payload`` with
big-endian headers and a pickled payload (the trusted-control-plane
boundary — same argument as the checkpoint snapshots and the wire's
codec 1). Offsets handed to callers are always END offsets: the
position a reader who has applied everything up to and including that
record resumes from, so ``last_offset()`` == file size and
``replay(0)`` yields the whole log.

Torn tails are expected (a crash mid-append): ``open`` scans the file
and truncates the first record whose header or checksum does not
validate — the log's prefix property is what replay correctness rests
on, so a torn record and everything after it are dropped rather than
skipped over.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Iterator, Optional, Tuple

from netsdb_tpu import obs
from netsdb_tpu.utils.locks import TrackedLock

#: record header: payload length (u64) + crc32 of the payload (u32)
_HDR = struct.Struct("!QI")

#: refuse to parse absurd lengths (a torn header read as a length
#: would otherwise allocate unbounded buffers during recovery scans)
_MAX_RECORD_BYTES = 1 << 31


class MutationLog:
    """Append-only framed record log at ``path``.

    All methods are thread-safe (``_mu`` is a leaf rank — the lock
    hierarchy in docs/ANALYSIS.md). ``fsync=False`` (the default)
    flushes to the OS on every append — durable across a process
    restart, which is the HA contract; a power loss losing the last
    records degrades to re-execution under the idempotency tokens the
    records carry, never divergence (the same durability stance as
    the idempotency sqlite's ``synchronous=NORMAL``)."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self._fsync = bool(fsync)
        self._mu = TrackedLock("storage.MutationLog._mu")
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        valid_end = self._scan_valid_end(path)
        self._f = open(path, "ab")
        if self._f.tell() != valid_end:
            # torn tail from a crash mid-append: drop the partial
            # record (and anything after it) — replay must only ever
            # see a valid prefix
            self._f.truncate(valid_end)
            self._f.seek(valid_end)
        self._end = valid_end

    @staticmethod
    def _scan_valid_end(path: str) -> int:
        """Largest offset such that [0, offset) parses as whole,
        checksum-clean records."""
        if not os.path.exists(path):
            return 0
        end = 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return end
                length, crc = _HDR.unpack(hdr)
                if length > _MAX_RECORD_BYTES:
                    return end
                payload = f.read(length)
                if len(payload) < length \
                        or zlib.crc32(payload) != crc:
                    return end
                end += _HDR.size + length

    # --- writes -------------------------------------------------------
    def append(self, record: Any) -> int:
        """Append one record; returns the log's END offset after it —
        the resume position for a reader that has applied this record."""
        payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._mu:
            if self._f.closed:
                raise ValueError(f"mutation log {self.path} is closed")
            self._f.write(frame)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self._end += len(frame)
            end = self._end
        obs.REGISTRY.counter("mutlog.appended_bytes").inc(len(frame))
        return end

    def truncate(self) -> None:
        """Reset the log to empty — the compaction moment (e.g. every
        spilled handoff batch has drained, or a snapshot superseded
        the whole tail)."""
        with self._mu:
            if self._f.closed:
                return
            self._f.truncate(0)
            self._f.seek(0)
            self._f.flush()
            self._end = 0

    # --- reads --------------------------------------------------------
    def last_offset(self) -> int:
        with self._mu:
            return self._end

    def replay(self, from_offset: int = 0) -> Iterator[Tuple[int, Any]]:
        """Yield ``(end_offset, record)`` for every record at or after
        ``from_offset``, bounded by the log's size at call time.
        Reads run on a dedicated handle — appends may continue
        concurrently (their records simply fall past the bound)."""
        with self._mu:
            bound = self._end
        if from_offset >= bound:
            return
        f = open(self.path, "rb")
        try:
            f.seek(from_offset)
            pos = from_offset
            while pos < bound:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return  # truncated under us — valid prefix ends
                length, crc = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length \
                        or zlib.crc32(payload) != crc:
                    return
                pos += _HDR.size + length
                yield pos, pickle.loads(payload)
        finally:
            f.close()

    def close(self) -> None:
        with self._mu:
            if not self._f.closed:
                self._f.close()
