"""Ingestion partition policies — the reference's dispatcher layer.

The reference's ``DispatcherServer`` splits each ingested
``Vector<Object>`` across storage nodes with a pluggable
``PartitionPolicy`` — RANDOM / ROUNDROBIN / FAIR / DEFAULT
(``src/dispatcher/headers/PartitionPolicy.h:29-60``), plus the
IR/lambda policies that hash-route objects by a query lambda for
co-partitioned joins (``IRPolicy.h``, dispatch lambda plumbing in
``src/mainClient/headers/PDBClient.h:79-103``).

On TPU, TENSOR placement is a sharding spec (``parallel/mesh.py``) and
XLA moves the bytes. What this module keeps is the record-set side:
deciding which shard (mesh slot / host / worker process) each host
object lands on at ingestion time, so multi-host ingest and
co-partitioned host joins distribute the same way the reference's
dispatcher distributes them. Policies are stateless functions from an
item batch to per-shard lists; ``FairPolicy`` weights shards by
capacity like the reference's FAIR mode; ``HashPolicy`` is the
partition-lambda (IR/Lambda) mode, giving deterministic co-partitioning.
"""

from __future__ import annotations

import random
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence


class PartitionPolicy:
    """Base: ``partition(items, n_shards)`` → list of n_shards lists
    (reference ``PartitionPolicy::partition``, which maps NodeID →
    sub-vector)."""

    name = "default"

    def partition(self, items: Sequence[Any],
                  n_shards: int) -> List[List[Any]]:
        raise NotImplementedError


class RoundRobinPolicy(PartitionPolicy):
    """Cycle through shards item by item — the reference's default
    (``RoundRobinPolicy.h``). Deterministic and maximally even."""

    name = "roundrobin"

    def __init__(self, start: int = 0):
        self._next = start

    def partition(self, items, n_shards):
        out: List[List[Any]] = [[] for _ in range(n_shards)]
        for item in items:
            out[self._next % n_shards].append(item)
            self._next += 1
        return out


class RandomPolicy(PartitionPolicy):
    """Uniform random shard per item (``RandomPolicy.h``). Seeded, so
    a given dispatcher instance is replayable."""

    name = "random"

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def partition(self, items, n_shards):
        out: List[List[Any]] = [[] for _ in range(n_shards)]
        for item in items:
            out[self.rng.randrange(n_shards)].append(item)
        return out


class FairPolicy(PartitionPolicy):
    """Capacity-weighted split (``FairPolicy.h``): shard i receives a
    share of each batch proportional to ``weights[i]`` (the reference
    weights by node cores/memory from the ResourceManager)."""

    name = "fair"

    def __init__(self, weights: Sequence[float]):
        if not weights or any(w < 0 for w in weights) or sum(weights) == 0:
            raise ValueError("weights must be non-negative, sum > 0")
        self.weights = list(weights)

    def partition(self, items, n_shards):
        if n_shards != len(self.weights):
            raise ValueError(
                f"{n_shards} shards != {len(self.weights)} weights")
        total = sum(self.weights)
        out: List[List[Any]] = [[] for _ in range(n_shards)]
        # largest-remainder apportionment of the batch
        n = len(items)
        quotas = [w / total * n for w in self.weights]
        counts = [int(q) for q in quotas]
        remainder = n - sum(counts)
        by_frac = sorted(range(n_shards), key=lambda i: quotas[i] - counts[i],
                         reverse=True)
        for i in by_frac[:remainder]:
            counts[i] += 1
        it = iter(items)
        for shard, c in enumerate(counts):
            for _ in range(c):
                out[shard].append(next(it))
        return out


def _stable_key_bytes(key: Any) -> bytes:
    """Canonical encoding for hash routing: only value types whose
    textual form is stable across processes (default object repr embeds
    a memory address, which would silently break co-partitioning)."""
    if isinstance(key, (bool, int, float)):
        # numerically equal keys must route identically regardless of
        # Python type (1 == 1.0 == True, 0.0 == -0.0): the host equi-join
        # treats them as one key, so co-partitioning must too
        if isinstance(key, float) and not key.is_integer():
            return repr(key).encode()
        return repr(int(key)).encode()
    if key is None or isinstance(key, (str, bytes)):
        return repr(key).encode()
    if isinstance(key, (tuple, list)):
        return b"(" + b",".join(_stable_key_bytes(k) for k in key) + b")"
    raise TypeError(
        f"hash partition key must be a primitive or tuple of primitives, "
        f"got {type(key).__name__}; return one from key_fn")


class HashPolicy(PartitionPolicy):
    """Partition-lambda routing (the reference's IR/LambdaPolicy +
    ``createSet(..., partition_lambda)`` plumbing): shard =
    hash(key_fn(item)) % n. Items with equal keys always co-locate, so
    two sets dispatched with the same key_fn are co-partitioned for
    joins. ``key_fn`` must return a primitive (or tuple of primitives)
    so the hash is stable across processes."""

    name = "hash"

    def __init__(self, key_fn: Callable[[Any], Any]):
        self.key_fn = key_fn

    def partition(self, items, n_shards):
        out: List[List[Any]] = [[] for _ in range(n_shards)]
        for item in items:
            h = zlib.crc32(_stable_key_bytes(self.key_fn(item)))
            out[h % n_shards].append(item)
        return out


POLICIES: Dict[str, Callable[..., PartitionPolicy]] = {
    "roundrobin": RoundRobinPolicy,
    "random": RandomPolicy,
    "fair": FairPolicy,
    "hash": HashPolicy,
}


def make_policy(name: str, **kwargs) -> PartitionPolicy:
    if name not in POLICIES:
        raise ValueError(f"unknown policy {name!r}; "
                         f"available: {', '.join(POLICIES)}")
    return POLICIES[name](**kwargs)


def dispatch_to_sets(client, db: str, base_name: str,
                     items: Sequence[Any], n_shards: int,
                     policy: Optional[PartitionPolicy] = None) -> List[str]:
    """Write one batch into per-shard sets ``{base}_shard{i}`` — the
    DispatcherServer → per-node StorageAddData fan-out
    (``src/serverFunctionalities/source/DispatcherServer.cc``), with
    sets standing in for nodes in the single-controller runtime.
    Returns the shard set names."""
    policy = policy or RoundRobinPolicy()
    parts = policy.partition(items, n_shards)
    names = []
    for i, part in enumerate(parts):
        name = f"{base_name}_shard{i}"
        if not client.set_exists(db, name):
            client.create_set(db, name, type_name="object")
        if part:
            client.send_data(db, name, part)
        names.append(name)
    return names
