"""Model checkpointing — the durability story for device state.

The reference's "checkpoint" is storage-level: weight sets flushed to
``PartitionedFile``s survive restart, catalog sqlite persists metadata,
and ``PreCompiledWorkload`` caches plans (SURVEY §5 "Checkpoint /
resume": ``WorkerMain.cc:131``, ``conf/headers/DataTypes.h:53``). Our
store already mirrors that (``storage/store.py::flush``/``load_set``).
This module adds the TPU-idiomatic layer on top: orbax snapshots of
whole parameter pytrees (``FFParams``, transformer stacks, optimizer
state) with step numbering and latest-step resume — what
checkpoint/resume means for a training loop on real hardware. Falls
back to a NumPy ``.npz``-per-leaf format when orbax is unavailable.

``BlockedTensor`` leaves round-trip because they are registered
pytrees (``core/blocked.py``): orbax sees their ``jax.Array`` leaves
and the BlockMeta aux data reconstructs the blocking.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step}")


def list_steps(root: str) -> list:
    """All checkpointed steps under ``root``, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    steps = list_steps(root)
    return steps[-1] if steps else None


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception:
        return None


def save(root: str, pytree: Any, step: int) -> str:
    """Snapshot ``pytree`` as ``root/step_<step>``. Overwrites an
    existing snapshot of the same step (the semantics a retrying
    training loop needs)."""
    path = _step_dir(root, step)
    ocp = _try_orbax()
    if ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, jax.tree_util.tree_map(np.asarray, pytree),
                   force=True)
        return path
    # numpy fallback: flatten to leaves + treedef-less structure file
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(pytree)
    np.savez(os.path.join(path, "leaves.npz"),
             **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    with open(os.path.join(path, "treedef.json"), "w") as f:
        json.dump({"n_leaves": len(leaves)}, f)
    return path


def restore(root: str, target: Any, step: Optional[int] = None) -> Any:
    """Restore into the structure of ``target`` (a template pytree with
    the right shapes — the standard orbax restore contract). ``step``
    defaults to the latest."""
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    path = _step_dir(root, step)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint at {path}")
    # dispatch on what is actually on disk, not on which library this
    # process happens to have: a checkpoint written by the npz fallback
    # must restore in an orbax-enabled process and vice versa
    is_npz = os.path.exists(os.path.join(path, "leaves.npz"))
    ocp = None if is_npz else _try_orbax()
    leaves_t, treedef = jax.tree_util.tree_flatten(target)
    if ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(
            path, item=jax.tree_util.tree_map(np.asarray, target))
        leaves_r = jax.tree_util.tree_leaves(restored)
    else:
        if not is_npz:
            raise FileNotFoundError(
                f"checkpoint at {path} is in orbax format but orbax is "
                f"not importable here")
        data = np.load(os.path.join(path, "leaves.npz"))
        with open(os.path.join(path, "treedef.json")) as f:
            n_saved = json.load(f)["n_leaves"]
        leaves_r = [data[f"leaf_{i}"] for i in range(n_saved)]
    if len(leaves_r) != len(leaves_t):
        raise ValueError(
            f"checkpoint has {len(leaves_r)} leaves, target expects "
            f"{len(leaves_t)}")
    import jax.numpy as jnp
    leaves = [jnp.asarray(r) for r in leaves_r]
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --- whole-store snapshots (follower resync) ---------------------------
# The serve layer's fault-tolerance path: when a follower daemon is
# evicted (failed mid-mirror, missed heartbeats), the leader snapshots
# its store here and the follower rebuilds from the snapshot before
# being readmitted — the same step-dir convention as model checkpoints
# (list_steps/latest_step see both), but the payload is an opaque
# pickled snapshot because sets hold arbitrary host objects (relational
# rows, ColumnTables) that are not numeric pytrees.
#
# TRUST BOUNDARY: load_store executes pickle from the given path —
# exactly the serve protocol's codec-1 boundary (serve/protocol.py
# security note). The RESYNC_FOLLOWER handler therefore requires
# allow_pickle on the follower daemon.

_STORE_FILE = "store.pkl"


def dumps_store(snapshot: Any) -> bytes:
    """Snapshot → one pickle blob. The serve layer's wire-streamed
    follower resync pickles ONCE and both writes the blob locally
    (:func:`save_store_bytes`, leader durability) and streams it to the
    follower in bounded frames — no shared-filesystem assumption."""
    import pickle

    return pickle.dumps(snapshot, protocol=pickle.HIGHEST_PROTOCOL)


def loads_store(blob) -> Any:
    """Inverse of :func:`dumps_store`; accepts any bytes-like buffer
    (the resync handler passes the assembled chunk stream). Same
    codec-1 trust boundary as :func:`load_store`."""
    import pickle

    return pickle.loads(blob)


def save_store_bytes(root: str, blob, step: int) -> str:
    """Persist an already-pickled snapshot blob as ``root/step_<step>``.
    Atomic per step: the file lands via rename, so a reader never
    observes a torn snapshot. Returns the step directory."""
    path = _step_dir(root, step)
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, _STORE_FILE)
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, final)
    return path


def save_store(root: str, snapshot: Any, step: int) -> str:
    """Persist ``snapshot`` (any picklable object — the serve layer
    passes its databases/sets/types dump) as ``root/step_<step>``."""
    return save_store_bytes(root, dumps_store(snapshot), step)


def prune_steps(root: str, keep: int = 1) -> list:
    """Delete all but the newest ``keep`` step directories under
    ``root`` (snapshots are full-store, so only the latest is ever
    restored — a follower flapping for days must not fill the leader's
    disk). Returns the removed step numbers."""
    import shutil

    steps = list_steps(root)
    victims = steps[:-keep] if keep > 0 else steps
    for s in victims:
        shutil.rmtree(_step_dir(root, s), ignore_errors=True)
    return victims


_META_FILE = "meta.json"


def save_meta(root: str, step: int, meta: dict) -> str:
    """Attach a small JSON metadata sidecar to a snapshot step (the
    serve layer records the mutation-log offset a snapshot captured,
    so a restart knows where log replay resumes). Atomic via rename,
    same as the blob itself."""
    path = _step_dir(root, step)
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, _META_FILE)
    tmp = final + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    os.replace(tmp, final)
    return final


def load_meta(root: str, step: Optional[int] = None) -> Optional[dict]:
    """Read a :func:`save_meta` sidecar (``step`` defaults to the
    latest). None when the step has no sidecar — older snapshots
    predate the convention."""
    if step is None:
        step = latest_step(root)
        if step is None:
            return None
    final = os.path.join(_step_dir(root, step), _META_FILE)
    try:
        with open(final, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_store(root: str, step: Optional[int] = None) -> Any:
    """Load a :func:`save_store` snapshot; ``step`` defaults to the
    latest under ``root``. Raises FileNotFoundError when absent."""
    import pickle

    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no store snapshots under {root}")
    final = os.path.join(_step_dir(root, step), _STORE_FILE)
    if not os.path.exists(final):
        raise FileNotFoundError(f"no store snapshot at {final}")
    with open(final, "rb") as f:
        return pickle.load(f)
