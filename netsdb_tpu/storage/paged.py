"""Paged tensor streaming — PageCache → pipeline feeding, TPU-shaped.

In the reference, a backend scan pins 64 MB pages one by one and feeds
them through ``PageCircularBuffer`` to the pipeline threads
(``src/storage/headers/PageScanner.h``, ``PageCircularBuffer.h``), so a
set larger than RAM streams from ``PartitionedFile`` through the
``PageCache``. Here the same role: a large matrix is stored row-block-
wise as pages in the native C++ page store (``native/pagestore.cpp``) —
which caches hot pages in its arena and spills cold ones — and is
streamed block-by-block into device HBM (``jax.device_put`` per chunk),
so working sets larger than host RAM or HBM flow through without ever
materializing densely.

Falls back to a pure-Python page dict when the native toolchain is
unavailable.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
from netsdb_tpu.utils.locks import TrackedLock


class _PyPageBackend:
    """Fallback backend with the same surface as NativePageStore.

    Thread-safe like the native store (its C++ side is mutex-guarded):
    concurrent writers — two object-set appends no longer serialized by
    the store-wide lock — must not race the page-id allocation or the
    per-set page lists."""

    def __init__(self):
        self._mu = TrackedLock("_PyPageBackend._mu")
        self._pages: Dict[int, bytes] = {}
        self._sets: Dict[int, list] = {}
        self._next = 1

    def create_set(self, set_id, policy="lru"):
        with self._mu:
            self._sets.setdefault(set_id, [])

    def write_page(self, set_id, payload) -> int:
        data = payload if isinstance(payload, bytes) else \
            np.ascontiguousarray(payload).tobytes()
        with self._mu:
            pid = self._next
            self._next += 1
            self._pages[pid] = data
            self._sets[set_id].append(pid)
        return pid

    def read_page(self, page_id) -> bytes:
        with self._mu:
            return self._pages[page_id]

    def free_page(self, page_id) -> None:
        with self._mu:
            self._pages.pop(page_id, None)
            for pages in self._sets.values():
                if page_id in pages:
                    pages.remove(page_id)

    def overwrite_page(self, page_id, payload) -> None:
        """Replace one page's bytes IN PLACE (same size — the
        update-a-column-in-its-page path; a size change would shift
        every derived block layout)."""
        data = payload if isinstance(payload, bytes) else \
            np.ascontiguousarray(payload).tobytes()
        with self._mu:
            old = self._pages.get(page_id)
            if old is None:
                raise KeyError(f"unknown page {page_id}")
            if len(old) != len(data):
                raise ValueError(
                    f"overwrite_page: size change {len(old)} -> "
                    f"{len(data)} not allowed")
            self._pages[page_id] = data

    def set_pages(self, set_id):
        with self._mu:
            return list(self._sets[set_id])

    def page_size(self, page_id) -> int:
        with self._mu:
            return len(self._pages[page_id])

    def flush_set(self, set_id):
        pass

    def stats(self):
        with self._mu:
            nbytes = sum(len(v) for v in self._pages.values())
        return {"hits": 0, "misses": 0, "evictions": 0, "spills": 0,
                "loads": 0, "bytes_allocated": nbytes,
                "bytes_in_use": nbytes}

    def close(self):
        pass


class PagedTensor:
    """Streaming read handle for a matrix living as arena pages — the
    value a ``ScanSet`` of a paged TENSOR set produces in the executor.

    Never materializes: consumers stream row blocks (the reference's
    FFMatrixBlockScanner feeding weight pages into the inference
    pipeline, ``src/FF/headers/FFMatrixBlockScanner.h`` +
    ``src/storage/headers/PageScanner.h:25-34``). ``rw`` is the owning
    set item's stream-vs-mutation lock; ``placement`` the owning set's
    declared distribution (applied per block by the executor).
    """

    def __init__(self, store: "PagedTensorStore", name: str,
                 rw=None, placement=None):
        from netsdb_tpu.utils.locks import RWLock

        self.store = store
        self.name = name
        self.rw = rw if rw is not None else RWLock()
        self.placement = placement
        # device-cache binding (set by SetStore.paged_tensor for
        # store-owned handles): scope = (ident str, write version) —
        # the executor's tensor stream keys cached runs on it, and
        # cache_version_fn re-checks currentness at install time
        self.devcache = None
        self.cache_scope = None
        self.cache_version_fn = None

    @property
    def shape(self) -> Tuple[int, int]:
        return self.store.meta(self.name)[0]

    @property
    def dtype(self) -> np.dtype:
        return self.store.meta(self.name)[2]

    def num_blocks(self) -> int:
        return self.store.num_blocks(self.name)

    def stream_blocks(self, prefetch: Optional[int] = None,
                      blocks: Optional[list] = None
                      ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield (start_row, block) holding the read lock for the
        generator's lifetime (a concurrent drop/replace must not free
        pages mid-stream); consumers should close() abandoned streams.
        ``prefetch=None`` takes the ``config.stream_prefetch_pages``
        read-ahead knob; ``blocks`` restricts to those page indices
        (the stitched gap feed — see ``PagedTensorStore.stream_blocks``)."""
        with self.rw.read():
            yield from self.store.stream_blocks(self.name, prefetch,
                                                blocks=blocks)

    def block_ranges(self) -> list:
        """[(start_row, end_row)] per page block, metadata only."""
        return self.store.block_ranges(self.name)


class PagedObjects:
    """Arbitrary host records paged as PICKLED BATCHES in the shared
    arena — the reference's pages hold arbitrary ``pdb::Object``s
    (``src/storage/headers/PDBPage.h:17-33``), so record workloads
    (reddit-style Filter/Join/Aggregate over Python objects) are
    out-of-core for free there; this is the TPU-native equivalent for
    the EAGER interpreter path. Iterating the handle streams records
    page by page (pin one batch, yield, move on), so the eager
    Filter/Join/Aggregate nodes consume it unchanged.

    Batches target ~the configured page size of pickled payload; the
    arena caps/spills these pages exactly like column pages.
    """

    def __init__(self, store: "PagedTensorStore", name: str,
                 num_items: int = 0):
        from netsdb_tpu.utils.locks import RWLock

        self.store = store
        self.name = name
        self.num_items = num_items
        self.rw = RWLock(name="PagedObjects.rw")
        # serializes concurrent appends against each other; appends
        # hold rw.READ (not write — see append()) so they never wait
        # for in-flight record streams to drain. Store-routed appends
        # additionally hold the set's ``_StoredSet.append_mu`` — that
        # one orders appends against the store's OTHER per-set
        # mutations; this one is the handle's own guarantee, so a
        # direct ``po.append`` (no store in sight) is still safe.
        self._append_mu = TrackedLock("PagedObjects._append_mu")
        self.dropped = False
        store.backend.create_set(store._set_id(name))

    @staticmethod
    def ingest(store: "PagedTensorStore", name: str,
               items: list) -> "PagedObjects":
        po = PagedObjects(store, name)
        po.append(items)
        return po

    def append(self, items: list) -> None:
        """Write records as additional pickled-batch pages (the
        reference's addData continuously appending objects).

        LOCKING (advisor round 5): appends only ADD pages — they never
        touch pages a live record stream is reading (``__iter__``
        snapshots the page list at its start, and freeing pages is
        ``drop``'s job, which does take the write lock). So append
        holds the relation's READ lock (drop exclusion only) plus a
        per-handle append mutex (order among concurrent appenders),
        NOT the write lock: it never waits for in-flight streams to
        drain — a slow wire scan cannot stall ingest, and a consumer
        appending while iterating the same set no longer
        self-deadlocks (its own read lock would make ``rw.write()``
        wait forever). A reader that starts mid-append may observe a
        prefix of the batch's pages — the same visibility a reader
        starting between two appends always had."""
        import io
        import pickle

        if not items:
            return
        with self._append_mu, self.rw.read():
            if self.dropped:
                raise KeyError(f"paged object set {self.name!r} was "
                               f"dropped; cannot append")
            sid = self.store._set_id(self.name)
            target = max(self.store.config.page_size_bytes, 4096)
            # page packing tracks CUMULATIVE PICKLED BYTES as the batch
            # fills: an incremental Pickler measures each record on
            # append, and the batch flushes the moment the measured
            # payload reaches the page target. The old scheme sized the
            # FIRST batch from a 256-byte seed estimate with an
            # 8-record floor, so eight multi-MB records landed on one
            # page — transiently blowing the arena far past
            # page_size_bytes before the estimate could adapt (ADVICE
            # round 5). The measuring stream shares the batch's object
            # graph, so its tell() tracks the real page size; each
            # flushed page stays ONE pickled list — the format
            # ``__iter__`` and the resync replay expect.
            batch: list = []
            buf = io.BytesIO()
            measurer = pickle.Pickler(buf,
                                      protocol=pickle.HIGHEST_PROTOCOL)

            def flush():
                nonlocal buf, measurer
                if not batch:
                    return
                self.store.backend.write_page(
                    sid, pickle.dumps(batch,
                                      protocol=pickle.HIGHEST_PROTOCOL))
                batch.clear()
                buf = io.BytesIO()
                measurer = pickle.Pickler(
                    buf, protocol=pickle.HIGHEST_PROTOCOL)

            for it in items:
                batch.append(it)
                try:
                    measurer.dump(it)
                    full = buf.tell() >= target
                except Exception:  # noqa: BLE001 — an unpicklable
                    # record must surface on the REAL dumps in flush()
                    # with the whole batch's context, not here
                    full = True
                if full:
                    flush()
            flush()
            self.num_items += len(items)

    def __iter__(self):
        """Stream records page by page under the read lock — the
        PageScanner feed for the eager interpreter."""
        import pickle

        with self.rw.read():
            if self.dropped:
                raise KeyError(f"paged object set {self.name!r} was "
                               f"dropped; cannot stream")
            sid = self.store._set_id(self.name)
            for pid in self.store.backend.set_pages(sid):
                yield from pickle.loads(self.store.backend.read_page(pid))

    def __len__(self) -> int:
        return self.num_items

    def to_list(self) -> list:
        return list(self)

    def drop(self) -> None:
        with self.rw.write():
            self.dropped = True
            sid = self.store._ids.pop(self.name, None)
            if sid is None:
                return
            for pid in self.store.backend.set_pages(sid):
                self.store.backend.free_page(pid)


class PagedTensorStore:
    """Row-block paged storage for large matrices."""

    def __init__(self, config: Configuration = DEFAULT_CONFIG,
                 pool_bytes: Optional[int] = None,
                 force_python: bool = False):
        self.config = config
        config.ensure_dirs()
        self._meta: Dict[int, Tuple[Tuple[int, int], Tuple[int, int], np.dtype]] = {}
        self._ids: Dict[str, int] = {}
        self._next_sid = 1
        # per-set (block_rows, block_starts) cache — derived from page
        # sizes once and reused, so read_block/stream starts stay O(1)
        # per call instead of O(pages); invalidated on put/append/drop
        self._layout: Dict[int, Tuple[list, list]] = {}
        # live prefetch reader threads: must be joined before the
        # backend is destroyed (a reader mid-read_page on a freed C++
        # arena is a use-after-free); mutations happen under _readers_lock
        # so concurrent streams can't interleave the prune/append and
        # drop a tracked reader
        self._readers: list = []
        self._readers_lock = TrackedLock("PagedTensorStore._readers_lock")
        self._closed = False
        if force_python:
            self.backend = _PyPageBackend()
            self.native = False
        else:
            try:
                from netsdb_tpu.native.pagestore import NativePageStore

                self.backend = NativePageStore(
                    pool_bytes or config.shared_mem_bytes,
                    os.path.join(config.data_dir, "pages"),
                )
                self.native = True
            except Exception:
                self.backend = _PyPageBackend()
                self.native = False

    def _set_id(self, name: str) -> int:
        # MONOTONIC allocation: len()+1 would recycle the id of a live
        # set after any drop() popped an entry, intermixing two sets'
        # pages (r5 review finding — reproduced as cross-set
        # corruption via the PagedObjects drop/re-ingest lifecycle)
        if name not in self._ids:
            self._ids[name] = self._next_sid
            self._next_sid += 1
        return self._ids[name]

    def put(self, name: str, dense: np.ndarray,
            row_block: Optional[int] = None,
            append: bool = False) -> None:
        """Page a matrix in as contiguous row-blocks. ``append=True``
        writes the batch as ADDITIONAL pages after the existing ones
        (the reference's addData appending pages to a set): blocks may
        then be ragged mid-stream (each batch's tail is short), which
        every reader handles by deriving per-page row counts from the
        actual page sizes (``_block_rows``)."""
        dense = np.ascontiguousarray(dense)
        if dense.ndim != 2:
            raise ValueError(f"paged store holds matrices; got rank-{dense.ndim} "
                             f"array of shape {dense.shape}")
        rows, cols = dense.shape
        if append and name in self._ids:
            sid = self._ids[name]
            (orows, ocols), (rb, _), dtype = self._meta[sid]
            if ocols != cols or dtype != dense.dtype:
                raise ValueError(
                    f"append to {name!r}: schema mismatch "
                    f"({ocols} cols/{dtype} vs {cols} cols/{dense.dtype})")
            for r0 in range(0, rows, rb):
                self.backend.write_page(sid, dense[r0:r0 + rb])
            self._meta[sid] = ((orows + rows, cols), (rb, cols), dtype)
            self._layout.pop(sid, None)
            return
        row_block = row_block or max(
            1, self.config.page_size_bytes // max(dense.dtype.itemsize * cols, 1))
        replacing = name in self._ids
        sid = self._set_id(name)
        self.backend.create_set(sid)
        if replacing:  # drop the old pages, else reads mix stale data
            for pid in self.backend.set_pages(sid):
                self.backend.free_page(pid)
        for r0 in range(0, rows, row_block):
            self.backend.write_page(sid, dense[r0:r0 + row_block])
        self._meta[sid] = ((rows, cols), (row_block, cols), dense.dtype)
        self._layout.pop(sid, None)

    def truncate_to(self, name: str, n_pages: int, rows: int) -> None:
        """Roll a set back to its first ``n_pages`` pages / ``rows``
        rows — the append-failure undo (frees the partially written
        pages so a failed batch cannot desynchronize co-paged
        matrices)."""
        sid = self._ids.get(name)
        if sid is None:
            return
        for pid in self.backend.set_pages(sid)[n_pages:]:
            self.backend.free_page(pid)
        (_, cols), (rb, _), dtype = self._meta[sid]
        self._meta[sid] = ((rows, cols), (rb, cols), dtype)
        self._layout.pop(sid, None)

    def _block_layout(self, sid: int) -> Tuple[list, list]:
        """(per-page row counts, per-page start rows), derived from
        ACTUAL page sizes (metadata-only backend calls) — correct for
        ragged appended streams, where start = index * row_block would
        lie. Cached per set (O(pages) once, O(1) per access)."""
        cached = self._layout.get(sid)
        if cached is not None:
            return cached
        import itertools

        (rows, cols), _, dtype = self._meta[sid]
        width = max(dtype.itemsize * cols, 1)
        ns = [self.backend.page_size(pid) // width
              for pid in self.backend.set_pages(sid)]
        starts = list(itertools.accumulate([0] + ns[:-1]))
        self._layout[sid] = (ns, starts)
        return ns, starts

    def meta(self, name: str) -> Tuple[Tuple[int, int], Tuple[int, int],
                                       np.dtype]:
        """((rows, cols), (row_block, cols), dtype) of a stored matrix
        — the public face of the per-set metadata (PagedTensor and the
        serve layer read shape/dtype through this, never the private
        maps)."""
        return self._meta[self._ids[name]]

    def read_block(self, name: str, index: int) -> Tuple[int, np.ndarray]:
        """Random access to one row-block: (start_row, block). The
        pin-one-partition access pattern of a partitioned hash table
        (ref ``src/queryExecution/headers/HashSetManager.h`` /
        PartitionedHashSet) — a build side stored with
        ``row_block=partition_rows`` makes partition *p* exactly block
        *p*, resident only while probed, spillable in between."""
        sid = self._ids[name]
        (rows, cols), _, dtype = self._meta[sid]
        pids = self.backend.set_pages(sid)
        if not 0 <= index < len(pids):
            raise IndexError(f"block {index} out of range "
                             f"({len(pids)} blocks in {name!r})")
        ns, starts = self._block_layout(sid)
        raw = self.backend.read_page(pids[index])
        return starts[index], np.frombuffer(raw, dtype=dtype).reshape(
            ns[index], cols)

    def rewrite_block(self, name: str, index: int,
                      block: np.ndarray) -> None:
        """Overwrite one row-block IN PLACE (same shape — the
        update-in-place write path: a column update rewrites each page
        it lives in without moving any other page). The block layout
        is unchanged by construction, so derived metadata stays
        valid."""
        sid = self._ids[name]
        (_rows, cols), _, dtype = self._meta[sid]
        pids = self.backend.set_pages(sid)
        if not 0 <= index < len(pids):
            raise IndexError(f"block {index} out of range "
                             f"({len(pids)} blocks in {name!r})")
        ns, _starts = self._block_layout(sid)
        block = np.ascontiguousarray(block, dtype=dtype)
        if block.shape != (ns[index], cols):
            raise ValueError(
                f"rewrite_block: block {index} of {name!r} is "
                f"{(ns[index], cols)}, got {block.shape} — in-place "
                f"rewrites must preserve the block's shape")
        self.backend.overwrite_page(pids[index], block.tobytes())

    def num_blocks(self, name: str) -> int:
        return len(self.backend.set_pages(self._ids[name]))

    def block_ranges(self, name: str) -> list:
        """[(start_row, end_row)] per block, METADATA ONLY (derived
        from page sizes — zero page-data reads). The partial-run
        device cache plans its range stitching against this: each
        streamed chunk's identity is its row range."""
        sid = self._ids[name]
        ns, starts = self._block_layout(sid)
        return [(s, s + n) for s, n in zip(starts, ns)]

    def stream_blocks(self, name: str,
                      prefetch: Optional[int] = None,
                      blocks: Optional[list] = None
                      ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield (start_row, block) in order — the PageScanner loop.

        ``prefetch`` pages are read ahead on a background thread (the
        reference's PageCircularBuffer between its scan thread and the
        pipeline threads — ``src/storage/headers/PageCircularBuffer.h``)
        so disk/arena reads overlap the consumer's compute; 0 disables,
        None takes the ``config.stream_prefetch_pages`` knob.

        ``blocks`` (sorted block indices) restricts the stream to just
        those pages — the GAP feed of a range-stitched cached stream
        (``plan/staging``): pages whose chunks are already device-
        resident are never read from the arena at all.
        """
        if prefetch is None:
            prefetch = getattr(self.config, "stream_prefetch_pages", 2)
        sid = self._ids[name]
        (rows, cols), _, dtype = self._meta[sid]
        pids = self.backend.set_pages(sid)
        _, starts = self._block_layout(sid)
        if blocks is not None:
            pids = [pids[i] for i in blocks]
            starts = [starts[i] for i in blocks]

        def view(raw, start):
            n = len(raw) // max(dtype.itemsize * cols, 1)
            return np.frombuffer(raw, dtype=dtype).reshape(n, cols)

        if prefetch <= 0 or len(pids) <= 1:
            for pid, start in zip(pids, starts):
                yield start, view(self.backend.read_page(pid), start)
            return

        import queue

        q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        SENTINEL = object()
        stop = threading.Event()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def reader():
            try:
                for pid, start in zip(pids, starts):
                    if not put((start, self.backend.read_page(pid))):
                        return  # consumer abandoned the stream
            except BaseException as e:  # ANY death must unblock the consumer
                put((SENTINEL, e))
                return
            put((SENTINEL, None))

        t = threading.Thread(target=reader, daemon=True)
        with self._readers_lock:
            if self._closed:  # backend may already be freed
                raise RuntimeError("PagedTensorStore is closed")
            self._readers[:] = [(rt, rs) for rt, rs in self._readers
                                if rt.is_alive()]
            self._readers.append((t, stop))
        t.start()
        try:
            while True:
                try:
                    start, raw = q.get(timeout=0.5)
                except queue.Empty:
                    if not t.is_alive():  # died without a sentinel
                        raise RuntimeError("page reader thread died")
                    continue
                if start is SENTINEL:
                    if raw is not None:
                        raise raw
                    break
                yield start, view(raw, start)
        finally:
            stop.set()
            t.join(timeout=5)

    def to_device_blocked(self, name: str, block_shape=None):
        """Stream into HBM chunk-by-chunk and assemble a BlockedTensor —
        the dense array never exists on host; uploads run a staging
        depth ahead of the assembly (``plan/staging``)."""
        import contextlib

        import jax.numpy as jnp

        from netsdb_tpu.core.blocked import BlockMeta, BlockedTensor
        from netsdb_tpu.plan.staging import stage_stream
        from netsdb_tpu.storage.devcache import to_device

        sid = self._ids[name]
        (rows, cols), _, dtype = self._meta[sid]
        block_shape = block_shape or self.config.default_block_shape
        meta = BlockMeta((rows, cols), tuple(block_shape))
        chunks = []
        with contextlib.closing(stage_stream(
                self.stream_blocks(name),
                lambda item: to_device(item[1]),
                depth=getattr(self.config, "stage_depth", 2),
                name=f"blocked:{name}")) as staged:
            for chunk in staged:
                chunks.append(chunk)
        data = jnp.concatenate(chunks, axis=0)
        pad = [(0, p - s) for s, p in zip((rows, cols), meta.padded_shape)]
        if any(p for _, p in pad):
            data = jnp.pad(data, pad)
        return BlockedTensor(data, meta)

    def matmul_streamed(self, name: str, rhs: np.ndarray,
                        stage_depth: Optional[int] = None,
                        devcache=None,
                        cache_scope: Optional[str] = None,
                        stats_out: Optional[Dict[str, Any]] = None
                        ) -> np.ndarray:
        """out = M @ rhs with M streamed page-by-page through the device
        — the larger-than-HBM compute pattern (reference: pipelines over
        pinned pages). Only one page + rhs (plus the staged NEXT page)
        live on device at a time: the upload of block *i+1* runs on the
        staging thread while block *i*'s matmul computes
        (``plan/staging.stage_stream``), and ragged blocks pad up to
        the row-block's shape bucket (zero rows, output rows sliced
        back off — exact) so the whole stream runs ONE compiled
        program. ``stage_depth`` pins the staging depth (None = the
        ``config.stage_depth`` knob; 0 = the synchronous baseline the
        staging bench measures against).

        With ``config.distributed_matmul`` on and >1 device visible,
        the stream routes through the SUMMA engine instead
        (``parallel/summa.py``): each mesh participant stages only its
        own panel of M and rhs, per-step panel broadcasts move B over
        the mesh axis, and per-host staged bytes drop to ~1/N.
        ``devcache``/``cache_scope`` (store-owned sets pass them) opt
        the SUMMA panels into the block-granular device cache under
        the mesh-labelled key."""
        import contextlib

        import jax
        import jax.numpy as jnp

        from netsdb_tpu.plan.staging import pad_rows_target, stage_stream
        from netsdb_tpu.storage.devcache import to_device

        if getattr(self.config, "distributed_matmul", False):
            from netsdb_tpu.parallel import summa

            devices = jax.devices()
            cap = getattr(self.config, "summa_participants", None)
            if cap:
                devices = devices[:int(cap)]
            grid = summa.grid_shape(self.config, len(devices))
            if grid is not None:
                return summa.summa_grid_matmul_streamed(
                    self, name, rhs, devices=devices, grid=grid,
                    stage_depth=stage_depth, cache=devcache,
                    cache_scope=cache_scope, stats_out=stats_out)
            if len(devices) >= 2:
                return summa.summa_matmul_streamed(
                    self, name, rhs, devices=devices,
                    stage_depth=stage_depth, cache=devcache,
                    cache_scope=cache_scope, stats_out=stats_out)

        depth = getattr(self.config, "stage_depth", 2) \
            if stage_depth is None else stage_depth
        bucketing = getattr(self.config, "shape_bucketing", True)
        density = getattr(self.config, "bucket_density", 2)
        rb = self._meta[self._ids[name]][1][0]
        rhs_dev = to_device(rhs)

        @jax.jit
        def block_mm(a, b):
            return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                       precision=jax.lax.Precision.HIGHEST,
                                       preferred_element_type=jnp.float32)

        def place(item):
            _start, block = item
            n = block.shape[0]
            target = pad_rows_target(max(n, rb), bucketing,
                                     density=density)
            if target > n:
                block = np.pad(block, ((0, target - n), (0, 0)))
            return n, to_device(block)

        outs = []
        with contextlib.closing(stage_stream(
                self.stream_blocks(name), place, depth,
                name=f"mm:{name}")) as staged:
            for n, block in staged:
                out = np.asarray(block_mm(block, rhs_dev))
                outs.append(out[:n] if out.shape[0] != n else out)
        return np.concatenate(outs, axis=0)

    def drop(self, name: str) -> None:
        """Free a matrix's pages from the arena (and its spill files) —
        the page-reclaim hook ``SetStore.remove_set`` uses so dropping
        a paged set returns its space to the shared capped pool."""
        sid = self._ids.pop(name, None)
        if sid is None:
            return
        for pid in self.backend.set_pages(sid):
            self.backend.free_page(pid)
        self._meta.pop(sid, None)
        self._layout.pop(sid, None)

    def stats(self) -> dict:
        return self.backend.stats()

    def close(self):
        # stop + join any live prefetch readers BEFORE freeing the
        # native arena they may be reading from
        with self._readers_lock:
            self._closed = True  # no new readers may register after this
            readers = list(self._readers)
            self._readers.clear()
        for t, stop in readers:
            stop.set()
        for t, stop in readers:
            t.join(timeout=30)
        still_alive = [t for t, _ in readers if t.is_alive()]
        if still_alive or getattr(self, "_leaked", False):
            # a reader wedged inside read_page (hung IO): destroying the
            # arena under it is a use-after-free — leak the backend
            # instead (process exit reclaims it). The flag makes later
            # close() calls keep leaking rather than free it after all.
            self._leaked = True
            import warnings

            warnings.warn(
                f"PagedTensorStore.close: {len(still_alive)} prefetch "
                f"reader(s) did not stop; leaking the page store to "
                f"avoid freeing memory they may still touch")
            return
        self.backend.close()
