"""Cross-query device-resident block cache — the buffer pool for HBM.

netsDB's workers owe their repeat-query speed to the shared-memory
buffer pool: pages of a hot set stay PINNED across jobs, so the second
query over ``lineitem`` never touches storage again
(``src/storage/headers/PageCache.h:106-118`` — pin/unpin + eviction
under one memory budget). Our reproduction had no analogue: every
serve ``EXECUTE`` re-read the arena, re-padded and re-``device_put``
every chunk of a set that was device-resident milliseconds ago. The
TPU literature says the same discipline is what makes pipelines fast —
keep operands device-resident across calls and ship only deltas
(arxiv 2112.09017 §IV); at this scale the avoided TRANSFERS dominate,
not kernel tweaks.

:class:`DeviceBlockCache` is that buffer pool for placed blocks:

* **Keying** — entries key on
  ``(scope, version, mutations, kind, bucket, sharding)`` where
  ``scope`` is the set identity (``"db:set"``), ``version`` the
  store's monotonic per-set write version (bumped by EVERY path that
  can change a set: ingest, BULK COMMIT, mirrored frames, resync,
  checkpoint restore — ``SetStore._touch``), ``mutations`` the
  relation handle's own append/drop counter (covers direct
  ``PagedColumns.append`` callers that bypass the store), ``bucket``
  the chunk pad target and ``sharding`` the placement label. A write
  moves the version, so a stale entry can never MATCH again — version
  keying is the correctness mechanism; eviction is only about memory.
* **Budget** — entries LRU-evict under ``config.device_cache_bytes``
  (``PageCache::evict`` under one pool size). An entry bigger than the
  whole budget is simply not installed.
* **Introspection** — hit/miss/install/evict/invalidate counters plus
  live bytes/entries, surfaced ``compile_stats()``-style via
  :meth:`stats` and through the serve ``COLLECT_STATS`` frame.
* **Ownership** — cached blocks are owned by the CACHE, not by any one
  execution: they are never donation targets. Fold steps donate only
  their carried accumulator (argument 0 — ``staging.
  fold_donate_argnums``); a jit must never be handed a cached block
  with ``donate_argnums`` covering it, or XLA would free a buffer the
  next query expects to reuse.

The one blessed upload helper, :func:`to_device`, lives here so the
static check (``tests/test_static_checks.py``) can ban direct
``device_put`` of store-owned set blocks everywhere else in
``storage/``, ``plan/`` and the out-of-core engine — future call sites
cannot silently bypass the cache/staging layer.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from netsdb_tpu import obs
from netsdb_tpu.utils.locks import TrackedLock


def to_device(x, sharding=None):
    """The ONE sanctioned host→device upload for store-owned blocks
    (everything else goes through ``plan/staging.stage_stream``, whose
    ``place`` functions call this). Centralized so the static check can
    ban loose ``device_put`` call sites."""
    import jax

    if sharding is not None:
        return jax.device_put(x, sharding)
    return jax.device_put(x)


def _array_nbytes(arr) -> int:
    """Bytes of one column/array WITHOUT touching its data: jax and
    numpy arrays expose ``nbytes`` as shape×itemsize metadata — calling
    ``np.asarray`` here would be a blocking device→host copy of the
    whole buffer just for accounting."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    import numpy as np

    return int(np.asarray(arr).nbytes)


def _value_nbytes(value) -> int:
    """Recursive byte accounting for a cached run: ColumnTables, jax
    arrays, numpy arrays, (n, block) tuples — anything a ``place``
    function yields. Metadata-only (never reads array data)."""
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    cols = getattr(value, "cols", None)
    if cols is not None:  # ColumnTable-shaped
        total = sum(_array_nbytes(v) for v in cols.values())
        valid = getattr(value, "valid", None)
        if valid is not None:
            total += _array_nbytes(valid)
        return total
    if getattr(value, "nbytes", None) is not None:
        return int(value.nbytes)
    if isinstance(value, dict):  # raw column maps (PagedColumns.stream)
        return sum(_value_nbytes(v) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(_value_nbytes(v) for v in value)
    return 64  # scalars / ints riding along with blocks


class DeviceBlockCache:
    """LRU cache of placed set-block runs under one byte budget.

    A cache ENTRY is one whole run — the ordered list of placed chunks
    one full stream of a set produces (matching the key's bucket and
    sharding). Whole-run granularity matches the key the tentpole
    names: ``(db, set, version, bucket, sharding)`` — a warm consumer
    replays the run without touching the arena or the transfer path at
    all, which is what makes the warm serve ``EXECUTE`` zero-copy.

    Thread-safe: consults happen on consumer threads, installs on
    staging threads, invalidations on serve handler threads.
    """

    def __init__(self, budget_bytes: int = 0):
        self._mu = TrackedLock("DeviceBlockCache._mu")
        self._budget = int(budget_bytes or 0)
        # key -> (blocks, nbytes); insertion order IS recency order
        self._entries: "OrderedDict[Tuple, Tuple[List[Any], int]]" = \
            OrderedDict()
        # scope -> keys, for prompt invalidation (version keying alone
        # already guarantees freshness; this returns the bytes NOW)
        self._by_scope: Dict[str, set] = {}
        self._bytes = 0
        self._stats = {"hits": 0, "misses": 0, "installs": 0,
                       "evictions": 0, "invalidations": 0,
                       "rejected": 0}

    # --- sizing -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._budget > 0

    @property
    def budget_bytes(self) -> int:
        return self._budget

    def resize(self, budget_bytes: int) -> None:
        """Re-point the budget (the serve knob path / the bench's
        cache-off baseline). Shrinking evicts immediately."""
        with self._mu:
            self._budget = int(budget_bytes or 0)
            self._evict_to_fit_locked(0)

    # --- the data path ------------------------------------------------
    def get(self, key: Tuple) -> Optional[List[Any]]:
        """The run cached under ``key``, or None (counted as a miss).
        Hits refresh LRU recency. Per-store counters stay on this
        instance (``stats()`` keeps its shape); the process-wide
        registry, the active query trace and the per-(client, set)
        resource ledger get the same tick — the profile's devcache
        hit/miss decomposition and the attribution the scheduler
        admits against. ``devcache.lookups`` (hits + misses in one
        monotonic counter) feeds the hit-rate SLO (obs/slo.py)."""
        with self._mu:
            if not self.enabled:
                return None
            entry = self._entries.get(key)
            if entry is None:
                self._stats["misses"] += 1
                entry = None
            else:
                self._entries.move_to_end(key)
                self._stats["hits"] += 1
        obs.REGISTRY.counter("devcache.lookups").inc()
        scope = str(key[0])
        if entry is None:
            obs.REGISTRY.counter("devcache.misses").inc()
            obs.add("devcache.misses")
            obs.operators.op_add("devcache.misses")
            obs.attrib.account("devcache.misses", scope=scope)
            return None
        obs.REGISTRY.counter("devcache.hits").inc()
        obs.add("devcache.hits")
        obs.operators.op_add("devcache.hits")
        obs.attrib.account("devcache.hits", scope=scope)
        return entry[0]

    def has_scope(self, scope: str) -> bool:
        """True when ANY run of ``scope`` ("db:set") is resident — the
        cache-aware admission probe (serve/sched/policy.AffinityGate):
        "is this set warm?", without touching the hit/miss counters
        (an admission decision must not move the SLO feeds it reads)."""
        with self._mu:
            return bool(self._by_scope.get(str(scope)))

    def make_room(self, nbytes: int) -> None:
        """Evict LRU entries until ``nbytes`` of headroom exists under
        the budget. Called INCREMENTALLY by the recorder while a cold
        stream installs-in-progress (``staging._CacheRecorder``), so
        peak device residency stays ~one budget — resident entries plus
        the in-flight run together — instead of transiently doubling at
        install time. Best-effort across concurrent recorders (two
        simultaneous cold streams can still briefly sum above budget)."""
        with self._mu:
            if self.enabled:
                self._evict_to_fit_locked(min(int(nbytes), self._budget))

    def reject_oversized(self) -> None:
        """Count a run the recorder refused to hold (it outgrew the
        whole budget mid-stream — ``staging._CacheRecorder``)."""
        with self._mu:
            if self.enabled:
                self._stats["rejected"] += 1

    def install(self, key: Tuple, blocks: List[Any],
                validator=None, client: Optional[str] = None) -> bool:
        """Insert one complete run. Returns False when the run exceeds
        the whole budget (never installed — a set bigger than the cache
        streams every time, it does not thrash everyone else out).

        ``client``: the attributed identity for the per-(client, set)
        ledger — installs run on STAGING threads, which don't inherit
        the dispatch context var, so the recorder captures the identity
        on the consumer thread and passes it here explicitly.

        ``validator`` (no-arg → bool) is evaluated INSIDE the cache
        lock: the write path bumps the set version BEFORE invalidating
        (``SetStore._touch``), so a validator that re-derives the key
        from the current version and runs after an invalidate always
        sees the bump and rejects — check-then-install cannot race a
        write into stranding a dead entry on the budget."""
        nbytes = _value_nbytes(blocks)
        with self._mu:
            if not self.enabled or nbytes > self._budget:
                if self.enabled:
                    self._stats["rejected"] += 1
                return False
            if validator is not None and not validator():
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._evict_to_fit_locked(nbytes)
            self._entries[key] = (blocks, nbytes)
            self._bytes += nbytes
            self._by_scope.setdefault(str(key[0]), set()).add(key)
            self._stats["installs"] += 1
        obs.REGISTRY.counter("devcache.installs").inc()
        obs.add("devcache.installs")
        obs.attrib.account("devcache.installs", scope=str(key[0]),
                           client=client)
        return True

    def _evict_to_fit_locked(self, incoming: int) -> None:
        while self._entries and self._bytes + incoming > self._budget:
            old_key, (_, old_bytes) = self._entries.popitem(last=False)
            self._bytes -= old_bytes
            scoped = self._by_scope.get(str(old_key[0]))
            if scoped is not None:
                scoped.discard(old_key)
                if not scoped:
                    self._by_scope.pop(str(old_key[0]), None)
            self._stats["evictions"] += 1
            obs.REGISTRY.counter("devcache.evictions").inc()

    # --- invalidation -------------------------------------------------
    def invalidate(self, scope: str) -> int:
        """Drop every entry of one set NOW (the write-path hook —
        version keying already prevents stale reads; this returns the
        dead bytes to the budget immediately). Returns entries
        dropped."""
        with self._mu:
            keys = self._by_scope.pop(str(scope), None)
            if not keys:
                return 0
            dropped = 0
            for key in keys:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self._bytes -= entry[1]
                    dropped += 1
            self._stats["invalidations"] += dropped
        obs.REGISTRY.counter("devcache.invalidations").inc(dropped)
        return dropped

    def clear(self) -> int:
        """Drop everything (the resync-restore hook: the whole store
        was just replaced wholesale)."""
        with self._mu:
            dropped = len(self._entries)
            self._entries.clear()
            self._by_scope.clear()
            self._bytes = 0
            self._stats["invalidations"] += dropped
            return dropped

    # --- introspection ------------------------------------------------
    def stats(self) -> Dict[str, int]:
        """Counter snapshot (the ``compile_stats()`` analogue for the
        transfer path) — also shipped in the serve COLLECT_STATS
        reply."""
        with self._mu:
            out = dict(self._stats)
            out["bytes"] = self._bytes
            out["entries"] = len(self._entries)
            out["budget_bytes"] = self._budget
            return out
