"""Cross-query device-resident block cache — the buffer pool for HBM.

netsDB's workers owe their repeat-query speed to the shared-memory
buffer pool: pages of a hot set stay PINNED across jobs, so the second
query over ``lineitem`` never touches storage again
(``src/storage/headers/PageCache.h:106-118`` — pin/unpin + eviction
under one memory budget). Our reproduction had no analogue: every
serve ``EXECUTE`` re-read the arena, re-padded and re-``device_put``
every chunk of a set that was device-resident milliseconds ago. The
TPU literature says the same discipline is what makes pipelines fast —
keep operands device-resident across calls and ship only deltas
(arxiv 2112.09017 §IV); at this scale the avoided TRANSFERS dominate,
not kernel tweaks.

:class:`DeviceBlockCache` is that buffer pool for placed blocks:

* **Keying** — entries key on
  ``(scope, version, mutations, kind, bucket, sharding)`` where
  ``scope`` is the set identity (``"db:set"``), ``version`` the
  store's monotonic per-set write version (bumped by EVERY path that
  can change a set: ingest, BULK COMMIT, mirrored frames, resync,
  checkpoint restore — ``SetStore._touch``), ``mutations`` the
  relation handle's own append/drop counter (covers direct
  ``PagedColumns.append`` callers that bypass the store), ``bucket``
  the chunk pad target and ``sharding`` the placement label. A write
  moves the version, so a stale entry can never MATCH again — version
  keying is the correctness mechanism; eviction is only about memory.
* **Budget** — entries LRU-evict under ``config.device_cache_bytes``
  (``PageCache::evict`` under one pool size). An entry bigger than the
  whole budget is simply not installed.
* **Introspection** — hit/miss/install/evict/invalidate counters plus
  live bytes/entries, surfaced ``compile_stats()``-style via
  :meth:`stats` and through the serve ``COLLECT_STATS`` frame.
* **Ownership** — cached blocks are owned by the CACHE, not by any one
  execution: they are never donation targets. Fold steps donate only
  their carried accumulator (argument 0 — ``staging.
  fold_donate_argnums``); a jit must never be handed a cached block
  with ``donate_argnums`` covering it, or XLA would free a buffer the
  next query expects to reuse.

With ``config.device_cache_partial`` (the default) the cache is
additionally **block-granular** — netsDB pins per PAGE, never per set
(``PageCache.h`` pin/unpin is a page-level contract), and the
whole-run design above could not keep a huge set's hot prefix
resident across appends: one small write unkeyed the entire run.
Partial mode installs each placed chunk as its own entry under
``(scope, kind, bucket, sharding, block_range)``, stitches contiguous
cached ranges into cold streams (``plan/staging.stage_stream`` serves
cached ranges from HBM with zero arena reads while gaps stream
normally), replaces version keying with per-page **dirty-range
invalidation** (``SetStore._touch`` passes the appended tail range;
only intersecting blocks drop), and optionally PINS a set's head
blocks under ``config.device_cache_pin_bytes`` so the hot prefix
survives LRU pressure. ``device_cache_partial=False`` restores the
whole-run behavior byte-for-byte.

The one blessed upload helper, :func:`to_device`, lives here so the
static check (``tests/test_static_checks.py``) can ban direct
``device_put`` of store-owned set blocks everywhere else in
``storage/``, ``plan/`` and the out-of-core engine — future call sites
cannot silently bypass the cache/staging layer.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from netsdb_tpu import obs
from netsdb_tpu.utils.locks import TrackedLock


def to_device(x, sharding=None):
    """The ONE sanctioned host→device upload for store-owned blocks
    (everything else goes through ``plan/staging.stage_stream``, whose
    ``place`` functions call this). Centralized so the static check can
    ban loose ``device_put`` call sites."""
    import jax

    if sharding is not None:
        return jax.device_put(x, sharding)
    return jax.device_put(x)


#: scope prefix for session-state entries — namespaced so a session
#: scope can never collide with a set scope ("db:set") in the
#: by-scope index or the affinity gate's warm probe.
SESSION_SCOPE_PREFIX = "__session__:"


def session_scope(sid: str) -> str:
    """The by-scope index key for one session's state entries."""
    return SESSION_SCOPE_PREFIX + str(sid)


def _array_nbytes(arr) -> int:
    """Bytes of one column/array WITHOUT touching its data: jax and
    numpy arrays expose ``nbytes`` as shape×itemsize metadata — calling
    ``np.asarray`` here would be a blocking device→host copy of the
    whole buffer just for accounting."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    import numpy as np

    return int(np.asarray(arr).nbytes)


def _value_nbytes(value) -> int:
    """Recursive byte accounting for a cached run: ColumnTables, jax
    arrays, numpy arrays, (n, block) tuples — anything a ``place``
    function yields. Metadata-only (never reads array data)."""
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    cols = getattr(value, "cols", None)
    if cols is not None:  # ColumnTable-shaped
        total = sum(_array_nbytes(v) for v in cols.values())
        valid = getattr(value, "valid", None)
        if valid is not None:
            total += _array_nbytes(valid)
        return total
    if getattr(value, "nbytes", None) is not None:
        return int(value.nbytes)
    if isinstance(value, dict):  # raw column maps (PagedColumns.stream)
        return sum(_value_nbytes(v) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(_value_nbytes(v) for v in value)
    return 64  # scalars / ints riding along with blocks


class DeviceBlockCache:
    """LRU cache of placed set blocks under one byte budget.

    Two entry granularities share the budget, the LRU order and the
    invalidation index:

    * **whole-run entries** (the PR 4 design, and the only kind when
      ``partial=False``) — one entry per complete stream of a set,
      keyed ``(scope, version, mutations, kind, bucket, sharding)``;
      version keying is the correctness mechanism, eviction is only
      about memory.
    * **block entries** (``partial=True`` — the netsDB pin-per-page
      discipline) — one entry per placed chunk, keyed
      ``base_key + ((start, end),)`` where ``base_key`` is
      ``(scope, kind, bucket, sharding, …)`` WITHOUT the write
      version: freshness comes from **dirty-range invalidation**
      (:meth:`invalidate_range` drops only intersecting blocks), so a
      tail append leaves every pre-append block resident and a warm
      re-query re-stages only the gap. A per-scope **epoch** (bumped
      by every invalidation touching the scope) gates installs: a
      block placed before a racing write carries the old epoch and is
      refused, so a dead entry can never squat on the budget.

    Partial-mode run-level counters keep their PR 4 meaning: a
    ``plan_ranges`` consult with FULL coverage counts one ``hit``, any
    gap counts one ``miss``, and an installer that lands every gap
    block of its stream counts one ``install`` — while per-block
    serving ticks ``partial_hits`` and stitched contiguous cached
    ranges tick ``stitched_ranges``.

    Thread-safe: consults happen on consumer threads, installs on
    staging threads, invalidations on serve handler threads.
    """

    def __init__(self, budget_bytes: int = 0, partial: bool = False,
                 pin_bytes: int = 0):
        self._mu = TrackedLock("DeviceBlockCache._mu")
        self._budget = int(budget_bytes or 0)
        self.partial = bool(partial)
        self._pin_budget = int(pin_bytes or 0)
        # key -> (blocks, nbytes); insertion order IS recency order.
        # Block entries hold a single-element blocks list.
        self._entries: "OrderedDict[Tuple, Tuple[List[Any], int]]" = \
            OrderedDict()
        # scope -> keys, for prompt invalidation (version keying alone
        # already guarantees freshness; this returns the bytes NOW)
        self._by_scope: Dict[str, set] = {}
        self._bytes = 0
        self._stats = {"hits": 0, "misses": 0, "installs": 0,
                       "evictions": 0, "invalidations": 0,
                       "rejected": 0}
        if self.partial:
            self._stats.update({"partial_hits": 0, "stitched_ranges": 0,
                                "dirty_invalidations": 0,
                                "pinned_bytes": 0})
        # --- partial-mode state (all guarded by _mu) ---
        # scope -> monotonic dirty epoch (bumped by every invalidation
        # touching the scope; installs are epoch-gated)
        self._epochs: Dict[str, int] = {}
        # pinned block keys (skipped by LRU eviction) + the global
        # pinned-byte total under _pin_budget
        self._pinned: set = set()
        self._pinned_bytes = 0
        # base_key -> end row of the contiguous pinned head prefix
        # (pinning only ever extends the prefix, in install order)
        self._pin_hw: Dict[Tuple, int] = {}
        # base_key -> total rows of the set as of the last plan (the
        # coverage probe's denominator)
        self._totals: Dict[Tuple, int] = {}
        # True when the pin budget is being driven by the feedback
        # loop (config.device_cache_pin_auto) rather than the static
        # knob — annotated in stats() so operators can tell which
        self._pin_auto = False
        # --- session-state entries (serve/sessions.py) ---
        # the third entry family: TTL'd MUTABLE per-session decode
        # state (recurrent h/c vectors, KV pages), keyed
        # ``(session_scope(sid), model, layer)``. Never version-keyed —
        # the blessed sessions.py update path swaps the value in place
        # on every decode step, so freshness is the writer's contract,
        # not the cache's. Entries share the LRU order and byte budget
        # with both block families; eviction and TTL expiry SPILL the
        # state through ``_session_spill_cb`` (the host-arena escape
        # hatch) instead of losing it. key -> meta dict
        # {"deadline": monotonic expiry, "ttl": seconds,
        #  "expired": bool (set by the sweep for counter attribution)}.
        self._session_meta: Dict[Tuple, Dict[str, Any]] = {}
        self._session_spill_cb: Optional[
            Callable[[str, str, str, Any], None]] = None
        self._stats.update({"session_evictions": 0,
                            "session_expirations": 0})
        # session_* stats keys stay hidden until the session lane is
        # actually wired (set_session_spill / session_put) — a plain
        # client cache keeps the original stats surface, same deal as
        # the partial-mode keys
        self._session_on = False

    # --- sizing -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._budget > 0

    @property
    def budget_bytes(self) -> int:
        return self._budget

    def resize(self, budget_bytes: int) -> None:
        """Re-point the budget (the serve knob path / the bench's
        cache-off baseline). Shrinking evicts immediately."""
        with self._mu:
            self._budget = int(budget_bytes or 0)
            if self._budget < self._pinned_bytes:
                # a shrink below the pinned total lifts every pin —
                # the operator explicitly chose the smaller pool
                self._pinned.clear()
                self._pinned_bytes = 0
                self._pin_hw.clear()
                if "pinned_bytes" in self._stats:
                    self._stats["pinned_bytes"] = 0
            self._evict_to_fit_locked(0)

    def set_pin_budget(self, pin_bytes: int, auto: bool = False) -> None:
        """Re-point the hot-prefix pin budget (partial mode only) —
        the ``device_cache_pin_auto`` feedback hook and the serve knob
        path. Shrinking below the currently pinned total lifts every
        pin (head blocks re-pin as streams reinstall them — the
        conservative reset; LRU then treats them like any entry).
        ``auto`` annotates :meth:`stats` with who is driving the
        budget."""
        with self._mu:
            if not self.partial:
                return
            self._pin_budget = max(int(pin_bytes or 0), 0)
            self._pin_auto = bool(auto)
            if self._pinned_bytes > self._pin_budget:
                self._pinned.clear()
                self._pinned_bytes = 0
                self._pin_hw.clear()
            if "pinned_bytes" in self._stats:
                self._stats["pinned_bytes"] = self._pinned_bytes
        obs.REGISTRY.gauge("devcache.pinned_bytes").set(
            self._pinned_bytes)

    # --- the data path ------------------------------------------------
    def get(self, key: Tuple) -> Optional[List[Any]]:
        """The run cached under ``key``, or None (counted as a miss).
        Hits refresh LRU recency. Per-store counters stay on this
        instance (``stats()`` keeps its shape); the process-wide
        registry, the active query trace and the per-(client, set)
        resource ledger get the same tick — the profile's devcache
        hit/miss decomposition and the attribution the scheduler
        admits against. ``devcache.lookups`` (hits + misses in one
        monotonic counter) feeds the hit-rate SLO (obs/slo.py)."""
        with self._mu:
            if not self.enabled:
                return None
            entry = self._entries.get(key)
            if entry is None:
                self._stats["misses"] += 1
                entry = None
            else:
                self._entries.move_to_end(key)
                self._stats["hits"] += 1
        obs.REGISTRY.counter("devcache.lookups").inc()
        scope = str(key[0])
        if entry is None:
            obs.REGISTRY.counter("devcache.misses").inc()
            obs.add("devcache.misses")
            obs.operators.op_add("devcache.misses")
            obs.attrib.account("devcache.misses", scope=scope)
            return None
        obs.REGISTRY.counter("devcache.hits").inc()
        obs.add("devcache.hits")
        obs.operators.op_add("devcache.hits")
        obs.attrib.account("devcache.hits", scope=scope)
        return entry[0]

    def has_scope(self, scope: str) -> bool:
        """True when ANY run of ``scope`` ("db:set") is resident — the
        cache-aware admission probe (serve/sched/policy.AffinityGate):
        "is this set warm?", without touching the hit/miss counters
        (an admission decision must not move the SLO feeds it reads)."""
        with self._mu:
            return bool(self._by_scope.get(str(scope)))

    def make_room(self, nbytes: int) -> None:
        """Evict LRU entries until ``nbytes`` of headroom exists under
        the budget. Called INCREMENTALLY by the recorder while a cold
        stream installs-in-progress (``staging._CacheRecorder``), so
        peak device residency stays ~one budget — resident entries plus
        the in-flight run together — instead of transiently doubling at
        install time. Best-effort across concurrent recorders (two
        simultaneous cold streams can still briefly sum above budget)."""
        with self._mu:
            if self.enabled:
                self._evict_to_fit_locked(min(int(nbytes), self._budget))

    def reject_oversized(self) -> None:
        """Count a run the recorder refused to hold (it outgrew the
        whole budget mid-stream — ``staging._CacheRecorder``)."""
        with self._mu:
            if self.enabled:
                self._stats["rejected"] += 1

    def install(self, key: Tuple, blocks: List[Any],
                validator=None, client: Optional[str] = None) -> bool:
        """Insert one complete run. Returns False when the run exceeds
        the whole budget (never installed — a set bigger than the cache
        streams every time, it does not thrash everyone else out).

        ``client``: the attributed identity for the per-(client, set)
        ledger — installs run on STAGING threads, which don't inherit
        the dispatch context var, so the recorder captures the identity
        on the consumer thread and passes it here explicitly.

        ``validator`` (no-arg → bool) is evaluated INSIDE the cache
        lock: the write path bumps the set version BEFORE invalidating
        (``SetStore._touch``), so a validator that re-derives the key
        from the current version and runs after an invalidate always
        sees the bump and rejects — check-then-install cannot race a
        write into stranding a dead entry on the budget."""
        nbytes = _value_nbytes(blocks)
        with self._mu:
            if not self.enabled or nbytes > self._budget:
                if self.enabled:
                    self._stats["rejected"] += 1
                return False
            if validator is not None and not validator():
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._evict_to_fit_locked(nbytes)
            self._entries[key] = (blocks, nbytes)
            self._bytes += nbytes
            self._by_scope.setdefault(str(key[0]), set()).add(key)
            self._stats["installs"] += 1
        obs.REGISTRY.counter("devcache.installs").inc()
        obs.add("devcache.installs")
        obs.attrib.account("devcache.installs", scope=str(key[0]),
                           client=client)
        return True

    def _evict_to_fit_locked(self, incoming: int) -> None:
        # ONE pass in LRU order collecting victims, skipping PINNED
        # block entries (a set's hot head prefix under the pin budget
        # — only invalidation drops them; when everything left is
        # pinned, eviction stops and the caller's install simply fails
        # to fit). A restart-per-victim scan would re-walk the pinned
        # head for every eviction — O(pinned × evicted) inside _mu.
        if self._bytes + incoming <= self._budget:
            return
        victims = []
        freed = 0
        for key, (_, nbytes) in self._entries.items():
            if key in self._pinned:
                continue
            victims.append(key)
            freed += nbytes
            if self._bytes - freed + incoming <= self._budget:
                break
        for key in victims:
            self._drop_entry_locked(key)
            self._stats["evictions"] += 1
        if victims:
            obs.REGISTRY.counter("devcache.evictions").inc(len(victims))

    def _drop_entry_locked(self, key: Tuple) -> bool:
        """Remove one entry (any granularity) from every index. A
        SESSION entry additionally spills its live state through the
        registered callback (host arena) before vanishing — LRU
        pressure and TTL expiry demote session state, they never lose
        it — and ticks the eviction/expiry counters the chaos tests
        and ``cli obs --sessions`` read."""
        entry = self._entries.pop(key, None)
        if entry is None:
            return False
        self._bytes -= entry[1]
        scoped = self._by_scope.get(str(key[0]))
        if scoped is not None:
            scoped.discard(key)
            if not scoped:
                self._by_scope.pop(str(key[0]), None)
        if key in self._pinned:
            self._pinned.discard(key)
            self._pinned_bytes -= entry[1]
        meta = self._session_meta.pop(key, None)
        if meta is not None:
            which = ("session_expirations" if meta.get("expired")
                     else "session_evictions")
            self._stats[which] += 1
            obs.REGISTRY.counter("session.evicted").inc()
            if self._session_spill_cb is not None:
                sid = str(key[0])[len(SESSION_SCOPE_PREFIX):]
                try:
                    self._session_spill_cb(sid, str(key[1]),
                                           str(key[2]), entry[0][0])
                except Exception:
                    pass  # spill is best-effort; the table still
                    # knows the step count and refuses silent reuse
        return True

    # --- partial mode: per-block entries + range stitching ------------
    @staticmethod
    def _block_key(base_key: Tuple, rng: Tuple[int, int]) -> Tuple:
        return tuple(base_key) + ((int(rng[0]), int(rng[1])),)

    def scope_epoch(self, scope: str) -> int:
        """The scope's current dirty epoch — captured by a stream at
        plan time and checked again at each block install, so a write
        racing the stream can never strand a stale block entry."""
        with self._mu:
            return self._epochs.get(str(scope), 0)

    def plan_ranges(self, base_key: Tuple,
                    ranges: List[Tuple[int, int]]
                    ) -> Tuple[int, Dict[Tuple[int, int], Any]]:
        """(epoch, {range: block}) for the block entries of
        ``base_key`` matching the expected ``ranges`` of one stream —
        the stitching consult. Run-level counters keep their whole-run
        meaning: full coverage is one hit, any gap one miss; the
        per-block ``partial_hits`` tick happens when blocks are
        actually SERVED (staging._StitchedStream), not here."""
        scope = str(base_key[0])
        with self._mu:
            if not (self.enabled and self.partial):
                return 0, {}
            epoch = self._epochs.get(scope, 0)
            if ranges:
                self._totals[tuple(base_key)] = int(ranges[-1][1])
            covered: Dict[Tuple[int, int], Any] = {}
            for rng in ranges:
                key = self._block_key(base_key, rng)
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    covered[(int(rng[0]), int(rng[1]))] = entry[0][0]
            full = bool(ranges) and len(covered) == len(ranges)
            self._stats["hits" if full else "misses"] += 1
        obs.REGISTRY.counter("devcache.lookups").inc()
        name = "devcache.hits" if full else "devcache.misses"
        obs.REGISTRY.counter(name).inc()
        obs.add(name)
        obs.operators.op_add(name)
        obs.attrib.account(name, scope=scope)
        return epoch, covered

    def install_block(self, base_key: Tuple, rng: Tuple[int, int],
                      block: Any, epoch: int,
                      client: Optional[str] = None) -> bool:
        """Insert ONE placed block under ``base_key + (range,)``.
        Refused when the scope's dirty epoch moved past ``epoch`` (a
        write raced the stream — the block may predate it), when the
        block alone exceeds the budget, or when eviction cannot make
        room without touching pinned entries. Head blocks (the
        contiguous prefix from row 0, in install order) are PINNED
        while the global pin budget lasts."""
        nbytes = _value_nbytes(block)
        scope = str(base_key[0])
        with self._mu:
            if not (self.enabled and self.partial):
                return False
            if self._epochs.get(scope, 0) != int(epoch):
                return False  # a write landed since the stream planned
            if nbytes > self._budget:
                self._stats["rejected"] += 1
                return False
            key = self._block_key(base_key, rng)
            if key in self._entries:  # concurrent stream won the race
                self._entries.move_to_end(key)
                return True
            self._evict_to_fit_locked(nbytes)
            if self._bytes + nbytes > self._budget:
                # everything evictable is gone and pinned entries hold
                # the rest — a cache full of pinned heads must not
                # thrash, the block simply streams uncached
                self._stats["rejected"] += 1
                return False
            self._entries[key] = ([block], nbytes)
            self._bytes += nbytes
            self._by_scope.setdefault(scope, set()).add(key)
            base = tuple(base_key)
            hw = self._pin_hw.get(base, 0)
            if (self._pin_budget > 0 and int(rng[0]) == hw
                    and self._pinned_bytes + nbytes <= self._pin_budget):
                self._pinned.add(key)
                self._pinned_bytes += nbytes
                self._pin_hw[base] = int(rng[1])
            self._stats["pinned_bytes"] = self._pinned_bytes
        obs.REGISTRY.gauge("devcache.pinned_bytes").set(
            self._pinned_bytes)
        return True

    def record_run_install(self, scope: str,
                           client: Optional[str] = None) -> None:
        """Tick the run-level ``installs`` counter once a stitched
        stream's installer landed every gap block of its run — the
        partial-mode analogue of one whole-run :meth:`install`."""
        with self._mu:
            if not (self.enabled and self.partial):
                return
            self._stats["installs"] += 1
        obs.REGISTRY.counter("devcache.installs").inc()
        obs.add("devcache.installs")
        obs.attrib.account("devcache.installs", scope=str(scope),
                           client=client)

    def tick_partial(self, scope: str, blocks_served: int,
                     stitched_ranges: int) -> None:
        """Account blocks served device-resident by a stitched stream
        (called from the consumer side as cached blocks are yielded)."""
        if blocks_served <= 0 and stitched_ranges <= 0:
            return
        with self._mu:
            if "partial_hits" in self._stats:
                self._stats["partial_hits"] += int(blocks_served)
                self._stats["stitched_ranges"] += int(stitched_ranges)
        if blocks_served > 0:
            obs.REGISTRY.counter("devcache.partial_hits").inc(
                int(blocks_served))
            obs.add("devcache.partial_hits", int(blocks_served))
            obs.operators.op_add("devcache.partial_hits",
                                 int(blocks_served))
            # attributed under the per-block name: the ledger's
            # "devcache.hits" stays run-level (plan_ranges ticks it),
            # so per-client hit-rate math against lookups never
            # exceeds 100%
            obs.attrib.account("devcache.partial_hits",
                               int(blocks_served), scope=str(scope))
        if stitched_ranges > 0:
            obs.REGISTRY.counter("devcache.stitched_ranges").inc(
                int(stitched_ranges))

    def coverage(self, scope: str) -> Tuple[int, Optional[int]]:
        """(covered_prefix_rows, total_rows) — the best contiguous
        cached prefix from row 0 over any base key of ``scope``, and
        that key's last-planned total (None when never planned). The
        scheduler's remainder-range probe (serve/sched/policy.py);
        counter-free like :meth:`has_scope`."""
        best = (0, None)
        with self._mu:
            keys = self._by_scope.get(str(scope), ())
            by_base: Dict[Tuple, List[Tuple[int, int]]] = {}
            for key in keys:
                rng = key[-1]
                if (isinstance(rng, tuple) and len(rng) == 2
                        and isinstance(rng[0], int)):
                    by_base.setdefault(key[:-1], []).append(rng)
            for base, rngs in by_base.items():
                covered = 0
                for s0, e0 in sorted(rngs):
                    if s0 > covered:
                        break
                    covered = max(covered, e0)
                total = self._totals.get(base)
                if covered > best[0] or (covered == best[0]
                                         and best[1] is None):
                    best = (covered, total)
        return best

    def invalidate_range(self, scope: str, start: int,
                         end: Optional[int] = None,
                         columns=None) -> int:
        """Drop only the entries a dirty row range intersects: block
        entries overlapping ``[start, end)`` (end=None → to infinity)
        plus every whole-run entry of the scope (version-keyed, so
        already unmatchable — dropping returns their bytes now). Bumps
        the scope's epoch either way, refusing in-flight installs
        planned before the write. Returns entries dropped.

        ``columns`` names the touched columns of an update-in-place
        write (the per-COLUMN dirty range): a block entry whose base
        key carries a column-projection marker (a ``frozenset`` —
        ``PagedColumns.partial_base_key(columns=...)``) DISJOINT from
        the touched set survives — its stream never contained the
        updated column, so its blocks are still byte-fresh. Unmarked
        entries contain every column and always drop."""
        scope = str(scope)
        columns = frozenset(columns) if columns is not None else None
        dropped = dirty = 0
        with self._mu:
            self._epochs[scope] = self._epochs.get(scope, 0) + 1
            # the write may have GROWN the set: last-planned totals are
            # stale until the next plan_ranges, and a stale total would
            # make coverage() report "fully resident" right after a
            # tail append — exactly when the affinity gate must
            # serialize the cold-tail installer, not admit everyone
            for base in [b for b in self._totals if str(b[0]) == scope]:
                self._totals.pop(base, None)
            keys = list(self._by_scope.get(scope, ()))
            for key in keys:
                rng = key[-1]
                is_block = (isinstance(rng, tuple) and len(rng) == 2
                            and isinstance(rng[0], int))
                if is_block:
                    s0, e0 = rng
                    if e0 <= start or (end is not None and s0 >= end):
                        continue  # disjoint: the block stays resident
                    if (columns is not None
                            and isinstance(key[-2], frozenset)
                            and key[-2].isdisjoint(columns)):
                        continue  # projected stream never held the
                        # updated column — still byte-fresh
                    dirty += 1
                if self._drop_entry_locked(key):
                    dropped += 1
            # the pinned head prefix may have been truncated: recompute
            # each base's high water from the SURVIVING pinned blocks
            # so re-installs re-pin from the break, not from scratch
            for base in [b for b in self._pin_hw if str(b[0]) == scope]:
                rngs = sorted(k[-1] for k in self._pinned
                              if k[:-1] == base)
                hw = 0
                for s0, e0 in rngs:
                    if s0 > hw:
                        break
                    hw = max(hw, e0)
                self._pin_hw[base] = hw
            if "dirty_invalidations" in self._stats:
                self._stats["dirty_invalidations"] += dirty
                self._stats["pinned_bytes"] = self._pinned_bytes
            self._stats["invalidations"] += dropped
        if dropped:
            obs.REGISTRY.counter("devcache.invalidations").inc(dropped)
        if dirty and self.partial:
            obs.REGISTRY.counter("devcache.dirty_invalidations").inc(
                dirty)
        obs.REGISTRY.gauge("devcache.pinned_bytes").set(
            self._pinned_bytes)
        return dropped

    # --- invalidation -------------------------------------------------
    def invalidate(self, scope: str) -> int:
        """Drop every entry of one set NOW (the write-path hook —
        version keying already prevents stale reads for whole-run
        entries; block entries NEED this, it is their correctness
        mechanism for whole-set writes). Bumps the scope's dirty epoch
        in partial mode. Returns entries dropped."""
        scope = str(scope)
        with self._mu:
            if self.partial:
                self._epochs[scope] = self._epochs.get(scope, 0) + 1
                for base in [b for b in self._pin_hw
                             if str(b[0]) == scope]:
                    self._pin_hw.pop(base, None)
                for base in [b for b in self._totals
                             if str(b[0]) == scope]:
                    self._totals.pop(base, None)
            keys = self._by_scope.pop(scope, None)
            if not keys:
                return 0
            dropped = 0
            for key in keys:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self._bytes -= entry[1]
                    dropped += 1
                if key in self._pinned:
                    self._pinned.discard(key)
                    self._pinned_bytes -= entry[1] if entry else 0
                self._session_meta.pop(key, None)  # administrative
                # drop: no spill — an operator invalidating a session
                # scope chose to discard it
            self._stats["invalidations"] += dropped
            if "pinned_bytes" in self._stats:
                self._stats["pinned_bytes"] = self._pinned_bytes
        obs.REGISTRY.counter("devcache.invalidations").inc(dropped)
        if self.partial:
            obs.REGISTRY.gauge("devcache.pinned_bytes").set(
                self._pinned_bytes)
        return dropped

    def clear(self) -> int:
        """Drop everything (the resync-restore hook: the whole store
        was just replaced wholesale)."""
        with self._mu:
            dropped = len(self._entries)
            if self.partial:
                for scope in {str(k[0]) for k in self._entries}:
                    self._epochs[scope] = self._epochs.get(scope, 0) + 1
            self._entries.clear()
            self._by_scope.clear()
            self._session_meta.clear()
            self._pinned.clear()
            self._pinned_bytes = 0
            self._pin_hw.clear()
            self._totals.clear()
            self._bytes = 0
            self._stats["invalidations"] += dropped
            if "pinned_bytes" in self._stats:
                self._stats["pinned_bytes"] = 0
            return dropped

    # --- session-state entries (TTL'd MUTABLE; serve/sessions.py) -----
    # The write methods below are the BLESSED mutation path for
    # session state: the ``session-state-mutation`` lint rule bans
    # them everywhere outside ``serve/sessions.py``, the same
    # discipline that keeps ``device_put`` behind :func:`to_device`.

    def set_session_spill(
            self, cb: Optional[Callable[[str, str, str, Any], None]]
    ) -> None:
        """Register the eviction/expiry escape hatch:
        ``cb(sid, model, layer, value)`` runs for every session entry
        LRU pressure or TTL expiry drops. The callback MUST be a leaf
        (record to the host arena and return) — it runs under the
        cache lock so a racing decode can never read the entry
        half-spilled."""
        with self._mu:
            self._session_spill_cb = cb
            if cb is not None:
                self._session_on = True

    def session_put(self, sid: str, model: str, layer: str, value: Any,
                    ttl_s: float, client: Optional[str] = None) -> bool:
        """Install (or replace) one session state entry. Unlike set
        blocks, session entries install even on a budget-less cache —
        an operator who disabled the block cache still gets sessions,
        just with no eviction pressure. Returns False only when the
        entry cannot fit under an enabled budget."""
        key = (session_scope(sid), str(model), str(layer))
        nbytes = _value_nbytes(value)
        with self._mu:
            self._session_on = True
            if self.enabled and nbytes > self._budget:
                self._stats["rejected"] += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            if self.enabled:
                self._evict_to_fit_locked(nbytes)
            self._entries[key] = ([value], nbytes)
            self._bytes += nbytes
            self._by_scope.setdefault(key[0], set()).add(key)
            self._session_meta[key] = {
                "deadline": time.monotonic() + float(ttl_s),
                "ttl": float(ttl_s)}
            self._stats["installs"] += 1
        obs.REGISTRY.counter("devcache.installs").inc()
        obs.attrib.account("devcache.installs", scope=key[0],
                           client=client)
        obs.REGISTRY.gauge("session.resident_bytes").set(
            self.session_resident_bytes())
        return True

    def session_get(self, sid: str, model: str, layer: str,
                    touch: bool = True) -> Optional[Any]:
        """The session's resident state for one layer, or None (not
        resident — evicted/expired/never installed; the caller revives
        from the arena spill). A hit refreshes BOTH recencies: the LRU
        position and the TTL deadline — an actively decoding session
        never expires under it. Expiry is checked lazily here as well
        as by the sweep, so a shrunk-TTL test observes it without
        waiting for a cadence."""
        key = (session_scope(sid), str(model), str(layer))
        with self._mu:
            entry = self._entries.get(key)
            meta = self._session_meta.get(key)
            if entry is None or meta is None:
                return None
            if time.monotonic() >= meta["deadline"]:
                meta["expired"] = True
                self._drop_entry_locked(key)
                return None
            if touch:
                self._entries.move_to_end(key)
                meta["deadline"] = time.monotonic() + meta["ttl"]
            return entry[0][0]

    def session_update(self, sid: str, model: str, layer: str,
                       value: Any) -> bool:
        """Swap one resident entry's value IN PLACE (the decode step's
        state advance): same key, new blocks, bytes re-accounted, LRU
        and TTL refreshed. Returns False when the entry is not
        resident — the caller re-installs via :meth:`session_put`
        (the revive-from-arena path) instead of mutating a ghost."""
        key = (session_scope(sid), str(model), str(layer))
        nbytes = _value_nbytes(value)
        with self._mu:
            entry = self._entries.get(key)
            meta = self._session_meta.get(key)
            if entry is None or meta is None:
                return False
            self._bytes += nbytes - entry[1]
            self._entries[key] = ([value], nbytes)
            self._entries.move_to_end(key)
            meta["deadline"] = time.monotonic() + meta["ttl"]
            if self.enabled:
                self._evict_to_fit_locked(0)
        obs.REGISTRY.gauge("session.resident_bytes").set(
            self.session_resident_bytes())
        return True

    def session_drop(self, sid: str) -> int:
        """Drop EVERY entry of one session with NO spill (the
        SESSION_CLOSE path — closed state must not linger in the
        arena). Returns entries dropped."""
        scope = session_scope(sid)
        with self._mu:
            keys = list(self._by_scope.get(scope, ()))
            for key in keys:
                self._session_meta.pop(key, None)  # popped FIRST: no
                # spill, no eviction tick — this is a close, not
                # memory pressure
                self._drop_entry_locked(key)
        obs.REGISTRY.gauge("session.resident_bytes").set(
            self.session_resident_bytes())
        return len(keys)

    def session_sweep(self, now: Optional[float] = None) -> int:
        """Drop (spilling) every session entry past its TTL deadline —
        the cadence-driven half of expiry (the lazy half lives in
        :meth:`session_get`). Returns entries expired."""
        now = time.monotonic() if now is None else now
        with self._mu:
            expired = [k for k, m in self._session_meta.items()
                       if now >= m["deadline"]]
            for key in expired:
                self._session_meta[key]["expired"] = True
                self._drop_entry_locked(key)
        if expired:
            obs.REGISTRY.gauge("session.resident_bytes").set(
                self.session_resident_bytes())
        return len(expired)

    def session_resident_bytes(self) -> int:
        """Live bytes across every resident session entry — the
        ``session.resident_bytes`` gauge's source of truth."""
        with self._mu:
            return sum(self._entries[k][1] for k in self._session_meta
                       if k in self._entries)

    def session_entries(self) -> int:
        with self._mu:
            return len(self._session_meta)

    # --- introspection ------------------------------------------------
    def stats(self) -> Dict[str, int]:
        """Counter snapshot (the ``compile_stats()`` analogue for the
        transfer path) — also shipped in the serve COLLECT_STATS
        reply."""
        with self._mu:
            out = {k: v for k, v in self._stats.items()
                   if self._session_on or not k.startswith("session_")}
            out["bytes"] = self._bytes
            out["entries"] = len(self._entries)
            out["budget_bytes"] = self._budget
            if self._session_on:
                out["session_entries"] = len(self._session_meta)
                out["session_bytes"] = sum(
                    self._entries[k][1] for k in self._session_meta
                    if k in self._entries)
            if self.partial:
                # who drives the hot-prefix pin budget: the static
                # knob or the feedback loop (device_cache_pin_auto)
                out["pin_budget_bytes"] = self._pin_budget
                out["pin_auto"] = self._pin_auto
            return out
