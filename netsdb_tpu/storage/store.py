"""Host-side set store — the Pangea storage engine, TPU-shaped.

The reference's worker-frontend ``PangeaStorageServer`` owns
databases→sets→64 MB shared-memory pages with a pin/unpin ``PageCache``
and flush threads spilling to ``PartitionedFile``s on disk (reference
``src/serverFunctionalities/headers/PangeaStorageServer.h:31-52``,
``src/storage/headers/PDBPage.h:17-33``, ``PageCache.h:106-118``,
``PartitionedFile.h``). Its job: keep hot sets in RAM, stream pages to
the execution pipelines, survive restarts.

On TPU the equivalent capability is: keep sets on host (numpy) or device
(jax.Array) with an LRU spill-to-disk cache, stream blocks into HBM on
demand, and persist sets as files. Sets hold either tensors
(:class:`BlockedTensor`) or arbitrary host objects (relational rows for
the TPCH-style workloads). Cache accounting mirrors ``CacheStats``
(``src/storage/headers/CacheStats.h:8-60``); eviction policy per set
mirrors ``LocalitySet`` {LRU, MRU, Random}
(``src/storage/headers/LocalitySet.h:16-24``).
"""

from __future__ import annotations

import dataclasses
import functools
import io
import os
import pickle
import random
import threading
import time
from collections import OrderedDict
from typing import (Any, Dict, Iterator, List, NamedTuple, Optional,
                    Tuple)

import jax
import numpy as np

from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
from netsdb_tpu.core.blocked import BlockedTensor, BlockMeta
from netsdb_tpu.utils.locks import TrackedLock, TrackedRLock


class SetIdentifier(NamedTuple):
    """(database, set) pair — reference ``SetIdentifier`` builtin object."""

    db: str
    set: str

    def __str__(self) -> str:
        return f"{self.db}:{self.set}"


@dataclasses.dataclass
class CacheStats:
    """Hit/miss/eviction counters (ref ``CacheStats.h:8-60``)."""

    hits: int = 0
    misses: int = 0
    evictions: int = 0
    spills: int = 0
    loads: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class _StoredSet:
    """One set's in-memory state."""

    ident: SetIdentifier
    items: Optional[List[Any]]  # None => spilled to disk
    # serializes PAGED appends per set OUTSIDE the global store lock
    # (an append must wait for in-flight streams to drain — rw.write —
    # and that wait must not freeze every unrelated store operation)
    append_mu: Any = dataclasses.field(
        default_factory=lambda: TrackedLock("_StoredSet.append_mu"))
    persistence: str = "transient"  # ref PersistenceType (DataTypes.h:53)
    eviction: str = "lru"  # ref LocalitySet replacement policy
    last_access: float = 0.0
    nbytes: int = 0
    # dedup: set whose physical storage this set aliases
    # (ref SharedTensorBlockSet, src/deduplication/headers/SharedTensorBlockSet.h:25)
    alias_of: Optional[SetIdentifier] = None
    shared_mapping: Optional[Dict] = None
    # declarative sharding applied by the data path (ref: the
    # PartitionPolicy chosen at createSet — distribution is a property
    # of the set, netsdb_tpu.parallel.placement)
    placement: Optional[Any] = None
    # "memory" (resident items) or "paged" (relation lives as row-chunk
    # pages in the shared PagedTensorStore; queries stream it — the
    # reference's PageScanner-fed sets, ``PageScanner.h:25-34``)
    storage: str = "memory"
    # monotonic write version, drawn from the store-wide counter by
    # EVERY mutating path (ingest, append, clear, resync restore,
    # spill reload, …) — the freshness token the device block cache
    # keys on (storage/devcache.py): a bumped version means no stale
    # cached block can ever match again. Store-wide numbering means a
    # removed-and-recreated set can never reuse an old version.
    version: int = 0
    # bounded per-set dirty-range log (partial-run device caching):
    # every _touch appends (start, end) — end=None for whole-scope
    # writes (replace/clear/restore), the appended tail for appends —
    # and the cache drops only intersecting block entries. Beyond
    # config.device_cache_dirty_log un-collapsed entries the log folds
    # to one whole-scope range (bounded memory, conservative cache).
    dirty_log: list = dataclasses.field(default_factory=list)


def _item_nbytes(item: Any) -> int:
    if isinstance(item, BlockedTensor):
        return int(np.prod(item.meta.padded_shape)) * item.data.dtype.itemsize
    if isinstance(item, (np.ndarray, jax.Array)):
        return int(item.nbytes)
    resident = getattr(item, "nbytes_resident", None)  # PooledTensor:
    if resident is not None:  # counts only its slot grid; the shared
        return int(resident)  # pool is accounted once, by its owner
    return 256  # rough per-object estimate for host records


@dataclasses.dataclass
class _PagedMatrix:
    """Handle for a matrix living as arena pages (a paged TENSOR set):
    identity only — shape/dtype's authoritative copies live in the
    page store's meta; the data streams through
    ``SetStore.paged_matmul`` or a :class:`PagedTensor` scan handle,
    never materializing densely (ref: pipelines over pinned weight
    pages). ``rw`` guards streams vs drop/replace."""

    ident: str
    rw: Any = None

    def __post_init__(self):
        if self.rw is None:
            from netsdb_tpu.utils.locks import RWLock

            self.rw = RWLock(name="_PagedMatrix.rw")


def _locked(method):
    """Run a public store method under the store's reentrant lock."""

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return method(self, *args, **kwargs)

    return wrapper


class SetStore:
    """All sets of all databases on this host.

    Single-controller JAX means one store per process plays the role of
    every worker's Pangea instance at once; sharded device placement of a
    set's tensor is handled by ``netsdb_tpu.parallel``.
    """

    def __init__(self, config: Configuration = DEFAULT_CONFIG,
                 max_host_bytes: Optional[int] = None):
        self.config = config
        self.config.ensure_dirs()
        self._sets: "OrderedDict[SetIdentifier, _StoredSet]" = OrderedDict()
        self.stats = CacheStats()
        self.max_host_bytes = max_host_bytes or config.shared_mem_bytes
        # serve-layer handler threads mutate sets concurrently (the
        # reference guards Pangea's set maps with pthread mutexes);
        # reentrant because e.g. add_data -> _maybe_evict -> flush
        self._lock = TrackedRLock("SetStore._lock")
        # the runtime lock-order witness (utils/locks.py): config-
        # gated so a production daemon can run lockdep-style checks
        if getattr(config, "lock_witness", False):
            from netsdb_tpu.utils.locks import enable_witness

            enable_witness()
        # sets whose items include a shared-pool tensor (dedup/pool.py)
        # — keeps pool-bytes accounting O(pooled sets)
        self._pooled: set = set()
        # ONE shared page arena for every paged set (the reference has
        # one shared-memory pool per worker); lazy — most processes
        # never create a paged set
        self._page_store = None
        # arena names are GENERATION-unique (ident#gN): a deferred
        # unlocked drop after remove_set must never free the pages of a
        # same-named set re-created in the window
        import itertools

        self._gen = itertools.count()
        # store-wide set-version counter + the cross-query device block
        # cache (the buffer-pool role, storage/devcache.py) — lazy like
        # the page store; most short-lived stores never touch it
        self._version_ctr = itertools.count(1)
        self._device_cache = None

    def page_store(self):
        """The shared :class:`PagedTensorStore` backing every
        ``storage="paged"`` set, created on first use with the
        configured pool cap (``config.page_pool_bytes``)."""
        with self._lock:
            if self._page_store is None:
                from netsdb_tpu.storage.paged import PagedTensorStore

                self._page_store = PagedTensorStore(
                    self.config,
                    pool_bytes=self.config.page_pool_bytes)
            return self._page_store

    def device_cache(self):
        """The cross-query device block cache (``storage/devcache.py``)
        backing warm repeat queries — one per store, budgeted by
        ``config.device_cache_bytes``."""
        with self._lock:
            if self._device_cache is None:
                from netsdb_tpu.storage.devcache import DeviceBlockCache

                self._device_cache = DeviceBlockCache(
                    getattr(self.config, "device_cache_bytes", 0) or 0,
                    partial=bool(getattr(self.config,
                                         "device_cache_partial", False)),
                    pin_bytes=getattr(self.config,
                                      "device_cache_pin_bytes", 0) or 0)
            return self._device_cache

    def _touch(self, s: _StoredSet,
               rows: Optional[Tuple[int, int]] = None,
               columns: Optional[Tuple[str, ...]] = None) -> None:
        """Advance a set's write version, log the dirty row range and
        drop the intersecting cached device blocks NOW. Called by EVERY
        path that can change the set's content — direct ingest,
        appends, BULK COMMIT (which lands through these same mutators),
        mirrored frames, resync restore, checkpoint/spill reload.

        ``rows=(start, end)`` names the dirty row range (an append
        passes its tail); None means the whole scope changed
        (replace/clear/restore — today's behavior). In whole-run cache
        mode the range is advisory only and invalidation stays
        whole-scope, byte-for-byte as before. In partial mode a
        ranged touch is only LOGGED here: the per-range cache
        invalidation already happened inside ``PagedColumns.append``
        (the one mutator every ranged caller routes through — it owns
        the range invalidation so store-bypassing direct appends stay
        coherent, and doing it again here would double-bump the scope
        epoch and refuse installs of streams planned between the two
        bumps). A caller adding a NEW ``rows=...`` site that does not
        route through ``pc.append`` must invalidate the range itself.

        ``columns=(name, ...)`` additionally names the touched COLUMNS
        (an update-in-place write): the dirty log entry is keyed by
        column — ``(start, end, cols)`` — and the per-range cache
        invalidation (owned by ``PagedColumns.update_column``, same
        contract as ``pc.append`` above) drops only block entries
        whose stream PROJECTED one of those columns, so a
        single-column update keeps every other column's cached blocks
        resident.

        When the bounded log overflows it folds to one whole-scope
        entry AND the cache degrades to a whole-scope invalidation —
        a pathological writer gets today's invalidate-everything
        behavior, never unbounded memory or silent fidelity loss."""
        s.version = next(self._version_ctr)
        bound = max(int(getattr(self.config, "device_cache_dirty_log",
                                64) or 64), 1)
        folded = len(s.dirty_log) >= bound
        if folded:
            s.dirty_log[:] = [(0, None)]  # fold to whole-scope
        elif rows is None:
            s.dirty_log.append((0, None))
        elif columns is not None:
            s.dirty_log.append((int(rows[0]), int(rows[1]),
                                tuple(sorted(columns))))
        else:
            s.dirty_log.append((int(rows[0]), int(rows[1])))
        if self._device_cache is not None:
            if rows is not None and self._device_cache.partial \
                    and not folded:
                pass  # range already invalidated by pc.append (above)
            else:
                self._device_cache.invalidate(str(s.ident))

    def version_of(self, ident: SetIdentifier) -> int:
        """The set's current write version (0 = unknown set) — the
        freshness token device-cache keys carry."""
        s = self._sets.get(ident)
        return s.version if s is not None else 0

    def _bind_cache(self, pc, ident: SetIdentifier) -> None:
        """Attach the device cache to a store-owned paged relation
        handle so its streams consult/install cached runs. Direct
        ``PagedColumns.ingest`` callers (grace-hash spill partitions,
        benches) never get a binding — temporaries stay uncached."""
        pc.devcache = self.device_cache()
        pc.cache_scope = str(ident)
        pc.cache_version_fn = functools.partial(self.version_of, ident)

    # --- set lifecycle ------------------------------------------------
    @_locked
    def create_set(
        self,
        ident: SetIdentifier,
        persistence: str = "transient",
        eviction: str = "lru",
        placement: Optional[Any] = None,
        storage: str = "memory",
    ) -> None:
        if storage not in ("memory", "paged"):
            raise ValueError(f"storage must be 'memory' or 'paged', "
                             f"got {storage!r}")
        if ident not in self._sets:
            self._sets[ident] = _StoredSet(
                ident=ident, items=[], persistence=persistence, eviction=eviction,
                last_access=time.time(), placement=placement, storage=storage,
            )
            self._touch(self._sets[ident])
        elif placement is not None:
            s = self._sets[ident]
            s.placement = placement
            if s.items:  # re-place already-stored data under the new policy
                s.items = [placement.apply(i) for i in s.items]
            self._touch(s)  # resharded items: cached runs are stale

    def placement_of(self, ident: SetIdentifier) -> Optional[Any]:
        s = self._sets.get(ident)
        return s.placement if s is not None else None

    @_locked
    def set_placement(self, ident: SetIdentifier, placement,
                      items: Optional[List[Any]] = None) -> None:
        """Swap a set's DECLARED placement without re-staging its data
        — the commit step of ``parallel/reshard.reshard_set``, which
        has already moved the device-resident blocks (or resident
        ``items``, passed here) through collective steps. Content is
        unchanged, so no write version moves and no dirty range is
        logged: cached blocks installed under the NEW layout's key
        stay matchable, which is the whole point. NOT the path for
        re-placing from host — ``create_set(placement=...)`` keeps
        that behavior (re-place + whole-scope invalidation)."""
        s = self._require(ident)
        s.placement = placement
        if items is not None:
            s.items = items
            s.nbytes = sum(_item_nbytes(i) for i in items)
        s.last_access = time.time()

    def storage_of(self, ident: SetIdentifier) -> str:
        s = self._sets.get(ident)
        return s.storage if s is not None else "memory"

    def exists(self, ident: SetIdentifier) -> bool:
        return ident in self._sets or os.path.exists(self._spill_path(ident))

    def remove_set(self, ident: SetIdentifier) -> None:
        with self._lock:
            s = self._sets.pop(ident, None)
            detached = list(s.items or []) if s is not None else []
            if s is not None:
                s.items = []
            if self._device_cache is not None:
                self._device_cache.invalidate(str(ident))
            path = self._spill_path(ident)
            if os.path.exists(path):
                os.remove(path)
        # page reclaim happens OUTSIDE the store lock: dropping a paged
        # relation waits for in-flight streams (its write lock) and must
        # not freeze every unrelated store operation while it waits
        self._drop_detached(detached)

    def clear_set(self, ident: SetIdentifier) -> None:
        with self._lock:
            s = self._sets.get(ident)
            detached = list(s.items or []) if s is not None else []
            if s is not None:
                s.items = []
                s.nbytes = 0
                self._touch(s)
        self._drop_detached(detached)

    def _drop_paged_items(self, s: Optional[_StoredSet]) -> None:
        """Return a dropped paged relation's (or paged matrix's) pages
        to the shared capped arena — without this, remove/clear of
        paged sets would leak dead pages against ``page_pool_bytes``
        until process restart. Called with the store lock held (ingest
        replace); remove/clear detach first and drop unlocked."""
        if s is None or not s.items:
            return
        self._drop_detached(s.items)

    def _drop_detached(self, items: List[Any]) -> None:
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.storage.paged import PagedObjects

        for item in items:
            if isinstance(item, (PagedColumns, PagedObjects)):
                item.drop()
            elif isinstance(item, _PagedMatrix) and \
                    self._page_store is not None:
                with item.rw.write():  # drain in-flight weight streams
                    self._page_store.drop(f"{item.ident}.mat")

    @_locked
    def list_sets(self) -> List[SetIdentifier]:
        return list(self._sets.keys())

    # --- data path (ref: StorageAddData / UserSet::addObject) ---------
    def add_data(self, ident: SetIdentifier, items: List[Any]) -> None:
        """Append/ingest ``items``. Paged OBJECT-set appends follow the
        same lock discipline as paged-table appends (advisor round 5):
        the store lock only LOCATES and pins the existing
        :class:`PagedObjects`; ``po.append`` runs OUTSIDE it under the
        set's ``append_mu`` — the append may wait on the relation's own
        locks (a concurrent drop), and that wait must never freeze
        every unrelated store operation. A concurrent remove/replace
        drops the pinned handle, making ``po.append`` fail loudly
        instead of resurrecting freed pages."""
        dead = []
        po = None
        with self._lock:
            s = self._require(ident)
            if s.alias_of is not None:
                raise ValueError(f"set {ident} aliases {s.alias_of}; "
                                 f"it is read-only")
            if s.storage == "paged":
                po = self._pin_paged_objects(s, items)
                if po is None:
                    # lint: disable=lock-blocking-call -- fresh ingest: the relation doesn't exist yet, so no stream can hold its rw lock and the append wait cannot occur
                    dead = self._ingest_paged(s, items)
                    self._touch(s)
            else:
                if s.items is None:  # evicted: reload before appending
                    # lint: disable=lock-blocking-call -- reload of an evicted set: its relation was spilled with no live streams, so the rebuild's appends cannot wait
                    self._load_from_spill(s)
                if s.placement is not None:
                    items = [s.placement.apply(i) for i in items]
                s.items.extend(items)
                s.nbytes += sum(_item_nbytes(i) for i in items)
                s.last_access = time.time()
                self._maybe_evict(exclude=ident)
                self._touch(s)
        if po is not None:
            with s.append_mu:  # per-set order among concurrent appends
                # lint: disable=lock-blocking-call -- append_mu exists to order THIS set's appends behind the relation locks; the global store lock stays released
                po.append(items)
            with self._lock:
                if self._sets.get(ident) is s:
                    s.last_access = time.time()
                    self._touch(s)
        self._drop_detached(dead)  # replaced pages reclaim UNLOCKED

    @staticmethod
    def _pin_paged_objects(s: _StoredSet, items: List[Any]):
        """The existing :class:`PagedObjects` of ``s`` when ``items``
        are host-object records appending to it, else None (fresh
        ingest / relation-replace — handled under the store lock,
        where no streams can exist on a relation that doesn't).
        Caller holds the store lock."""
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.relational.table import ColumnTable
        from netsdb_tpu.storage.paged import PagedObjects

        if not items or isinstance(
                items[0], (PagedColumns, np.ndarray, BlockedTensor,
                           ColumnTable)):
            return None
        return next((i for i in (s.items or [])
                     if isinstance(i, PagedObjects)), None)

    def _ingest_paged(self, s: _StoredSet, items: List[Any],
                      append: bool = False) -> List[Any]:
        """Route a relation into the page arena instead of RAM — the set
        property the reference expresses by EVERY set living in pages
        (``PangeaStorageServer.h:31-52``); here only sets that opt into
        streaming pay the page granularity. One relation per paged set
        (matching ``send_table`` semantics); re-ingest replaces, or
        APPENDS new pages when asked (the reference's addData flow) —
        dictionary-encoded batch columns remap into the stored
        dictionaries first.

        Returns the REPLACED paged items: arena names are generation-
        unique, so the caller reclaims the old pages OUTSIDE the store
        lock (``_drop_detached`` waits for in-flight streams; that wait
        must not freeze unrelated store operations)."""
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.relational.table import ColumnTable

        if not items:
            return []
        item = items[0]
        if isinstance(item, (PagedColumns, np.ndarray, BlockedTensor,
                             ColumnTable)) and len(items) != 1:
            raise ValueError(f"paged set {s.ident} holds exactly one "
                             f"relation; got {len(items)} items")
        if isinstance(item, PagedColumns):
            # replacing with a new handle: the OLD relation's arena
            # pages go back to the caller for reclaim (cross-type-leak
            # rule) — unless the "new" handle IS the stored one
            dead = []
            if not (s.items and len(s.items) == 1 and s.items[0] is item):
                dead = list(s.items or [])
            s.items = [item]
            self._bind_cache(item, s.ident)
            return dead
        if isinstance(item, (np.ndarray, BlockedTensor)):
            if append:
                raise ValueError(f"append is not supported for paged "
                                 f"matrices ({s.ident}); re-send the "
                                 f"full matrix")
            # paged TENSOR set: a matrix larger than HBM pages into the
            # arena; consumers stream it (``paged_matmul`` — the r1
            # matmul_streamed capability, now a property of the set).
            # Replace semantics: the old contents are returned for
            # unlocked reclaim (cross-type replaces must not leak)
            dead = list(s.items or [])
            dense = (np.asarray(item.to_dense()) if
                     isinstance(item, BlockedTensor) else
                     np.ascontiguousarray(item))
            arena_name = f"{s.ident}#g{next(self._gen)}"
            self.page_store().put(f"{arena_name}.mat", dense)
            s.items = [_PagedMatrix(arena_name)]
            s.nbytes = 0
            s.last_access = time.time()
            return dead
        if not isinstance(item, ColumnTable):
            # HOST-OBJECT records: pickled-batch pages (the reference's
            # pages hold arbitrary pdb::Objects, PDBPage.h:17-33).
            # Object add_data APPENDS, matching the memory object
            # path's extend semantics (relations replace; see above) —
            # but the append to an EXISTING PagedObjects never reaches
            # here: add_data pins it under the store lock and runs
            # po.append outside it (the round-5 lock-inversion fix),
            # so this branch only ever does the fresh first ingest
            from netsdb_tpu.storage.paged import PagedObjects

            dead = list(s.items or [])
            po = PagedObjects.ingest(
                self.page_store(), f"{s.ident}#g{next(self._gen)}",
                items)
            s.items = [po]
            s.nbytes = 0
            s.last_access = time.time()
            return dead
        existing = [i for i in (s.items or [])
                    if isinstance(i, PagedColumns)]
        if append and existing:
            self._append_paged_existing(s, existing[0], item)
            return []
        # fresh/replace table ingest: whatever the set held (table pages
        # or a matrix) is returned for unlocked reclaim — generation-
        # unique arena names make new-before-drop ordering safe
        dead = list(s.items or [])
        # page row count sized to the configured page bytes (floor 64 so
        # tiny test pages still hold whole rows); for placed sets,
        # rounded to the shard granularity so streamed chunks mesh-shard
        # with no second padding round
        width = max(len(item.cols), 1)
        row_block = max(self.config.page_size_bytes // (4 * width), 64)
        if s.placement is not None:
            div = s.placement.axis_size()
            row_block = -(-row_block // div) * div
        cols = {n: np.asarray(item[n]) for n in item.cols if n != "_rowid"}
        if item.valid is not None:
            keep = np.asarray(item.mask())
            cols = {n: c[keep] for n, c in cols.items()}
        pc = PagedColumns.ingest(self.page_store(),
                                 f"{s.ident}#g{next(self._gen)}", cols,
                                 row_block=row_block, dicts=dict(item.dicts))
        self._bind_cache(pc, s.ident)
        s.items = [pc]
        s.nbytes = 0  # pages are accounted (and capped) by the arena
        s.last_access = time.time()
        return dead

    def _append_paged_existing(self, s: _StoredSet, pc, item) -> None:
        """Append a batch to a LIVE paged relation (never a fresh
        ingest — the pc is pinned by the caller, so a concurrent
        remove cannot silently turn this into an orphaned re-create;
        ``pc.append`` raises if the relation was dropped). Safe to run
        outside the store lock under the set's append lock."""
        from netsdb_tpu.relational.autojoin import merge_dicts

        cols = {n: np.asarray(item[n]) for n in item.cols
                if n != "_rowid"}
        if item.valid is not None:
            keep = np.asarray(item.mask())
            cols = {n: c[keep] for n, c in cols.items()}
        # validate EVERYTHING before mutating any stored state — a
        # rejected batch must leave the set (dictionaries included)
        # exactly as it was
        expected = set(pc.int_names) | set(pc.float_names)
        if set(cols) != expected:
            raise ValueError(
                f"append to {s.ident}: schema mismatch — stored "
                f"{sorted(expected)}, batch {sorted(cols)}")
        missing = [n for n in pc.dicts
                   if n in cols and n not in item.dicts]
        if missing:
            raise ValueError(
                f"append to {s.ident}: columns {missing} are "
                f"dict-encoded in the stored set but arrive as raw "
                f"ints — codes would be meaningless")
        staged_dicts = {}
        for name, d_new in item.dicts.items():
            d_old = pc.dicts.get(name)
            if d_old is None:
                raise ValueError(f"append to {s.ident}: column "
                                 f"{name!r} is dict-encoded in the "
                                 f"batch but not in the stored set")
            merged, remap = merge_dicts(d_old, d_new)
            staged_dicts[name] = merged
            cols[name] = remap[cols[name]]
        pc.append(cols)  # atomic (rolls back its pages on failure)
        pc.dicts.update(staged_dicts)  # commit only after success
        s.last_access = time.time()

    @_locked
    def update_set(self, ident: SetIdentifier, fn) -> None:
        """Atomic read-modify-write of a set's items: ``fn(items) ->
        new_items`` runs UNDER the store lock, so concurrent updaters
        (e.g. two daemon handlers appending to one objects set) cannot
        interleave their read-concat-replace sequences and lose
        batches. Placement applies to the result like any ingest."""
        s = self._require(ident)
        if s.alias_of is not None:
            raise ValueError(f"set {ident} aliases {s.alias_of}; it is read-only")
        if s.items is None:
            self._load_from_spill(s)
        items = fn(list(s.items))
        if s.placement is not None:
            items = [s.placement.apply(i) for i in items]
        s.items = items
        s.nbytes = sum(_item_nbytes(i) for i in items)
        s.last_access = time.time()
        self._touch(s)
        self._maybe_evict(exclude=ident)

    def paged_matmul(self, ident: SetIdentifier, rhs) -> np.ndarray:
        """``stored_matrix @ rhs`` with the left side STREAMED page by
        page through the device — the larger-than-HBM weight pattern
        (only one page + rhs resident at a time; r1's matmul_streamed,
        reachable as a set property since the matrix lives in a
        ``storage="paged"`` set). The stream runs OUTSIDE the store
        lock under the item's read lock (the arena pin): a concurrent
        remove/re-ingest waits for the stream instead of the stream
        freezing every other store operation."""
        with self._lock:
            s = self._require(ident)
            pm = next((i for i in (s.items or [])
                       if isinstance(i, _PagedMatrix)), None)
            if pm is None:
                raise ValueError(f"set {ident} holds no paged matrix")
            s.last_access = time.time()
            ps = self.page_store()
        with pm.rw.read():
            # the devcache binding lets the SUMMA route (config.
            # distributed_matmul) install its per-participant panels
            # as block entries keyed by the mesh label — a warm
            # distributed matmul re-run stages zero bytes
            return ps.matmul_streamed(f"{pm.ident}.mat", np.asarray(rhs),
                                      devcache=self.device_cache(),
                                      cache_scope=str(ident))

    @_locked
    def paged_tensor(self, ident: SetIdentifier):
        """Streaming read handle for a paged TENSOR set — the ScanSet
        value the executor feeds to :class:`~netsdb_tpu.plan.fold.
        TensorFold`-bearing nodes (in-DB inference over storage-managed
        weights, ref ``SimpleFF.cc:94-290``). Never materializes."""
        from netsdb_tpu.storage.paged import PagedTensor

        s = self._require(ident)
        pm = next((i for i in (s.items or [])
                   if isinstance(i, _PagedMatrix)), None)
        if pm is None:
            raise ValueError(f"set {ident} holds no paged matrix")
        s.last_access = time.time()
        pt = PagedTensor(self.page_store(), f"{pm.ident}.mat",
                         rw=pm.rw, placement=s.placement)
        # version-scoped device-cache binding: the tensor stream's
        # staged uploads install under (ident, version) and a warm
        # consumer replays them without touching the arena; the
        # version_fn lets the install re-check currentness (a racing
        # write must not strand a dead entry on the budget)
        pt.devcache = self.device_cache()
        pt.cache_scope = (str(ident), s.version)
        pt.cache_version_fn = functools.partial(self.version_of, ident)
        return pt

    def restore_paged_matrix(self, ident: SetIdentifier, blocks,
                             row_block: int) -> None:
        """Rebuild a paged TENSOR set from its arena pages — the
        RESYNC_FOLLOWER replay path (the PR 2 leftover: a paged MATRIX
        used to resync as an empty set). ``blocks`` are the leader's
        row-blocks in order; each is written as its own arena page
        (ragged blocks fine — readers derive per-page row counts from
        actual page sizes), so the matrix NEVER materializes densely on
        the follower."""
        dead = []
        with self._lock:
            s = self._require(ident)
            dead = list(s.items or [])
            if not blocks:
                s.items = []
                s.nbytes = 0
                self._touch(s)
            else:
                arena_name = f"{s.ident}#g{next(self._gen)}"
                ps = self.page_store()
                first = True
                for b in blocks:
                    ps.put(f"{arena_name}.mat", np.ascontiguousarray(b),
                           row_block=max(int(row_block), 1),
                           append=not first)
                    first = False
                s.items = [_PagedMatrix(arena_name)]
                s.nbytes = 0
                s.last_access = time.time()
                self._touch(s)
        self._drop_detached(dead)

    def append_table(self, ident: SetIdentifier, table) -> None:
        """Append a batch of rows to a table set (the reference's
        addData flow, ``StorageAddData``): paged sets write additional
        arena pages (no rewrite); memory sets concat on device with
        dictionary remap.

        Paged appends serialize on the SET's append lock outside the
        global store lock: the page write must wait for in-flight
        streams of the same relation (rw.write), and that wait must not
        freeze unrelated store operations. The store lock is re-taken
        only to verify the set wasn't removed/replaced in between."""
        from netsdb_tpu.relational.autojoin import concat_tables
        from netsdb_tpu.relational.table import ColumnTable

        with self._lock:
            s = self._require(ident)
            if s.alias_of is not None:
                raise ValueError(f"set {ident} aliases {s.alias_of}; "
                                 f"it is read-only")
            paged = s.storage == "paged"
        if paged:
            from netsdb_tpu.relational.outofcore import PagedColumns

            with s.append_mu:  # concurrent appends: dict remaps must
                # not interleave (per-set, not global)
                with self._lock:
                    if self._sets.get(ident) is not s:
                        raise KeyError(f"set {ident} was removed "
                                       f"during append")
                    pc = next((i for i in (s.items or [])
                               if isinstance(i, PagedColumns)), None)
                    if pc is None:
                        # FIRST batch = a fresh ingest, done under the
                        # store lock: no streams can exist on a
                        # relation that doesn't, so no rw wait — and a
                        # concurrent replace can no longer interleave
                        # and orphan one relation's pages
                        # lint: disable=lock-blocking-call -- first batch of a fresh relation (comment above): no streams exist, the append wait cannot occur
                        dead = self._ingest_paged(s, [table],
                                                  append=True)
                rows = None
                if pc is not None:
                    # live relation: append outside the store lock
                    # (waits for in-flight streams via pc.rw; a
                    # concurrent remove/replace drops pc, making
                    # pc.append fail loudly instead of resurrecting)
                    before = pc.num_rows
                    self._append_paged_existing(s, pc, table)
                    # the appended tail is the ONLY dirty range: the
                    # partial device cache keeps every pre-append
                    # block resident (whole-run mode ignores it)
                    rows = (before, pc.num_rows)
                    dead = []
                with self._lock:
                    self._touch(s, rows=rows)
            self._drop_detached(dead)
            return
        self._append_table_memory(ident, table)

    def update_columns(self, ident: SetIdentifier,
                       cols: Dict[str, Any]) -> None:
        """Overwrite whole COLUMNS of a paged table set in place —
        the update-in-place write path (netsDB's UpdateSet over one
        attribute). Pages are rewritten where they live (same shape,
        no layout change); the device cache drops ONLY block entries
        whose stream projected a touched column (per-column dirty
        ranges — an untouched column's cached blocks keep serving
        with zero re-stages).

        Lock discipline mirrors ``append_table``: the store lock only
        locates and pins the relation; the page rewrites run outside
        it under the set's ``append_mu`` (they wait on the relation's
        own rw lock for in-flight streams)."""
        from netsdb_tpu.relational.outofcore import PagedColumns

        with self._lock:
            s = self._require(ident)
            if s.alias_of is not None:
                raise ValueError(f"set {ident} aliases {s.alias_of}; "
                                 f"it is read-only")
            if s.storage != "paged":
                raise ValueError(f"update_columns needs a paged table "
                                 f"set; {ident} is {s.storage!r}")
            pc = next((i for i in (s.items or [])
                       if isinstance(i, PagedColumns)), None)
            if pc is None:
                raise ValueError(f"set {ident} holds no paged relation")
        with s.append_mu:
            with self._lock:
                if self._sets.get(ident) is not s:
                    raise KeyError(f"set {ident} was removed during "
                                   f"update")
            for name, values in cols.items():
                # pc.update_column owns the per-range, per-column
                # cache invalidation (the pc.append contract)
                pc.update_column(name, values)
            with self._lock:
                self._touch(s, rows=(0, pc.num_rows),
                            columns=tuple(sorted(cols)))

    @_locked
    def _append_table_memory(self, ident: SetIdentifier, table) -> None:
        from netsdb_tpu.relational.autojoin import concat_tables
        from netsdb_tpu.relational.table import ColumnTable

        s = self._require(ident)
        if s.alias_of is not None:
            raise ValueError(f"set {ident} aliases {s.alias_of}; it is read-only")
        if s.items is None:
            self._load_from_spill(s)
        tables = [i for i in s.items if isinstance(i, ColumnTable)]
        if len(s.items) != len(tables) or len(tables) > 1:
            raise ValueError(
                f"append_table needs a single-relation table set; "
                f"{ident} holds {len(s.items)} items "
                f"({len(tables)} tables) — appending would drop the rest")
        new = concat_tables(tables[0], table) if tables else table
        if s.placement is not None:
            new = s.placement.apply(new)
        s.items = [new]
        s.nbytes = _item_nbytes(new)
        s.last_access = time.time()
        self._touch(s)
        self._maybe_evict(exclude=ident)

    def put_tensor(self, ident: SetIdentifier, tensor: BlockedTensor) -> None:
        """Replace a set's contents with one tensor — the dominant pattern
        for model-weight sets (each netsDB weight set is exactly one
        blocked matrix)."""
        dead = []
        with self._lock:
            s = self._require(ident)
            if s.alias_of is not None:
                raise ValueError(f"set {ident} aliases {s.alias_of}; "
                                 f"it is read-only")
            if s.storage == "paged":
                # lint: disable=lock-blocking-call -- replace builds a FRESH relation (the old one is dropped after the swap); no stream can hold the new relation's rw lock yet
                dead = self._ingest_paged(s, [tensor])
            else:
                if s.placement is not None:
                    tensor = s.placement.apply(tensor)
                s.items = [tensor]
                s.nbytes = _item_nbytes(tensor)
                s.last_access = time.time()
                self._maybe_evict(exclude=ident)
            self._touch(s)
        self._drop_detached(dead)  # replaced pages reclaim UNLOCKED

    def get_tensor(self, ident: SetIdentifier) -> BlockedTensor:
        items = self.get_items(ident)
        if any(isinstance(i, _PagedMatrix) for i in items):
            raise ValueError(
                f"set {ident} holds a PAGED matrix — it streams, it is "
                f"never device-resident; consume it with paged_matmul")
        tensors = [i for i in items if isinstance(i, BlockedTensor)]
        if len(tensors) != 1:
            raise ValueError(
                f"set {ident} holds {len(tensors)} tensors; expected exactly 1"
            )
        return tensors[0]

    @_locked
    def get_items(self, ident: SetIdentifier) -> List[Any]:
        s = self._require(ident)
        if s.alias_of is not None:
            # Shared-storage set: physical pages live in another set
            # (ref PartitionTensorBlockSharedPageIterator).
            return self.get_items(s.alias_of)
        if s.items is None:
            self._load_from_spill(s)
        else:
            self.stats.hits += 1
        s.last_access = time.time()
        from netsdb_tpu.dedup.pool import PooledTensor

        if any(isinstance(i, PooledTensor) for i in s.items):
            # dedup'd model set: resident HBM holds the shared pool +
            # slot grid; consumers get an eagerly-assembled TRANSIENT
            # BlockedTensor (freed when the consuming job drops it) —
            # the shared-page read path (SharedTensorBlockSet.h:25).
            # Per-read gather cost and the transient's peak-HBM are the
            # price of keeping consumers pooling-agnostic (dedup/pool.py
            # module docstring).
            return [i.assemble() if isinstance(i, PooledTensor) else i
                    for i in s.items]
        return s.items

    def scan(self, ident: SetIdentifier) -> Iterator[Any]:
        """Stream a set's items — reference ``SetScan`` / ``SetIterator``
        (``src/queries/headers/SetIterator.h``)."""
        yield from self.get_items(ident)

    @_locked
    def add_shared_mapping(
        self, private: SetIdentifier, shared: SetIdentifier, mapping: Optional[Dict] = None
    ) -> None:
        """Point ``private`` at ``shared``'s physical storage — model-dedup
        client API ``addSharedPage``/``addSharedMapping`` (reference
        ``src/mainClient/headers/PDBClient.h:113-138``)."""
        s = self._require(private)
        s.alias_of = shared
        s.shared_mapping = mapping or {}
        s.items = []
        s.nbytes = 0
        self._touch(s)

    @_locked
    def set_pooled(self, ident: SetIdentifier, pooled: Any) -> None:
        """Swap a weight set's dense tensor for its pooled form (the
        shared-block dedup flow, ``dedup/pool.py``) — the original
        device buffer is released once no set references it."""
        s = self._require(ident)
        s.items = [pooled]
        s.nbytes = _item_nbytes(pooled)
        self._touch(s)
        self._pooled.add(ident)  # pool-bytes accounting registry

    # --- persistence (ref: flush threads → PartitionedFile) -----------
    def _spill_path(self, ident: SetIdentifier) -> str:
        safe = f"{ident.db}__{ident.set}".replace("/", "_")
        return os.path.join(self.config.data_dir, f"{safe}.pdbset")

    @_locked
    def flush(self, ident: SetIdentifier) -> str:
        """Write a set durably to disk (keeps it in RAM). A PAGED set
        snapshots as its materialized relation tagged ``paged`` — on
        reload it re-ingests into the arena, so paged sets survive
        restart like any other (the reference's PartitionedFile +
        soft-reboot story; the snapshot holds the full relation on
        host once, the same peak as the original ingest). The arena's
        own spill files remain capacity, not durability."""
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.storage.paged import PagedObjects

        s = self._require(ident)
        items = self.get_items(ident)
        path = self._spill_path(ident)
        payload = []
        for item in items:
            if isinstance(item, BlockedTensor):
                payload.append(
                    ("tensor", np.asarray(item.data), item.meta.shape,
                     item.meta.block_shape)
                )
            elif isinstance(item, PagedColumns):
                # HOST-side snapshot (numpy columns): the flush path
                # must never materialize the relation in device memory
                payload.append(("paged", item.to_host_table(), None, None))
            elif isinstance(item, _PagedMatrix):
                # paged matrix: host-side block concat (never device)
                blocks = [b for _, b in self.page_store().stream_blocks(
                    f"{item.ident}.mat")]
                payload.append(("paged_mat", np.concatenate(blocks),
                                None, None))
            elif isinstance(item, PagedObjects):
                # object pages snapshot as the record list (host-side)
                payload.append(("paged_objs", item.to_list(), None,
                                None))
            else:
                payload.append(("object", item, None, None))
        record = {"ident": tuple(s.ident), "persistence": s.persistence,
                  "storage": s.storage,
                  "placement": (s.placement.to_meta()
                                if s.placement is not None else None),
                  "items": payload}
        with open(path, "wb") as f:
            if self.config.enable_compression:
                # reference -DENABLE_COMPRESSION snappy-compresses its
                # shuffle/page byte streams (PipelineStage.cc:179-196);
                # level 1 = the same speed-over-ratio tradeoff. Streamed
                # (compressobj wrapper) because flush runs from
                # _maybe_evict under memory pressure — materializing
                # pickle+compressed copies of a multi-GB set there
                # would spike RAM exactly when it is scarce.
                import zlib

                f.write(b"NZ01")
                comp = zlib.compressobj(1)

                class _W:
                    def write(self, chunk):
                        f.write(comp.compress(chunk))

                pickle.dump(record, _W(), protocol=pickle.HIGHEST_PROTOCOL)
                f.write(comp.flush())
            else:
                pickle.dump(record, f, protocol=pickle.HIGHEST_PROTOCOL)
        self.stats.spills += 1
        return path

    def _load_from_spill(self, s: _StoredSet) -> None:
        path = self._spill_path(s.ident)
        if not os.path.exists(path):
            raise KeyError(f"set {s.ident} has no data in RAM or on disk")
        with open(path, "rb") as f:
            magic = f.read(4)
            if magic == b"NZ01":  # compressed spill (see flush)
                # streamed, mirroring flush: never hold compressed +
                # decompressed + deserialized copies at once
                import zlib

                decomp = zlib.decompressobj()

                class _R:
                    """Minimal file-like over the decompressed stream.
                    Buffer is a bytearray: in-place append, so a large
                    pickle frame read stays linear, not quadratic."""

                    def __init__(self):
                        self.buf = bytearray()

                    def read(self, n=-1):
                        while (n < 0 or len(self.buf) < n):
                            chunk = f.read(1 << 20)
                            if not chunk:
                                self.buf += decomp.flush()
                                break
                            self.buf += decomp.decompress(chunk)
                        if n < 0:
                            out, self.buf = bytes(self.buf), bytearray()
                        else:
                            out = bytes(self.buf[:n])
                            del self.buf[:n]
                        return out

                    def readline(self):  # pickle protocol 2+ never calls
                        raise io.UnsupportedOperation("readline")

                blob = pickle.load(_R())
            else:
                f.seek(0)
                blob = pickle.load(f)
        # restore the set-level attributes the record carries: a fresh
        # load_set builds a bare _StoredSet, and paged-ness/placement
        # must come back BEFORE ingest (placement rounds the page row
        # count to the shard granularity)
        if blob.get("storage"):
            s.storage = blob["storage"]
        if s.placement is None and blob.get("placement"):
            from netsdb_tpu.parallel.placement import Placement

            s.placement = Placement.from_meta(blob["placement"])
        paged_objs = [data for kind, data, _, _ in blob["items"]
                      if kind == "paged_objs"]
        if paged_objs:
            # object-set snapshot: records re-page into the arena
            self._drop_detached(self._ingest_paged(s, paged_objs[0]))
            self._touch(s)
            self.stats.misses += 1
            self.stats.loads += 1
            return
        paged_tables = [data for kind, data, _, _ in blob["items"]
                        if kind in ("paged", "paged_mat")]
        if paged_tables:
            # snapshot of a paged set: re-ingest the relation into the
            # arena — the set comes back PAGED, placement and all.
            # (Reload happens under the store lock; a reload never
            # replaces live paged items, so the dead list is empty —
            # still reclaimed for belt-and-braces.)
            self._drop_detached(self._ingest_paged(s, paged_tables))
            self._touch(s)
            self.stats.misses += 1
            self.stats.loads += 1
            return
        if s.storage == "paged":
            # empty paged snapshot: nothing to ingest, but the set must
            # NOT silently demote to resident storage
            s.items = []
            s.nbytes = 0
            self.stats.loads += 1
            return
        items: List[Any] = []
        for kind, data, shape, block_shape in blob["items"]:
            if kind == "tensor":
                meta = BlockMeta(tuple(shape), tuple(block_shape))
                import jax.numpy as jnp

                items.append(BlockedTensor(jnp.asarray(data), meta))
            else:
                items.append(data)
        if s.placement is not None:
            # distribution is a property of the set: an eviction round-trip
            # must not silently demote a placed set to single-device
            items = [s.placement.apply(i) for i in items]
        s.items = items
        s.nbytes = sum(_item_nbytes(i) for i in items)
        self._touch(s)  # fresh objects: cached runs of the old
        # incarnation must never match (checkpoint-restore freshness)
        self.stats.misses += 1
        self.stats.loads += 1

    @_locked
    def load_set(self, ident: SetIdentifier) -> None:
        """Recover a persisted set after restart (ref: sets survive soft
        reboot, README.md:101-113)."""
        if ident not in self._sets:
            self._sets[ident] = _StoredSet(ident=ident, items=None,
                                           persistence="persistent")
        self.get_items(ident)

    @_locked
    def live_pool_bytes(self) -> int:
        """Bytes of every distinct shared block pool referenced by at
        least one resident set (``dedup/pool.py``) — counted ONCE per
        pool regardless of how many sets share it, and dropping out
        automatically when the last referencing set goes away. Scans
        only the sets registered by ``set_pooled`` (O(pooled sets), not
        O(all items))."""
        return self._live_pool_bytes()

    def _live_pool_bytes(self) -> int:
        seen: Dict[int, int] = {}
        dead = []
        for ident in self._pooled:
            s = self._sets.get(ident)
            if s is None:
                dead.append(ident)
                continue
            for item in (s.items or []):
                p = getattr(item, "pool", None)
                if p is not None and hasattr(p, "nbytes"):
                    seen[id(p)] = int(p.nbytes)
        for ident in dead:
            self._pooled.discard(ident)
        return sum(seen.values())

    @_locked
    def drop_pool_caches(self) -> int:
        """Release every pooled set's cached assembly (dedup/pool.py) —
        the cheapest memory to give back under pressure (re-creatable
        by one gather). Returns bytes released."""
        from netsdb_tpu.dedup.pool import PooledTensor

        released = 0
        for ident in list(self._pooled):
            s = self._sets.get(ident)
            for item in (s.items or []) if s is not None else []:
                if isinstance(item, PooledTensor):
                    released += item.drop_cache()
        return released

    def _live_pool_cache_bytes(self) -> int:
        """Bytes currently held by pooled sets' cached assemblies —
        counted into the pressure total (the caches themselves can BE
        the pressure; invisible bytes would defeat the cap)."""
        from netsdb_tpu.dedup.pool import PooledTensor

        total = 0
        for ident in self._pooled:
            s = self._sets.get(ident)
            for item in (s.items or []) if s is not None else []:
                if isinstance(item, PooledTensor) and item._cache is not None:
                    total += int(item._cache.data.nbytes)
        return total

    # --- eviction (ref: PageCache::evict + LocalitySet policies) ------
    def _maybe_evict(self, exclude: Optional[SetIdentifier] = None) -> None:
        total = sum(s.nbytes for s in self._sets.values() if s.items is not None)
        total += self._live_pool_bytes()
        total += self._live_pool_cache_bytes()
        if total <= self.max_host_bytes:
            return
        # pressure: cached pool assemblies go first — dropping them is
        # free (one gather re-creates), spilling a set is not
        total -= self.drop_pool_caches()
        if total <= self.max_host_bytes:
            return
        candidates = [
            s for s in self._sets.values()
            if s.items is not None and s.ident != exclude and s.nbytes > 0
            and s.alias_of is None and s.storage != "paged"
        ]
        # Policy per set; mixed policies resolved by sorting key.
        def key(s: _StoredSet):
            if s.eviction == "mru":
                return -s.last_access
            if s.eviction == "random":
                return random.random()
            return s.last_access  # lru

        pool_before = self._live_pool_bytes()
        for s in sorted(candidates, key=key):
            if total <= self.max_host_bytes:
                break
            self.flush(s.ident)
            total -= s.nbytes
            s.items = None
            s.nbytes = 0
            self.stats.evictions += 1
            if s.ident in self._pooled:
                # evicting a pooled set may release its shared pool
                # (when it was the last referencing set) — credit the
                # released bytes or the loop over-evicts everyone else
                pool_now = self._live_pool_bytes()
                total -= pool_before - pool_now
                pool_before = pool_now

    def _require(self, ident: SetIdentifier) -> _StoredSet:
        if ident not in self._sets:
            if os.path.exists(self._spill_path(ident)):
                self._sets[ident] = _StoredSet(ident=ident, items=None,
                                               persistence="persistent")
                return self._sets[ident]
            raise KeyError(f"unknown set {ident}; create_set first")
        return self._sets[ident]

    # --- stats (ref: StorageCollectStats → Statistics) ----------------
    @_locked
    def set_stats(self, ident: SetIdentifier) -> Dict[str, Any]:
        s = self._require(ident)
        items = s.items if s.items is not None else []
        return {
            "ident": str(ident),
            "num_items": len(items),
            "nbytes": s.nbytes,
            "in_memory": s.items is not None,
            "persistence": s.persistence,
            "alias_of": str(s.alias_of) if s.alias_of else None,
            "placement": s.placement.label() if s.placement is not None else None,
            "storage": s.storage,
            "version": s.version,
            # the bounded dirty-range log (partial-run device caching):
            # (start, end) per write, end=None for whole-scope writes
            "dirty_ranges": list(s.dirty_log),
        }
