from netsdb_tpu.storage.store import SetStore, SetIdentifier, CacheStats

__all__ = ["SetStore", "SetIdentifier", "CacheStats"]
