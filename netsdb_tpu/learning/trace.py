"""TPC-H self-learning trace generation & training drivers.

Reference: ``src/tpch/source/tpchPrepareTraining.cc`` (builds
LambdaStatistics / PartitionSchemeStatistics / EnvironmentStatistics
tables from initial runs), ``tpchGenTrace.cc`` (for each partition
scheme: recreate + reload every table partitioned by that scheme's
lambda, run the query suite, append RUN_STAT rows — the traces shipped
in ``gen_trace.sql``), and ``tpchTraining1.cc`` (feed state/reward to
the RL server per scheme). README workflow: ``README.md:216-256``.

Here the three drivers are functions over the same stores:

- :func:`prepare_training` — harvest candidate partition lambdas per
  table (the reference reads the LAMBDA table its SelfLearningDB filled
  during initial runs; we declare the join/group-by keys the ten queries
  actually use) and enumerate partition schemes into a :class:`TraceDB`.
- :func:`gen_trace` — per scheme: reload tables hash-dispatched by the
  scheme's lambda (``storage.dispatcher.HashPolicy`` over shard sets =
  the reference's per-node partitioned reload), run the queries, record
  RUN_STAT rows.
- :func:`train` — replay the trace through the in-process actor-critic
  (:class:`~netsdb_tpu.learning.rl.DRLPlacementAdvisor`), returning the
  learned best scheme per query.
"""

from __future__ import annotations

import dataclasses
import sqlite3
import time
from typing import Dict, List, Optional, Sequence, Tuple

from netsdb_tpu.learning.advisor import PlacementCandidate
from netsdb_tpu.learning.history import HistoryDB
from netsdb_tpu.learning.rl import DRLPlacementAdvisor
from netsdb_tpu.storage.dispatcher import HashPolicy, dispatch_to_sets
from netsdb_tpu.workloads import tpch


@dataclasses.dataclass(frozen=True)
class PartitionLambda:
    """A candidate hash-partition key for one table — the reference's
    ``LambdaIdentifier`` (jobName, computationName, lambdaName) resolved
    to what it actually denotes: a key column."""
    lambda_id: int
    table: str
    column: str


@dataclasses.dataclass(frozen=True)
class PartitionScheme:
    """One lambda per table — reference PartitionSchemeStatistics row
    (customerLambda, lineitemLambda, orderLambda, ...)."""
    scheme_id: int
    lambdas: Tuple[PartitionLambda, ...]

    @property
    def label(self) -> str:
        return "scheme:" + ",".join(
            f"{l.table}.{l.column}" for l in self.lambdas)

    def column_for(self, table: str) -> Optional[str]:
        for l in self.lambdas:
            if l.table == table:
                return l.column
        return None


# The partition-key candidates the ten implemented queries exercise
# (join keys and group-by keys in workloads/tpch.py; the reference's
# LAMBDA table records the same attribute-access lambdas from its runs).
CANDIDATE_LAMBDAS: Dict[str, Tuple[str, ...]] = {
    "customer": ("c_custkey", "c_nationkey"),
    "lineitem": ("l_orderkey", "l_partkey"),
    "orders": ("o_orderkey", "o_custkey"),
    "part": ("p_partkey",),
    "supplier": ("s_suppkey", "s_nationkey"),
    "partsupp": ("ps_partkey", "ps_suppkey"),
    "region": ("r_regionkey",),
    "nation": ("n_nationkey", "n_regionkey"),
}

DEFAULT_QUERIES = ("q01", "q02", "q03", "q04", "q06",
                   "q12", "q13", "q14", "q17", "q22")

# The partition key each query actually needs per table (its join/probe
# key in workloads/tpch.py). When the active scheme partitions a table
# by a different key, the engine must re-shuffle that table before the
# co-partitioned join — precisely the cost the reference's self-learning
# observes in RUN_STAT and learns to avoid (documentation.md:5-10: the
# win is reusing a placement that matches the workload's keys).
QUERY_JOIN_KEYS: Dict[str, Dict[str, str]] = {
    "q01": {},  # single-table scan+aggregate
    "q02": {"part": "p_partkey", "partsupp": "ps_partkey",
            "supplier": "s_suppkey", "nation": "n_nationkey",
            "region": "r_regionkey"},
    "q03": {"customer": "c_custkey", "orders": "o_custkey",
            "lineitem": "l_orderkey"},
    "q04": {"orders": "o_orderkey", "lineitem": "l_orderkey"},
    "q06": {},  # single-table scan
    "q12": {"orders": "o_orderkey", "lineitem": "l_orderkey"},
    "q13": {"customer": "c_custkey", "orders": "o_custkey"},
    "q14": {"lineitem": "l_partkey", "part": "p_partkey"},
    "q17": {"lineitem": "l_partkey", "part": "p_partkey"},
    "q22": {"customer": "c_custkey", "orders": "o_custkey"},
}

_SCHEMA = """
CREATE TABLE IF NOT EXISTS lambda_statistics (
    lambda_id INTEGER PRIMARY KEY, table_name TEXT, column_name TEXT);
CREATE TABLE IF NOT EXISTS partition_scheme_statistics (
    scheme_id INTEGER PRIMARY KEY, label TEXT, lambda_ids TEXT);
CREATE TABLE IF NOT EXISTS environment_statistics (
    env_id INTEGER PRIMARY KEY, data_scale INTEGER, num_nodes INTEGER,
    ts REAL);
CREATE TABLE IF NOT EXISTS run_stat (
    run_id INTEGER PRIMARY KEY AUTOINCREMENT, scheme_id INTEGER,
    query_name TEXT, elapsed_s REAL, ts REAL);
"""


class TraceDB:
    """The four statistics tables + RUN_STAT, as in the reference's
    self-learning sqlite DB (``tpchPrepareTraining.cc`` comments list
    the schema; trace rows: ``gen_trace.sql``)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path)
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    # -- prepare-training writes --------------------------------------
    def put_lambda(self, lam: PartitionLambda) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO lambda_statistics VALUES (?, ?, ?)",
            (lam.lambda_id, lam.table, lam.column))

    def put_scheme(self, scheme: PartitionScheme) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO partition_scheme_statistics "
            "VALUES (?, ?, ?)",
            (scheme.scheme_id, scheme.label,
             ",".join(str(l.lambda_id) for l in scheme.lambdas)))

    def put_environment(self, data_scale: int, num_nodes: int) -> None:
        self._conn.execute(
            "INSERT INTO environment_statistics "
            "(data_scale, num_nodes, ts) VALUES (?, ?, ?)",
            (data_scale, num_nodes, time.time()))
        self._conn.commit()

    # -- trace writes/reads -------------------------------------------
    def record_run(self, scheme_id: int, query_name: str,
                   elapsed_s: float) -> None:
        self._conn.execute(
            "INSERT INTO run_stat (scheme_id, query_name, elapsed_s, ts) "
            "VALUES (?, ?, ?, ?)",
            (scheme_id, query_name, elapsed_s, time.time()))
        self._conn.commit()

    def runs(self, query_name: Optional[str] = None) -> List[Dict]:
        q = ("SELECT scheme_id, query_name, elapsed_s FROM run_stat"
             + (" WHERE query_name = ?" if query_name else ""))
        cur = self._conn.execute(q, (query_name,) if query_name else ())
        return [{"scheme_id": s, "query": n, "elapsed_s": e}
                for s, n, e in cur.fetchall()]

    def schemes(self) -> List[PartitionScheme]:
        lams = {i: PartitionLambda(i, t, c) for i, t, c in self._conn.execute(
            "SELECT lambda_id, table_name, column_name "
            "FROM lambda_statistics")}
        out = []
        for sid, _label, ids in self._conn.execute(
                "SELECT scheme_id, label, lambda_ids "
                "FROM partition_scheme_statistics"):
            out.append(PartitionScheme(
                sid, tuple(lams[int(i)] for i in ids.split(","))))
        return sorted(out, key=lambda s: s.scheme_id)

    def close(self) -> None:
        self._conn.close()


def prepare_training(trace_db: TraceDB, data_scale: int = 1,
                     num_nodes: int = 1,
                     candidates: Optional[Dict[str, Sequence[str]]] = None,
                     ) -> List[PartitionScheme]:
    """Build the statistics tables and enumerate partition schemes.

    Scheme enumeration mirrors the reference's: a baseline scheme of
    each table's primary candidate, plus one variant per alternative
    lambda (vary one table at a time) — not the full cross product,
    which the reference also avoids (its schemes come from observed
    lambda combinations)."""
    candidates = {t: tuple(v) for t, v in (candidates
                                           or CANDIDATE_LAMBDAS).items()}
    lambda_ids: Dict[Tuple[str, str], PartitionLambda] = {}
    next_id = 1
    for table, cols in sorted(candidates.items()):
        for col in cols:
            lam = PartitionLambda(next_id, table, col)
            lambda_ids[(table, col)] = lam
            trace_db.put_lambda(lam)
            next_id += 1

    baseline = tuple(lambda_ids[(t, cols[0])]
                     for t, cols in sorted(candidates.items()))
    schemes = [PartitionScheme(0, baseline)]
    sid = 1
    for table, cols in sorted(candidates.items()):
        for col in cols[1:]:
            variant = tuple(lambda_ids[(t, col if t == table else c[0])]
                            for t, c in sorted(candidates.items()))
            schemes.append(PartitionScheme(sid, variant))
            sid += 1
    for s in schemes:
        trace_db.put_scheme(s)
    trace_db.put_environment(data_scale, num_nodes)
    return schemes


def load_partitioned(client, scheme: PartitionScheme, db: str = "tpch",
                     tables: Optional[Dict] = None, scale: int = 1,
                     seed: int = 0, n_shards: int = 2) -> None:
    """Reload every table under the scheme: whole-table set for the
    queries plus hash-dispatched shard sets (the reference recreates
    each set with the scheme's partition lambda and re-sends the data —
    ``tpchGenTrace.cc:1028-1072``)."""
    tables = tables or tpch.generate(scale, seed)
    tpch.load_tables(client, db=db, tables=tables)
    for name, rows in tables.items():
        col = scheme.column_for(name)
        if col is None:
            continue
        for i in range(n_shards):  # a reload replaces the old partitioning
            shard = f"{name}_shard{i}"
            if client.set_exists(db, shard):
                client.clear_set(db, shard)
        dispatch_to_sets(client, db, name, rows, n_shards,
                         policy=HashPolicy(lambda r, c=col: r[c]))


def gen_trace(client, trace_db: TraceDB,
              schemes: Optional[Sequence[PartitionScheme]] = None,
              queries: Sequence[str] = DEFAULT_QUERIES,
              db: str = "tpch", scale: int = 1, seed: int = 0,
              n_shards: int = 2) -> None:
    """Run the suite once per scheme, recording RUN_STAT rows —
    ``tpchGenTrace.cc``'s main loop.

    The recorded time is repartition cost + query cost. Repartition
    happens for every table whose scheme key differs from the key the
    query joins on (``QUERY_JOIN_KEYS``): those rows are re-dispatched
    into join-keyed shard sets first, the single-controller stand-in for
    the reference's cross-node shuffle. A scheme matching the workload's
    join keys therefore genuinely runs faster — the signal the
    reference's RUN_STAT captures."""
    schemes = list(schemes) if schemes is not None else trace_db.schemes()
    tables = tpch.generate(scale, seed)
    for scheme in schemes:
        load_partitioned(client, scheme, db=db, tables=tables,
                         n_shards=n_shards)
        for qname in queries:
            t0 = time.perf_counter()
            for table, req_key in QUERY_JOIN_KEYS.get(qname, {}).items():
                if scheme.column_for(table) == req_key:
                    continue  # co-partitioned already: no shuffle
                for i in range(n_shards):
                    shard = f"{table}_reshuffle_shard{i}"
                    if client.set_exists(db, shard):
                        client.clear_set(db, shard)
                dispatch_to_sets(
                    client, db, f"{table}_reshuffle", tables[table],
                    n_shards,
                    policy=HashPolicy(lambda r, c=req_key: r[c]))
            tpch.run_query(client, qname, db=db)
            trace_db.record_run(scheme.scheme_id, qname,
                                time.perf_counter() - t0)


def _scheme_candidate(scheme: PartitionScheme) -> PlacementCandidate:
    return PlacementCandidate(label=scheme.label, mesh_shape=(1,),
                              specs={l.table: (l.column,)
                                     for l in scheme.lambdas})


def train(trace_db: TraceDB, query_name: str,
          schemes: Optional[Sequence[PartitionScheme]] = None,
          epochs: int = 4, seed: int = 0) -> PartitionScheme:
    """Replay the recorded trace through the actor-critic and return the
    scheme the learned policy picks for this query —
    ``tpchTraining1.cc``'s train-from-RUN_STAT loop, with the in-process
    :class:`DRLPlacementAdvisor` standing in for the A3C server."""
    schemes = list(schemes) if schemes is not None else trace_db.schemes()
    by_id = {s.scheme_id: s for s in schemes}
    cands = [_scheme_candidate(s) for s in schemes]
    advisor = DRLPlacementAdvisor(cands, db=HistoryDB(), seed=seed)
    runs = [r for r in trace_db.runs(query_name)
            if r["scheme_id"] in by_id]
    if not runs:
        raise ValueError(f"no trace rows for {query_name!r}")
    for _ in range(epochs):
        for r in runs:
            idx = [s.scheme_id for s in schemes].index(r["scheme_id"])
            advisor.record(query_name, cands[idx], r["elapsed_s"])
    best = advisor.choose(query_name, explore=False)
    return schemes[cands.index(best)]
