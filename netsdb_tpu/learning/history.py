"""Execution-history store — the Lachesis self-learning database, lite.

The reference persists every job/stage/data interaction to sqlite
(``src/selfLearning/headers/SelfLearningDB.h:21-51``: tables JOB /
JOB_INSTANCE / JOB_STAGE / DATA / LAMBDA / RUN_STAT), written by the
scheduler during planning (``QuerySchedulerServer.cc:246-430``). Our
executor records one row per job run: plan structure, elapsed wall
time, and the placement/sharding config label in effect — the signal
the placement advisor (``netsdb_tpu.learning.advisor``) learns from.
"""

from __future__ import annotations


import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS job_run (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_name TEXT NOT NULL,
    plan_key TEXT NOT NULL,
    config_label TEXT NOT NULL DEFAULT '',
    elapsed_s REAL NOT NULL,
    ts REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS job_run_name ON job_run (job_name);
"""


class HistoryDB:
    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def record(self, job_name: str, plan_key: str, elapsed_s: float,
               config_label: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_run (job_name, plan_key, config_label, "
                "elapsed_s, ts) VALUES (?, ?, ?, ?, ?)",
                (job_name, plan_key, config_label, elapsed_s, time.time()))
            self._conn.commit()

    def runs(self, job_name: str) -> List[Dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT plan_key, config_label, elapsed_s, ts FROM job_run "
                "WHERE job_name = ? ORDER BY ts", (job_name,))
            return [{"plan_key": r[0], "config": r[1], "elapsed_s": r[2],
                     "ts": r[3]} for r in cur.fetchall()]

    def mean_elapsed(self, job_name: str, config_label: str) -> Optional[float]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT AVG(elapsed_s), COUNT(*) FROM job_run "
                "WHERE job_name = ? AND config_label = ?",
                (job_name, config_label))
            avg, n = cur.fetchone()
        return float(avg) if n else None

    def close(self):
        with self._lock:
            self._conn.close()


# process-global sink the executor writes through (None → in-memory)
_db: Optional[HistoryDB] = None
_current_config_label = ""


def set_history_db(db: Optional[HistoryDB]) -> None:
    global _db
    _db = db


def set_config_label(label: str) -> None:
    """Tag subsequent runs with the active placement config."""
    global _current_config_label
    _current_config_label = label


def record_job(job_name: str, plan, elapsed_s: float) -> None:
    """Called by the executor after every job (see plan/executor.py)."""
    global _db
    if _db is None:
        _db = HistoryDB()
    _db.record(job_name, plan.cache_key()[:512], elapsed_s,
               _current_config_label)


def get_history_db() -> HistoryDB:
    global _db
    if _db is None:
        _db = HistoryDB()
    return _db
