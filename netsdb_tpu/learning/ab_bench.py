"""Live Lachesis A/B: the placement advisor closing the loop end-to-end.

The reference's self-learning story is "first run slow, later runs
fast": the optimizer tries placements, records runtimes, then serves
the best (``documentation.md:5-10``). This module reproduces that as a
LIVE run through the client: each round builds a fresh client with the
advisor installed, ``create_set`` consults the advisor for the block
shape (the page-size analogue), the FF job runs under the chosen arm,
and the measured wall time lands in the history DB — so the advisor's
next choice is driven by real rewards, not test fixtures.

The candidate arms differ in padding waste: at a deliberately
non-block-aligned model width (e.g. 1100), a 1024-block pads every
dimension to 2048 (~3.5x the FLOPs and bytes) while a 128-block pads to
1152 (~5% waste) — a real, measurable placement consequence on one
chip, exactly the kind of knob the reference's optimizer tunes.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Dict, Optional

import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.config import Configuration
from netsdb_tpu.learning.advisor import PlacementAdvisor, PlacementCandidate
from netsdb_tpu.learning.history import HistoryDB
from netsdb_tpu.models.ff import FFModel

DEFAULT_CANDIDATES = (
    PlacementCandidate("block1024", (1,), {"block": (1024, 1024)}),
    PlacementCandidate("block128", (1,), {"block": (128, 128)}),
)


def bench_placement_ab(width: int = 1100, batch: int = 4096,
                       labels: int = 16, rounds: int = 4,
                       history_path: str = ":memory:",
                       seed: int = 0,
                       advisor_kind: str = "rule") -> Dict[str, object]:
    """Run ``rounds`` live FF-inference jobs under the advisor.

    ``advisor_kind="rule"``: explore each arm once, then exploit the
    measured winner (the frequency/rule-based optimizer).
    ``advisor_kind="drl"``: the actor-critic
    :class:`~netsdb_tpu.learning.rl.DRLPlacementAdvisor` makes the live
    choices and learns from the measured on-chip rewards — the
    reference's RLClient wired into live scheduling
    (``src/selfLearning/headers/RLClient.h:18-38``), not just replay
    training. Both speak the same choose/record surface, so the live
    loop is identical; the returned dict adds ``converged`` (greedy
    post-training choice == measured-mean winner) for the DRL arm.

    Returns per-arm mean wall seconds, the decisions audit trail, and
    the exploit-phase speedup of learned-vs-worst."""
    hdb = HistoryDB(history_path)
    if advisor_kind == "drl":
        from netsdb_tpu.learning.rl import DRLPlacementAdvisor

        advisor = DRLPlacementAdvisor(list(DEFAULT_CANDIDATES), hdb,
                                      seed=seed)
    elif advisor_kind == "rule":
        advisor = PlacementAdvisor(list(DEFAULT_CANDIDATES), hdb)
    else:
        raise ValueError(f"advisor_kind must be 'rule' or 'drl', "
                         f"got {advisor_kind!r}")
    job = "ab-inference"
    rng = np.random.default_rng(seed)
    w1 = rng.standard_normal((width, width)).astype(np.float32) * 0.02
    b1 = rng.standard_normal((width,)).astype(np.float32) * 0.01
    wo = rng.standard_normal((labels, width)).astype(np.float32) * 0.02
    bo = rng.standard_normal((labels,)).astype(np.float32) * 0.01
    x = rng.standard_normal((batch, width)).astype(np.float32)

    # one STABLE compile-cache dir for all rounds: the per-round roots
    # are deleted below, and the jax cache pointer is process-global —
    # pointing it at a to-be-deleted dir would leave it dangling (and
    # the warm cache also makes later rounds measure steady state).
    # uid-suffixed so shared machines don't collide on ownership; the
    # pointer intentionally survives the bench (enable_compilation_cache
    # is re-entrant — the next Client repoints it).
    import os

    uid = os.getuid() if hasattr(os, "getuid") else "u"
    cache_dir = os.path.join(tempfile.gettempdir(),
                             f"netsdb_ab_cache_{uid}")
    def one_round(advisor_on: bool = True, force_block=None):
        root = tempfile.mkdtemp(prefix="ab_bench_")
        try:
            client = Client(Configuration(
                root_dir=root, compilation_cache_dir=cache_dir))
            if advisor_on:
                client.set_placement_advisor(advisor, key=job)
            model = FFModel(db="ab")
            model.setup(client)  # create_set consults the advisor HERE
            if force_block is not None:
                model.block = tuple(force_block)
            cand = next(c for c in advisor.candidates
                        if tuple(c.specs["block"]) == model.block)
            model.load_weights(client, w1, b1, wo, bo)
            model.load_inputs(client, x)
            model.inference(client)  # warm this arm's program
            # min-of-3: the noise-robust location estimate for a
            # milliseconds-scale job on a possibly loaded machine (a
            # single inflated wall on the explore round would teach
            # the advisor the wrong winner)
            elapsed = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = model.inference(client)
                np.asarray(out.to_dense())  # sync
                elapsed = min(elapsed, time.perf_counter() - t0)
            return cand, elapsed
        finally:
            shutil.rmtree(root, ignore_errors=True)

    for cand in advisor.candidates:  # warm both compiles, unrecorded
        one_round(advisor_on=False, force_block=cand.specs["block"])
    chosen = []
    for _ in range(rounds):
        cand, elapsed = one_round()
        advisor.record(job, cand, elapsed)
        chosen.append((cand.label, round(elapsed, 4)))

    means = {c.label: hdb.mean_elapsed(job, c.label)
             for c in advisor.candidates}
    if advisor_kind == "drl":
        winner = advisor.choose(job, explore=False).label
    else:
        winner = advisor.choose(job).label
    decisions = hdb.runs(f"{job}:decisions")
    worst = max(v for v in means.values() if v is not None)
    best = min(v for v in means.values() if v is not None)
    out = {"advisor": advisor_kind, "rounds": chosen, "mean_s": means,
           "winner": winner, "decisions_recorded": len(decisions),
           "learned_speedup": round(worst / best, 2) if best else None}
    if advisor_kind == "drl":
        by_mean = min((v, k) for k, v in means.items()
                      if v is not None)[1]
        out["converged"] = winner == by_mean
    return out


def _converged(winner: str, means: Dict[str, Optional[float]],
               noise_frac: float = 0.25) -> bool:
    """DRL convergence check under the measurement-noise discipline
    (r2 lesson, utils.timing): the greedy choice must match the
    measured-mean winner UNLESS the arm means are within ``noise_frac``
    of each other — statistically indistinguishable arms make either
    choice correct (both-below-noise = undecidable, not a failure)."""
    vals = {k: v for k, v in means.items() if v is not None}
    if winner not in vals:
        return False  # greedy picked an arm that was never measured
    by_mean = min(vals, key=vals.get)
    if winner == by_mean:
        return True
    lo = vals[by_mean]
    return vals[winner] <= lo * (1.0 + noise_frac)


# --------------------------------------------- distribution A/B (arms
# carrying Placements — Lachesis choosing SHARDING, the interesting
# decision variable on a TPU mesh)
def distribution_candidates():
    """Replicated vs row-sharded dimension table over all devices —
    the broadcast-join-vs-repartition decision as advisor arms
    (``arm.specs["placement"]`` consumed by ``Client.create_set``)."""
    from netsdb_tpu.parallel.placement import Placement

    return (
        PlacementCandidate("dim_replicated", (1,),
                           {"placement": Placement((("data", 0),),
                                                   (None,))}),
        PlacementCandidate("dim_rowsharded", (1,),
                           {"placement": Placement((("data", 0),),
                                                   ("data",))}),
    )


def batch_candidates():
    """Replicated vs batch-sharded ACTIVATIONS for FF inference — the
    data-parallelism decision as advisor arms, keyed by set name so
    only the ``inputs`` set takes the arm's placement. This pair is
    DISCRIMINATING by construction: a replicated batch makes every
    mesh device compute the full inference (N× the FLOPs under SPMD —
    on the shared-core virtual CPU mesh that is N× the wall clock, on
    real chips N× the energy/HBM for no throughput), while the sharded
    arm splits the batch. The gap is workload-sized, far outside the
    measurement-noise band, so convergence is asserted STRICTLY."""
    from netsdb_tpu.parallel.placement import Placement

    return (
        PlacementCandidate("x_replicated", (1,),
                           {"inputs": Placement((("data", 0),),
                                                (None, None))}),
        PlacementCandidate("x_sharded", (1,),
                           {"inputs": Placement((("data", 0),),
                                                ("data", None))}),
    )


def bench_batch_distribution_ab(width: int = 768, batch: int = 4096,
                                labels: int = 16, rounds: int = 4,
                                reps: int = 3,
                                history_path: str = ":memory:",
                                seed: int = 0,
                                advisor_kind: str = "drl"
                                ) -> Dict[str, object]:
    """Live A/B where the advisor decides whether the FF inference
    batch is replicated or data-sharded over the mesh — the
    DISCRIMINATING distribution decision (see
    :func:`batch_candidates`): the loser does mesh-size× the compute,
    so the greedy choice must match the measured winner exactly
    (``converged_strict``; the 25%-band fallback of ``_converged`` is
    reserved for genuinely indistinguishable arms, documented there).

    Weights are replicated explicitly; only ``inputs`` consults the
    advisor. Each measured round runs ``reps`` inferences (amortizing
    per-job dispatch overhead) under a warm compile cache."""
    import os

    import jax

    from netsdb_tpu.parallel.placement import Placement

    hdb = HistoryDB(history_path)
    cands = list(batch_candidates())
    if advisor_kind == "drl":
        from netsdb_tpu.learning.rl import DRLPlacementAdvisor

        advisor = DRLPlacementAdvisor(cands, hdb, seed=seed)
    else:
        advisor = PlacementAdvisor(cands, hdb)
    job = "ab-batch-dist"
    rng = np.random.default_rng(seed)
    w1 = rng.standard_normal((width, width)).astype(np.float32) * 0.02
    b1 = rng.standard_normal((width,)).astype(np.float32) * 0.01
    wo = rng.standard_normal((labels, width)).astype(np.float32) * 0.02
    bo = rng.standard_normal((labels,)).astype(np.float32) * 0.01
    x = rng.standard_normal((batch, width)).astype(np.float32)
    uid = os.getuid() if hasattr(os, "getuid") else "u"
    cache_dir = os.path.join(tempfile.gettempdir(),
                             f"netsdb_ab_cache_{uid}")
    wpl = {n: Placement((("data", 0),), (None, None))
           for n in ("w1", "b1", "wo", "bo")}

    def one_round(placement_override=None):
        root = tempfile.mkdtemp(prefix="ab_batch_")
        try:
            client = Client(Configuration(
                root_dir=root, compilation_cache_dir=cache_dir))
            if placement_override is None:
                client.set_placement_advisor(advisor, key=job)
            model = FFModel(db="ab", block=(256, 256))
            placements = dict(wpl)
            if placement_override is not None:
                placements["inputs"] = placement_override
            model.setup(client, placements=placements)
            arm = getattr(client, "_advisor_arm", None)
            model.load_weights(client, w1, b1, wo, bo)
            model.load_inputs(client, x)
            out = model.inference(client)  # warm this arm's program
            jax.block_until_ready(out.data)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = model.inference(client)
            jax.block_until_ready(out.data)
            return arm, (time.perf_counter() - t0) / reps
        finally:
            shutil.rmtree(root, ignore_errors=True)

    for cand in cands:  # warm both compiled programs, unrecorded
        one_round(placement_override=cand.specs["inputs"])
    chosen = []
    r = 0
    while r < rounds or (r < 2 * rounds and any(
            hdb.mean_elapsed(job, c.label) is None for c in cands)):
        # extra rounds until every arm has a measurement: a stochastic
        # policy that happened to sample one arm only would make the
        # convergence check vacuous — the exact r4 complaint
        arm, elapsed = one_round()
        assert arm is not None, "advisor arm was not applied"
        advisor.record(job, arm, elapsed)
        chosen.append((arm.label, round(elapsed, 4)))
        r += 1

    means = {c.label: hdb.mean_elapsed(job, c.label)
             for c in advisor.candidates}
    winner = (advisor.choose(job, explore=False).label
              if advisor_kind == "drl" else advisor.choose(job).label)
    vals = {k: v for k, v in means.items() if v is not None}
    by_mean = min(vals, key=vals.get) if vals else None
    worst = max(vals.values()) if vals else None
    best = min(vals.values()) if vals else None
    return {"advisor": advisor_kind, "rounds": chosen, "mean_s": means,
            "winner": winner, "by_mean": by_mean,
            "gap": round(worst / best, 2) if best else None,
            "converged_strict": winner == by_mean,
            "decisions_recorded": len(hdb.runs(f"{job}:decisions"))}


def bench_fusion_ab(rows: int = 120_000, spine: int = 6,
                    rounds: int = 4, reps: int = 3,
                    history_path: str = ":memory:",
                    seed: int = 0) -> Dict[str, object]:
    """Live A/B where the advisor decides ``plan_fusion`` for a mixed
    paged/resident job — the fusion decision as a bandit arm
    (:func:`~netsdb_tpu.learning.advisor.fusion_candidates`), driven
    through the same measure-record-choose loop as every placement
    arm.  Each round executes a q06-style paged fold joined against a
    ``spine``-node resident Apply chain under the arm's config, with
    the measured wall recorded against the arm — "first run explores,
    later runs serve the measured winner" (the reference's
    self-learning loop, applied to plan compilation)."""
    import os

    import jax

    from netsdb_tpu.learning.advisor import fusion_candidates
    from netsdb_tpu.plan.computations import Apply, Join, ScanSet, WriteSet
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable

    hdb = HistoryDB(history_path)
    cands = list(fusion_candidates())
    advisor = PlacementAdvisor(cands, hdb)
    job = "ab-fusion"
    rng = np.random.default_rng(seed)
    uid = os.getuid() if hasattr(os, "getuid") else "u"
    cache_dir = os.path.join(tempfile.gettempdir(),
                             f"netsdb_ab_cache_{uid}")
    li = {
        "l_shipdate": rng.integers(19940101, 19950101, rows,
                                   dtype=np.int32),
        "l_discount": np.full(rows, 0.06, np.float32),
        "l_quantity": np.full(rows, 10.0, np.float32),
        "l_extendedprice": rng.uniform(1000, 2000, rows
                                       ).astype(np.float32),
    }
    dim = {"x": rng.standard_normal(4096).astype(np.float32)}

    def build_sink():
        import jax.numpy as jnp

        s = ScanSet("ab", "dim")
        node = s
        for i in range(spine):
            node = Apply(node, lambda t, _i=i: ColumnTable(
                {"x": t["x"] * 1.000001 + _i * 0.0}, t.dicts, t.valid),
                label=f"spine{i}")
        z = Apply(node, lambda t: jnp.sum(t["x"]) * 0.0, label="zsum")
        q06 = rdag.q06_sink("ab")
        j = Join(q06.inputs[0], z, fn=lambda rev, v: ColumnTable(
            {"revenue": rev["revenue"] + v}, rev.dicts, rev.valid),
            label="combine")
        return WriteSet(j, "ab", "fusion_out")

    def one_round(arm):
        root = tempfile.mkdtemp(prefix="ab_fusion_")
        try:
            cfg = Configuration(root_dir=root,
                                compilation_cache_dir=cache_dir,
                                fusion_cost_source="static")
            cfg.plan_fusion = bool(arm.specs["plan_fusion"])
            client = Client(cfg)
            client.create_database("ab")
            client.create_set("ab", "lineitem", type_name="table",
                              storage="paged")
            client.send_table("ab", "lineitem", ColumnTable(li, {}))
            client.create_set("ab", "dim", type_name="table")
            client.send_table("ab", "dim", ColumnTable(dim, {}))
            out = client.execute_computations(build_sink(),
                                              job_name=job)  # warm
            jax.block_until_ready(next(iter(out.values()))["revenue"])
            elapsed = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                out = client.execute_computations(build_sink(),
                                                  job_name=job)
                jax.block_until_ready(
                    next(iter(out.values()))["revenue"])
                elapsed = min(elapsed, time.perf_counter() - t0)
            return elapsed
        finally:
            shutil.rmtree(root, ignore_errors=True)

    for cand in cands:  # warm both arms' programs, unrecorded
        one_round(cand)
    chosen = []
    for _ in range(rounds):
        cand = advisor.choose(job)
        elapsed = one_round(cand)
        advisor.record(job, cand, elapsed)
        chosen.append((cand.label, round(elapsed, 4)))
    means = {c.label: hdb.mean_elapsed(job, c.label) for c in cands}
    winner = advisor.choose(job).label
    vals = {k: v for k, v in means.items() if v is not None}
    worst = max(vals.values()) if vals else None
    best = min(vals.values()) if vals else None
    return {"rounds": chosen, "mean_s": means, "winner": winner,
            "learned_speedup": round(worst / best, 2) if best else None}


def bench_mapper_ab(rows: int = 120_000, spine: int = 6,
                    rounds: int = 4, reps: int = 3,
                    shape: str = "mixed",
                    history_path: str = ":memory:",
                    seed: int = 0) -> Dict[str, object]:
    """Live A/B where the advisor decides the fusion MAPPER (optimal
    DP vs greedy whole-run) for one plan SHAPE
    (:func:`~netsdb_tpu.learning.advisor.mapper_candidates`).  The
    history key carries the shape (``ab-mapper:<shape>``), so the
    bandit learns a per-shape winner — ``shape="spine"`` runs the
    resident Apply chain alone (where the DP's segmentation can
    differ), ``shape="mixed"`` the same mixed paged/resident DAG
    :func:`bench_fusion_ab` measures."""
    import os

    import jax

    from netsdb_tpu.learning.advisor import mapper_candidates
    from netsdb_tpu.plan.computations import Apply, Join, ScanSet, WriteSet
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.table import ColumnTable

    hdb = HistoryDB(history_path)
    cands = list(mapper_candidates())
    advisor = PlacementAdvisor(cands, hdb)
    job = f"ab-mapper:{shape}"
    rng = np.random.default_rng(seed)
    uid = os.getuid() if hasattr(os, "getuid") else "u"
    cache_dir = os.path.join(tempfile.gettempdir(),
                             f"netsdb_ab_cache_{uid}")
    li = {
        "l_shipdate": rng.integers(19940101, 19950101, rows,
                                   dtype=np.int32),
        "l_discount": np.full(rows, 0.06, np.float32),
        "l_quantity": np.full(rows, 10.0, np.float32),
        "l_extendedprice": rng.uniform(1000, 2000, rows
                                       ).astype(np.float32),
    }
    dim = {"x": rng.standard_normal(4096).astype(np.float32)}

    def build_sink():
        import jax.numpy as jnp

        s = ScanSet("ab", "dim")
        node = s
        for i in range(spine):
            node = Apply(node, lambda t, _i=i: ColumnTable(
                {"x": t["x"] * 1.000001 + _i * 0.0}, t.dicts, t.valid),
                label=f"spine{i}")
        if shape == "spine":
            return WriteSet(node, "ab", "mapper_out")
        z = Apply(node, lambda t: jnp.sum(t["x"]) * 0.0, label="zsum")
        q06 = rdag.q06_sink("ab")
        j = Join(q06.inputs[0], z, fn=lambda rev, v: ColumnTable(
            {"revenue": rev["revenue"] + v}, rev.dicts, rev.valid),
            label="combine")
        return WriteSet(j, "ab", "mapper_out")

    def one_round(arm):
        root = tempfile.mkdtemp(prefix="ab_mapper_")
        try:
            cfg = Configuration(root_dir=root,
                                compilation_cache_dir=cache_dir,
                                fusion_cost_source="static")
            cfg.fusion_mapper = str(arm.specs["fusion_mapper"])
            client = Client(cfg)
            client.create_database("ab")
            client.create_set("ab", "lineitem", type_name="table",
                              storage="paged")
            client.send_table("ab", "lineitem", ColumnTable(li, {}))
            client.create_set("ab", "dim", type_name="table")
            client.send_table("ab", "dim", ColumnTable(dim, {}))

            def one():
                out = client.execute_computations(build_sink(),
                                                  job_name=job)
                v = next(iter(out.values()))
                leaf = v["revenue"] if shape != "spine" else v["x"]
                jax.block_until_ready(leaf)

            one()  # warm
            elapsed = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                one()
                elapsed = min(elapsed, time.perf_counter() - t0)
            return elapsed
        finally:
            shutil.rmtree(root, ignore_errors=True)

    for cand in cands:  # warm both arms' programs, unrecorded
        one_round(cand)
    chosen = []
    for _ in range(rounds):
        cand = advisor.choose(job)
        elapsed = one_round(cand)
        advisor.record(job, cand, elapsed)
        chosen.append((cand.label, round(elapsed, 4)))
    means = {c.label: hdb.mean_elapsed(job, c.label) for c in cands}
    winner = advisor.choose(job).label
    vals = {k: v for k, v in means.items() if v is not None}
    worst = max(vals.values()) if vals else None
    best = min(vals.values()) if vals else None
    return {"shape": shape, "rounds": chosen, "mean_s": means,
            "winner": winner,
            "learned_speedup": round(worst / best, 2) if best else None}


def bench_distribution_ab(scale: int = 16, rounds: int = 4,
                          history_path: str = ":memory:",
                          seed: int = 0,
                          advisor_kind: str = "rule") -> Dict[str, object]:
    """Live A/B where the advisor decides a SET'S PLACEMENT: each round
    creates the TPC-H ``orders`` set with NO explicit placement — the
    installed advisor's arm supplies one (replicated = broadcast join,
    or row-sharded = repartitioned build) — then runs the q12 suite
    DAG distributed over the placed sets and records the measured wall
    time against the arm that was actually applied (the reference's
    RLClient driving live scheduling, ``RLClient.h:18-38``).

    Needs a multi-device mesh to have signal (on one chip every
    placement degrades to the trivial mesh); the test suite runs it on
    the virtual 8-device CPU mesh."""
    from netsdb_tpu.parallel.placement import Placement
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.relational.queries import tables_from_rows
    from netsdb_tpu.storage.store import SetIdentifier
    from netsdb_tpu.workloads import tpch

    hdb = HistoryDB(history_path)
    cands = list(distribution_candidates())
    if advisor_kind == "drl":
        from netsdb_tpu.learning.rl import DRLPlacementAdvisor

        advisor = DRLPlacementAdvisor(cands, hdb, seed=seed)
    elif advisor_kind == "rule":
        advisor = PlacementAdvisor(cands, hdb)
    else:
        raise ValueError(f"advisor_kind must be 'rule' or 'drl', "
                         f"got {advisor_kind!r}")
    job = "ab-distribution"
    tables = tables_from_rows(tpch.generate(scale=scale, seed=seed))
    chosen = []
    applied_labels = []
    # one STABLE compile cache across rounds (same discipline as
    # bench_placement_ab): without it the explore rounds measure cold
    # compiles, not placements — the r2 autotune noise trap
    import os

    uid = os.getuid() if hasattr(os, "getuid") else "u"
    cache_dir = os.path.join(tempfile.gettempdir(),
                             f"netsdb_ab_cache_{uid}")

    def one_round(placement_override=None):
        """One job under either an explicit placement (warmup) or the
        advisor's choice (measured). The fact placement is EXPLICIT
        (always row-sharded); the advisor only decides the dimension
        set. Returns (applied arm, placement label, elapsed)."""
        from netsdb_tpu.parallel.placement import Placement as _P

        root = tempfile.mkdtemp(prefix="ab_dist_")
        try:
            client = Client(Configuration(
                root_dir=root, compilation_cache_dir=cache_dir))
            if placement_override is None:
                client.set_placement_advisor(advisor, key=job)
            client.create_database("d")
            client.create_set("d", "lineitem", type_name="table",
                              placement=_P.data_parallel(ndim=1))
            client.create_set("d", "orders", type_name="table",
                              placement=placement_override)
            arm = getattr(client, "_advisor_arm", None)
            pl = client.store.placement_of(SetIdentifier("d", "orders"))
            for n in ("lineitem", "orders"):
                client.send_table("d", n, tables[n])
            sink = rdag.suite_sink_for(client, "d", "q12")
            t0 = time.perf_counter()
            out = client.execute_computations(sink, job_name=job)
            import jax

            jax.block_until_ready(next(iter(out.values())))
            return (arm, pl.label() if pl is not None else None,
                    time.perf_counter() - t0)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    # warm every arm's compiled program once, UNRECORDED: the measured
    # rounds must compare placements, not first compiles (the r2
    # autotune lesson — cold-compile walls are pure noise)
    for cand in cands:
        one_round(placement_override=cand.specs["placement"])
    for _ in range(rounds):
        arm, pl_label, elapsed = one_round()
        assert arm is not None, "advisor arm was not applied"
        applied_labels.append((arm.label, pl_label))
        advisor.record(job, arm, elapsed)
        chosen.append((arm.label, round(elapsed, 4)))

    means = {c.label: hdb.mean_elapsed(job, c.label)
             for c in advisor.candidates}
    if advisor_kind == "drl":
        winner = advisor.choose(job, explore=False).label
    else:
        winner = advisor.choose(job).label
    worst = max(v for v in means.values() if v is not None)
    best = min(v for v in means.values() if v is not None)
    out = {"advisor": advisor_kind, "rounds": chosen, "mean_s": means,
           "winner": winner, "applied": applied_labels,
           "decisions_recorded": len(hdb.runs(f"{job}:decisions")),
           "learned_speedup": round(worst / best, 2) if best else None}
    if advisor_kind == "drl":
        out["converged"] = _converged(winner, means)
    return out


def bench_rebalance_ab(rows: int = 24_000, rounds: int = 2,
                       queries: int = 20,
                       history_path: str = ":memory:",
                       seed: int = 0) -> Dict[str, object]:
    """Live A/B where the advisor decides ``config.rebalance`` for a
    skewed serving pool — the self-rebalancing loop as a bandit arm
    (:func:`~netsdb_tpu.learning.advisor.rebalance_candidates`).

    Each round spins a fresh 4-daemon pool, ingests an 80/20
    hot/cold pair of range-sharded tables, registers a 5th daemon
    mid-run, then serves a skewed routed-read mix and records the
    measured wall against the arm. The ``rebalance_on`` arm drives
    the FULL advisor protocol on the live pool —
    :meth:`~netsdb_tpu.serve.rebalance.Rebalancer.advise` measures
    baseline routed throughput, applies the skew-planner's moves,
    re-measures, and commits (ticking ``rebalance.advisor_commits``)
    or reverts the campaign — while ``rebalance_frozen`` leaves the
    new member slot-less. Exactness is asserted every round: the
    scanned-back tables must be row-exact regardless of arm."""
    from netsdb_tpu.learning.advisor import rebalance_candidates
    from netsdb_tpu.serve.client import RemoteClient
    from netsdb_tpu.serve.server import ServeController
    from netsdb_tpu.workloads.serve_bench import scaleout_table

    hdb = HistoryDB(history_path)
    cands = list(rebalance_candidates())
    advisor = PlacementAdvisor(cands, hdb)
    job = "ab-rebalance"
    hot = scaleout_table(rows, seed=seed + 1)
    cold = scaleout_table(max(rows // 10, 8), seed=seed + 2)
    decisions = []

    def one_round(arm):
        root = tempfile.mkdtemp(prefix="ab_rebalance_")
        daemons = []
        client = None
        try:
            on = bool(arm.specs["rebalance"])
            workers = []
            for i in range(3):
                w = ServeController(Configuration(
                    root_dir=f"{root}/w{i}", rebalance=on), port=0)
                w.start()
                daemons.append(w)
                workers.append(w)
            leader = ServeController(
                Configuration(root_dir=f"{root}/leader", rebalance=on),
                port=0,
                workers=[f"127.0.0.1:{w.port}" for w in workers])
            leader.start()
            daemons.append(leader)
            client = RemoteClient(f"127.0.0.1:{leader.port}")
            client.create_database("ab")
            client.create_set("ab", "hot", type_name="table",
                              placement="range")
            client.create_set("ab", "cold", type_name="table",
                              placement="range")
            client.send_table("ab", "hot", hot)
            client.send_table("ab", "cold", cold)
            w4 = ServeController(Configuration(
                root_dir=f"{root}/w4", rebalance=on), port=0)
            w4.start()
            daemons.append(w4)
            # register only — the move decision belongs to the
            # measured advisor pass below, not the registration
            leader.add_worker(f"127.0.0.1:{w4.port}", campaign=False)

            def routed_throughput() -> float:
                t0 = time.perf_counter()
                for i in range(queries):
                    name = "hot" if i % 5 else "cold"
                    t = client.get_table_streamed("ab", name)
                    want = rows if name == "hot" else cold.num_rows
                    if t.num_rows != want:
                        raise AssertionError(
                            f"{name}: {t.num_rows} != {want}")
                return queries / (time.perf_counter() - t0)

            if on:
                verdict = leader.rebalancer.advise(routed_throughput)
                decisions.append((arm.label, verdict["decision"],
                                  len(verdict.get("moves") or [])))
            t0 = time.perf_counter()
            routed_throughput()
            elapsed = time.perf_counter() - t0
            # exactness gate: the campaign (or its absence) must not
            # change a single row the clients see
            back = client.get_table_streamed("ab", "hot")
            if back.num_rows != rows:
                raise AssertionError(
                    f"hot rows drifted: {back.num_rows} != {rows}")
            return elapsed
        finally:
            if client is not None:
                client.close()
            for d in daemons:
                d.shutdown()
            shutil.rmtree(root, ignore_errors=True)

    for cand in cands:  # warm both arms' pools, unrecorded
        one_round(cand)
    chosen = []
    for _ in range(rounds):
        cand = advisor.choose(job)
        elapsed = one_round(cand)
        advisor.record(job, cand, elapsed)
        chosen.append((cand.label, round(elapsed, 4)))
    means = {c.label: hdb.mean_elapsed(job, c.label) for c in cands}
    winner = advisor.choose(job).label
    vals = {k: v for k, v in means.items() if v is not None}
    worst = max(vals.values()) if vals else None
    best = min(vals.values()) if vals else None
    return {"rounds": chosen, "mean_s": means, "winner": winner,
            "advise_decisions": decisions,
            "learned_speedup": round(worst / best, 2) if best else None}
