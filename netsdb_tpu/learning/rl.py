"""Deep-RL placement learner — the reference's Lachesis DRL mode.

The reference optionally routes placement decisions through a separate
TensorFlow A3C process: the C++ ``RLClient`` (``src/selfLearning/
headers/RLClient.h:18-38``) sends a JSON state vector + last reward over
TCP and receives an action index from ``scripts/pangeaDeepRL/
rlServer.py`` (state dim ``S_DIM = 4*K + 7``, action space ``A_DIM =
K + 1`` — K candidate partition lambdas plus "no partition";
actor/critic nets in ``a3c.py``; enabled by
``-DAPPLY_REINFORCEMENT_LEARNING``). Here the action space is the K
candidates without the extra "no partition" arm: on a mesh some
sharding is always chosen, so opting out isn't an action. The DRL placement optimizer
(``DRLBasedDataPlacementOptimizerForLoadJob.h``) builds the state from
job-history stats for each candidate.

Here the learner is in-process (single-controller — no socket hop to
ourselves): an actor-critic with a linear softmax policy and linear
value baseline over the same state layout (per-candidate feature
blocks + global features), trained online from measured wall-time
rewards. NumPy, not JAX: the nets are a few hundred parameters and run
on the host between jobs — putting them on the TPU would cost more in
dispatch than the math. :class:`DRLPlacementAdvisor` is a drop-in
alternative to the frequency-based
:class:`~netsdb_tpu.learning.advisor.PlacementAdvisor`.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from netsdb_tpu.learning.advisor import PlacementCandidate
from netsdb_tpu.learning.history import HistoryDB, get_history_db

# Per-candidate feature block size and global feature count — same state
# layout as the reference server (S_DIM = PER_CANDIDATE*K + GLOBAL).
PER_CANDIDATE = 4
GLOBAL = 7


def state_dim(num_candidates: int) -> int:
    return PER_CANDIDATE * num_candidates + GLOBAL


class ActorCritic:
    """Linear softmax policy + linear value baseline, REINFORCE-with-
    baseline updates (the reference's a3c.py actor/critic pair, minus
    the asynchrony — decisions arrive one at a time here anyway)."""

    def __init__(self, state_dim: int, num_actions: int,
                 actor_lr: float = 0.05, critic_lr: float = 0.1,
                 entropy_beta: float = 0.01, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.w_pi = rng.normal(0, 0.01, (num_actions, state_dim))
        self.b_pi = np.zeros(num_actions)
        self.w_v = np.zeros(state_dim)
        self.b_v = 0.0
        self.actor_lr = actor_lr
        self.critic_lr = critic_lr
        self.entropy_beta = entropy_beta
        self.rng = rng

    def policy(self, state: np.ndarray) -> np.ndarray:
        logits = self.w_pi @ state + self.b_pi
        logits -= logits.max()
        p = np.exp(logits)
        return p / p.sum()

    def value(self, state: np.ndarray) -> float:
        return float(self.w_v @ state + self.b_v)

    def act(self, state: np.ndarray, explore: bool = True) -> int:
        p = self.policy(state)
        if explore:
            return int(self.rng.choice(len(p), p=p))
        return int(np.argmax(p))

    def learn(self, state: np.ndarray, action: int, reward: float) -> None:
        """One-step advantage update: A = r - V(s); ∇logπ(a|s)·A for the
        actor (+entropy bonus), squared-error for the critic."""
        state = np.asarray(state, np.float64)
        # normalized-LMS step scale: keeps the linear heads stable for
        # any O(1) lr regardless of state magnitude
        norm = 1.0 + float(state @ state)
        adv = np.clip(reward - self.value(state), -5.0, 5.0)
        p = self.policy(state)
        # d logits = (onehot(a) - p) * adv  +  entropy gradient
        grad_logits = -p * adv
        grad_logits[action] += adv
        ent_grad = -p * (np.log(p + 1e-12) + 1.0)  # d entropy / d logits
        ent_grad -= p * ent_grad.sum()
        grad_logits += self.entropy_beta * ent_grad
        self.w_pi += (self.actor_lr / norm) * np.outer(grad_logits, state)
        self.b_pi += self.actor_lr * grad_logits
        self.w_v += (self.critic_lr / norm) * adv * state
        self.b_v += self.critic_lr * adv


def build_state(candidate_stats: Sequence[Sequence[float]],
                global_stats: Sequence[float]) -> np.ndarray:
    """Assemble the state vector: K blocks of PER_CANDIDATE features
    (e.g. candidate's historical mean time, run count, data volume,
    recency) then GLOBAL features (set size, page count, …), matching
    the reference layout. Blocks are zero-padded/truncated."""
    parts: List[float] = []
    for s in candidate_stats:
        block = list(s)[:PER_CANDIDATE]
        block += [0.0] * (PER_CANDIDATE - len(block))
        parts += block
    g = list(global_stats)[:GLOBAL]
    g += [0.0] * (GLOBAL - len(g))
    return np.asarray(parts + g, np.float64)


class DRLPlacementAdvisor:
    """Choose a placement candidate for a job with the actor-critic,
    rewarding measured speed — the DRL counterpart of
    :class:`~netsdb_tpu.learning.advisor.PlacementAdvisor` (reference
    ``DRLBasedDataPlacementOptimizerForLoadJob.h``). Reward is
    ``-elapsed / reference_time`` so it is scale-free across jobs."""

    def __init__(self, candidates: Sequence[PlacementCandidate],
                 db: Optional[HistoryDB] = None, seed: int = 0,
                 actor_lr: float = 0.05, critic_lr: float = 0.1):
        if not candidates:
            raise ValueError("need at least one candidate")
        self.candidates = list(candidates)
        self.db = db or get_history_db()
        self.net = ActorCritic(state_dim(len(candidates)),
                               len(self.candidates),
                               actor_lr=actor_lr, critic_lr=critic_lr,
                               seed=seed)
        self._ref_time: Dict[str, float] = {}

    # --- state from history ------------------------------------------
    def _state(self, job_name: str) -> np.ndarray:
        cand_stats = []
        runs = self.db.runs(job_name)
        total = max(len(runs), 1)
        for c in self.candidates:
            mine = [r for r in runs if r["config"] == c.label]
            mean_t = self.db.mean_elapsed(job_name, c.label)
            ref = self._ref_time.get(job_name)
            cand_stats.append([   # all features bounded O(1): the linear
                                  # heads need comparable scales to stay stable
                math.tanh(mean_t / ref) if (mean_t is not None and ref) else 0.0,
                len(mine) / total,
                math.log2(max(float(np.prod(c.mesh_shape)), 1.0)) / 8.0,
                1.0 if mine else 0.0,
            ])
        global_stats = [math.tanh(len(runs) / 10.0),
                        math.log2(max(len(self.candidates), 1)) / 4.0,
                        1.0 if self._ref_time.get(job_name) else 0.0,
                        0.0, 0.0, 0.0, 0.0]
        return build_state(cand_stats, global_stats)

    # --- RLClient-compatible surface ---------------------------------
    def choose(self, job_name: str, explore: bool = True,
               ) -> PlacementCandidate:
        return self.candidates[self.net.act(self._state(job_name), explore)]

    def record(self, job_name: str, candidate: PlacementCandidate,
               elapsed_s: float) -> None:
        """Report the measured time: reward the policy and persist the
        run to the history DB (the reference writes RUN_STAT rows)."""
        state = self._state(job_name)  # state as seen at decision time
        # first measurement anchors the scale; guard zero (cached result /
        # coarse timer) so the division and the cached ref stay finite
        ref = self._ref_time.setdefault(job_name, elapsed_s or 1e-9)
        reward = -elapsed_s / ref
        action = self.candidates.index(candidate)
        self.net.learn(state, action, reward)
        self.db.record(job_name, plan_key="", elapsed_s=elapsed_s,
                       config_label=candidate.label)

    def measure_and_choose(self, job_name: str,
                           run: Callable[[PlacementCandidate], float],
                           rounds: int = 12) -> PlacementCandidate:
        """Explore/learn loop, then return the greedy choice — the
        'first runs slow, later runs fast' behavior the reference's
        experiments report (documentation.md:5-10)."""
        for _ in range(rounds):
            cand = self.choose(job_name, explore=True)
            self.record(job_name, cand, run(cand))
        return self.choose(job_name, explore=False)
