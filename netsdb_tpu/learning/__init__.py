from netsdb_tpu.learning.history import HistoryDB, record_job, set_history_db
from netsdb_tpu.learning.advisor import PlacementAdvisor

__all__ = ["HistoryDB", "record_job", "set_history_db", "PlacementAdvisor"]
