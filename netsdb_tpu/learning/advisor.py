"""Placement advisor — Lachesis without the RL server.

The reference chooses data-placement (partition lambda + page size) for
new sets from job history, via a rule-based frequency optimizer or a
deep-RL server (``src/selfLearning/headers/
RuleBasedDataPlacementOptimizerForLoadJob.h``,
``DRLBasedDataPlacementOptimizerForLoadJob.h``, Python A3C
``scripts/pangeaDeepRL/rlServer.py``). On TPU the decision variable is
the sharding config (mesh shape + PartitionSpecs per set role), and the
reward is measured wall time — so the advisor is an explore/exploit
bandit over candidate configs backed by the history DB: try each
candidate once, then serve the best known, re-exploring stale arms.
The reference's separate-process RL loop is deliberately not
reproduced; measured-history selection is what its own experiments
showed mattered (documentation.md:5-10 — the win comes from reusing
the learned placement, not the learner).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from netsdb_tpu.learning.history import HistoryDB, get_history_db


@dataclasses.dataclass(frozen=True)
class PlacementCandidate:
    """One sharding configuration, e.g. mesh (4,2) with batch on data.

    ``specs`` maps a set role to its decision value: a block-shape
    tuple under ``"block"``, and/or a
    :class:`~netsdb_tpu.parallel.placement.Placement` under
    ``"placement"`` (or a specific set name) — ``Client.create_set``
    applies the latter as the set's mesh sharding, making distribution
    itself an arm of the bandit."""

    label: str
    mesh_shape: tuple
    specs: Dict[str, object]  # set-role → block tuple or Placement


def fusion_candidates() -> tuple:
    """Fusion on/off as advisor ARMS — the plan-compilation decision
    exposed to the same explore/exploit bandit that learns placements
    (``arm.specs["plan_fusion"]`` is applied to the executing client's
    ``config.plan_fusion`` by the A/B harness,
    :func:`~netsdb_tpu.learning.ab_bench.bench_fusion_ab`).  The cost
    model in ``plan/fusion.py`` decides WHICH regions fuse; these arms
    let measured wall time decide WHETHER fusing pays for a given job
    at all — the never-fuse/always-fuse comparison *Fast and Fusiest*
    (arxiv 2602.15166) shows a mapper must win against."""
    return (
        PlacementCandidate("fusion_on", (1,), {"plan_fusion": True}),
        PlacementCandidate("fusion_off", (1,), {"plan_fusion": False}),
    )


def mapper_candidates() -> tuple:
    """The fusion MAPPER choice (optimal DP vs PR 10's greedy) as
    advisor arms (``arm.specs["fusion_mapper"]`` applied to the
    executing client's ``config.fusion_mapper`` by
    :func:`~netsdb_tpu.learning.ab_bench.bench_mapper_ab`).  The DP is
    exact under its cost model, but the cost model is learned — so
    whether its splits actually beat greedy whole-run fusion on a
    given plan SHAPE is a measured decision, recorded per job the same
    way placements are."""
    return (
        PlacementCandidate("mapper_optimal", (1,),
                           {"fusion_mapper": "optimal"}),
        PlacementCandidate("mapper_greedy", (1,),
                           {"fusion_mapper": "greedy"}),
    )


def rebalance_candidates() -> tuple:
    """Live shard rebalancing on/off as advisor arms
    (``arm.specs["rebalance"]`` applied to the serving pool's
    ``config.rebalance`` by
    :func:`~netsdb_tpu.learning.ab_bench.bench_rebalance_ab`).  The
    skew detector in ``serve/rebalance.py`` decides WHAT to move;
    these arms let measured routed throughput decide WHETHER moving
    pays for a given traffic mix — the observe → propose → measure →
    commit-or-revert loop of the self-rebalancing placement design,
    with the advisor's history DB as its memory."""
    return (
        PlacementCandidate("rebalance_on", (1,), {"rebalance": True}),
        PlacementCandidate("rebalance_frozen", (1,),
                           {"rebalance": False}),
    )


class PlacementAdvisor:
    def __init__(self, candidates: Sequence[PlacementCandidate],
                 db: Optional[HistoryDB] = None,
                 explore_threshold: int = 1):
        if not candidates:
            raise ValueError("need at least one candidate")
        self.candidates = list(candidates)
        self.db = db or get_history_db()
        self.explore_threshold = explore_threshold

    def _runs_for(self, job_name: str, label: str) -> int:
        return sum(1 for r in self.db.runs(job_name) if r["config"] == label)

    def choose(self, job_name: str) -> PlacementCandidate:
        """Unexplored candidate first; otherwise the best mean elapsed."""
        for c in self.candidates:
            if self._runs_for(job_name, c.label) < self.explore_threshold:
                return c
        best, best_t = None, float("inf")
        for c in self.candidates:
            t = self.db.mean_elapsed(job_name, c.label)
            if t is not None and t < best_t:
                best, best_t = c, t
        return best or self.candidates[0]

    def record(self, job_name: str, candidate: PlacementCandidate,
               elapsed_s: float) -> None:
        self.db.record(job_name, plan_key="", elapsed_s=elapsed_s,
                       config_label=candidate.label)

    def measure_and_choose(self, job_name: str,
                           run: Callable[[PlacementCandidate], float],
                           rounds: Optional[int] = None) -> PlacementCandidate:
        """Drive the explore loop: run each candidate (run() returns
        elapsed seconds), then return the winner — the reference's
        'first run slow, later runs fast' self-learning behavior
        (documentation.md:5-10)."""
        if rounds is None:  # enough to explore every arm to threshold
            rounds = len(self.candidates) * self.explore_threshold
        for _ in range(rounds):
            cand = self.choose(job_name)
            if self._runs_for(job_name, cand.label) >= self.explore_threshold:
                break  # all explored; cand is the winner
            elapsed = run(cand)
            self.record(job_name, cand, elapsed)
        return self.choose(job_name)
