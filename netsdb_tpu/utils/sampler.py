"""Sampling utilities — reference ``src/utilities/headers/Sampler.h``.

The reference uses these to Bernoulli-sample initial centroids for
KMeans/GMM with a probabilistic lower-bound guarantee
(``TestKMeansMLLibCompliant.cc:462-505``, ``TestGmmLazy.cc:425``): pick a
fraction such that a Bernoulli sample of ``total`` items contains at
least ``sample_size_lower_bound`` items with probability ~1-1e-4,
re-sampling if it comes up short, then Fisher-Yates shuffle and truncate.

``SafeResult`` (``src/utilities/headers/SafeResult.h``), the reference's
error-or-value wrapper, has no analogue here on purpose: Python
exceptions are the idiomatic equivalent and are what every API in this
framework raises.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np


def num_std(sample_size_lower_bound: int) -> float:
    """Standard-deviation multiplier for the with-replacement bound
    (``Sampler.h:14-22``): tighter for larger sample sizes."""
    if sample_size_lower_bound < 6.0:
        return 12.0
    if sample_size_lower_bound < 16.0:
        return 9.0
    return 6.0


def compute_fraction_for_sample_size(sample_size_lower_bound: int,
                                     total: int,
                                     with_replacement: bool = False) -> float:
    """Bernoulli fraction guaranteeing >= ``sample_size_lower_bound``
    samples out of ``total`` w.h.p. (``Sampler.h:25-41``)."""
    if total <= 0:
        raise ValueError(f"total must be positive, got {total}")
    n = float(sample_size_lower_bound)
    if with_replacement:
        return max(n + num_std(sample_size_lower_bound) * math.sqrt(n),
                   1e-15) / total
    fraction = n / total
    delta = 1e-4
    gamma = -math.log(delta) / total
    return min(1.0, max(1e-10, fraction + gamma +
                        math.sqrt(gamma * gamma + 2 * gamma * fraction)))


def randomize_in_place(items: List, seed: Optional[int] = None) -> None:
    """Fisher-Yates shuffle (``Sampler.h:44-53``)."""
    rng = np.random.default_rng(seed)
    for i in range(len(items) - 1, -1, -1):
        j = int(rng.integers(0, i + 1))
        items[i], items[j] = items[j], items[i]


def bernoulli_sample_rows(points: np.ndarray, fraction: float,
                          seed: Optional[int] = None) -> np.ndarray:
    """Row-wise Bernoulli sample — the ``KMeansSampleSelection`` UDF
    (each point kept independently with probability ``fraction``)."""
    rng = np.random.default_rng(seed)
    mask = rng.random(points.shape[0]) < fraction
    return points[mask]


def sample_k_distinct(points: np.ndarray, k: int,
                      seed: Optional[int] = None) -> np.ndarray:
    """The full MLLib-compliant init (``TestKMeansMLLibCompliant.cc:
    462-530``): Bernoulli-sample until >= k rows, shuffle, truncate to
    k, and drop duplicates (the reference's distinct pass; the returned
    model may therefore have < k rows, as there)."""
    n = points.shape[0]
    if n == 0:
        raise ValueError("cannot sample from an empty point set")
    fraction = compute_fraction_for_sample_size(k, n, with_replacement=False)
    rng = np.random.default_rng(seed)
    samples = np.empty((0, points.shape[1]), dtype=points.dtype)
    while samples.shape[0] < k:
        take = bernoulli_sample_rows(points, fraction,
                                     seed=int(rng.integers(0, 2**31)))
        samples = np.concatenate([samples, take], axis=0)
    idx = list(range(samples.shape[0]))
    randomize_in_place(idx, seed=int(rng.integers(0, 2**31)))
    samples = samples[np.asarray(idx[:k])]
    return np.unique(samples, axis=0)
