"""Small concurrency primitives shared across the storage layer."""

from __future__ import annotations

import contextlib
import threading


class RWLock:
    """Readers-preference shared/exclusive lock.

    The arena pin-refcount pattern (``native/pagestore.cpp``) at Python
    granularity: many concurrent page streams may read one paged
    relation while mutations (append / drop) wait for the readers to
    drain. Readers-preference deliberately: a stream that opens a
    nested stream of the same relation (grace-hash self-probe) must not
    deadlock behind a queued writer, and at this layer's scale writer
    starvation is not a realistic load.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    @contextlib.contextmanager
    def read(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()
