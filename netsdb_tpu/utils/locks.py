"""Concurrency primitives shared across the storage layer, plus the
lockdep-style runtime lock-order witness.

The witness is the dynamic twin of the static ``lock-order`` lint
rule (``netsdb_tpu/analysis/rules/locking.py``): every
:class:`TrackedLock`/:class:`TrackedRLock`/named :class:`RWLock`
acquisition, while enabled, records *rank* edges (held-lock → newly-
acquired-lock) into one bounded process-wide graph and checks each new
edge for a cycle — i.e. an AB/BA inversion that is a potential
deadlock even if this run never interleaved it.  Linux's lockdep does
exactly this for kernel locks; here the ranks are lock *names* (every
per-set serve lock is one rank; every relation RWLock is one rank per
OWNER CLASS — ``PagedObjects.rw``, ``PagedColumns.rw``,
``_PagedMatrix.rw``), so the graph stays tiny and instance churn
can't grow it.

Mode-aware like lockdep's recursive-read handling: RWLock acquisitions
record their share mode, and a rank cycle whose RWLock participation
is read-on-both-cycle-edges is SUPPRESSED (counted, not raised) — the
readers-preference semantics make it unrealizable (a read never blocks
while another reader holds the lock, because waiting writers do not
gate new readers).  This is what lets the supported append-while-
iterating pattern (stream holds ``rw.read`` → re-enters the store)
coexist with the store's own ``lock → rw.read`` ingest edges without
false alarms, while a genuine ``rw.write`` inversion still fires.

Cost model: with the witness DISABLED (the default), every tracked
acquisition pays one module-global read and an ``is None`` check on
top of the raw ``threading`` primitive — nothing allocates.  Enabled
(``config.lock_witness``, or the test suite's conftest), each
acquisition walks the thread's held stack (depth ≤ 3 in practice) and
consults the edge set; ``micro_bench --lint-overhead`` pins the
enabled cost < 2% on the staged fold stream.

Findings export through the obs registry: ``analysis.lock_edges``
(gauge: distinct rank edges observed) and ``analysis.violations``
(counter: cycles detected).  ``raise_on_cycle`` mode raises
:class:`LockOrderViolation` naming both acquisition sites — the
deterministic-test mode; record mode (the conftest default) collects
violations for a session-end gate.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """A runtime lock-acquisition-order cycle (potential deadlock)."""


#: per-thread held-rank stack, shared across witness instances so
#: acquire/release pairs spanning a witness swap stay balanced
_HELD_TLS = threading.local()


class LockWitness:
    """Bounded cross-thread acquisition-order graph with cycle
    detection — one per process while enabled."""

    def __init__(self, max_edges: int = 4096, max_violations: int = 64,
                 raise_on_cycle: bool = False):
        self._mu = threading.Lock()
        self.max_edges = max_edges
        self.raise_on_cycle = raise_on_cycle
        #: (held_rank, acquired_rank) → {"sites": (held_site,
        #: acquired_site) of the first sighting, "modes": set of
        #: (held_mode, acquired_mode) pairs observed — 'r' shared /
        #: 'w' exclusive}
        self.edges: Dict[Tuple[str, str], Dict[str, object]] = {}
        self._succ: Dict[str, Set[str]] = {}
        self.violations: List[dict] = []
        self._max_violations = max_violations
        self.dropped_edges = 0
        #: rank cycles realized ONLY through shared-mode (read/read)
        #: RWLock participation — unrealizable as deadlocks under the
        #: readers-preference semantics (waiting writers never block
        #: new readers), counted but not violations: lockdep's
        #: recursive-read exemption
        self.read_cycles_suppressed = 0
        #: total tracked acquisitions observed (unsynchronized tally —
        #: the lint-overhead bench's deterministic-bound multiplier)
        self.acquisitions = 0

    # --- per-thread held stack ---------------------------------------
    # The stack is MODULE-level (shared by every witness instance):
    # acquire/release pairs that span a witness_scope() swap — a
    # background thread acquiring under the session witness and
    # releasing while a test's scoped witness is installed — must
    # still balance, or the restored witness would carry stale held
    # entries and manufacture phantom edges forever after.
    @staticmethod
    def _held() -> List[Tuple[str, str, str]]:
        stack = getattr(_HELD_TLS, "stack", None)
        if stack is None:
            stack = _HELD_TLS.stack = []
        return stack

    def note_acquire(self, rank: str, site: str,
                     mode: str = "w") -> None:
        self.acquisitions += 1
        held = self._held()
        if any(r == rank for r, _, _ in held):
            # re-entrant / same-rank nesting (RLock, reader-preference
            # RWLock self-probe): no self-edges
            held.append((rank, site, mode))
            return
        new_edges = list(held)
        held.append((rank, site, mode))
        if not new_edges:
            return
        try:
            with self._mu:
                for h_rank, h_site, h_mode in new_edges:
                    key = (h_rank, rank)
                    rec = self.edges.get(key)
                    if rec is not None:
                        rec["modes"].add((h_mode, mode))
                        continue
                    if len(self.edges) >= self.max_edges:
                        self.dropped_edges += 1
                        continue
                    # cycle check BEFORE inserting: a path rank →*
                    # h_rank means some thread orders them the other way
                    path = self._path(rank, h_rank)
                    self.edges[key] = {"sites": (h_site, site),
                                       "modes": {(h_mode, mode)}}
                    self._succ.setdefault(h_rank, set()).add(rank)
                    self._export_edge_count()  # new edges are rare
                    if path is not None:
                        self._check_cycle(h_rank, rank, h_site, site,
                                          path)
        except LockOrderViolation:
            # raise mode: the CALLER undoes the underlying primitive;
            # undo our held-stack push so the witness stays balanced
            # (a detector of potential deadlocks must never wedge the
            # lock it just flagged)
            self.note_release(rank)
            raise

    def note_release(self, rank: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == rank:
                del held[i]
                return

    def _export_edge_count(self) -> None:
        """Mirror the edge count into the registry gauge AT INSERTION
        (a collector-time set would land one snapshot late)."""
        try:
            from netsdb_tpu.obs.metrics import registry

            registry().gauge("analysis.lock_edges").set(len(self.edges))
        except Exception:  # noqa: BLE001 — obs must never break locking
            pass

    # --- graph -------------------------------------------------------
    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A src →* dst path in the current edge set, else None.
        Iterative DFS; the graph is rank-sized (tens of nodes)."""
        if src == dst:
            return [src]
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._succ.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _check_cycle(self, a: str, b: str, a_site: str, b_site: str,
                     path: List[str]) -> None:
        """``path`` runs b →* a; with the new a→b edge that closes a
        rank cycle.  Suppress it when some lock in the cycle
        participates ONLY in shared mode on both its cycle edges:
        under readers-preference, a read acquisition can never block
        while another reader holds the lock (waiting writers do not
        gate new readers), so no interleaving realizes the deadlock
        the cycle suggests — lockdep's recursive-read exemption."""
        cycle_nodes = path + [b]  # b, ..., a, b
        cycle_edges = list(zip(cycle_nodes[:-1], cycle_nodes[1:]))
        for node in path:  # every node; a and b included via path ends
            in_edge = next((e for e in cycle_edges if e[1] == node),
                           None)
            out_edge = next((e for e in cycle_edges if e[0] == node),
                            None)
            if in_edge is None or out_edge is None:
                continue
            in_modes = {m[1] for m in
                        self.edges.get(in_edge, {}).get("modes", ())}
            out_modes = {m[0] for m in
                         self.edges.get(out_edge, {}).get("modes", ())}
            if in_modes == {"r"} and out_modes == {"r"}:
                self.read_cycles_suppressed += 1
                return
        self._violation(a, b, a_site, b_site, path)

    def _violation(self, a: str, b: str, a_site: str, b_site: str,
                   path: List[str]) -> None:
        rec = {
            "cycle": path + [b],
            "edge": (a, b),
            "sites": {a: a_site, b: b_site},
            "reverse_sites": {
                y: self.edges.get((x, y),
                                  {"sites": ("?", "?")})["sites"][1]
                for x, y in zip(path, path[1:])},
            "thread": threading.current_thread().name,
        }
        if len(self.violations) < self._max_violations:
            self.violations.append(rec)
        try:  # export through the central registry (never fatal)
            from netsdb_tpu.obs.metrics import registry

            registry().counter("analysis.violations").inc()
        except Exception:  # noqa: BLE001 — obs must never break locking
            pass
        if self.raise_on_cycle:
            cyc = " -> ".join(rec["cycle"])
            other = "; ".join(f"{p} acquired at {s}"
                              for p, s in rec["reverse_sites"].items())
            raise LockOrderViolation(
                f"lock-order inversion: acquiring {b!r} at {b_site} "
                f"while holding {a!r} (acquired at {a_site}), but the "
                f"reverse order already exists: cycle {cyc} ({other})")

    def report(self) -> dict:
        with self._mu:
            return {
                "edges": len(self.edges),
                "dropped_edges": self.dropped_edges,
                "acquisitions": self.acquisitions,
                "read_cycles_suppressed": self.read_cycles_suppressed,
                "violations": list(self.violations),
            }

    def _export_edges_locked(self) -> List[dict]:
        return [{"held": a, "acquired": b,
                 "sites": list(rec["sites"]),
                 "modes": sorted("".join(m) for m in rec["modes"])}
                for (a, b), rec in sorted(self.edges.items())]

    def export_edges(self) -> List[dict]:
        """The recorded rank edges as plain JSON-safe records — the
        input half of ``cli lint --witness-coverage``, which diffs
        this dynamic graph against the static lock-order graph
        (ranks here and tokens there share one grammar, so the diff
        is a set comparison)."""
        with self._mu:
            return self._export_edges_locked()

    def dump(self, path: str) -> None:
        """Write the edge graph (plus run totals) as JSON. The tier-1
        conftest writes one per run when ``NETSDB_WITNESS_DUMP`` is
        set; ``cli lint --witness-coverage <path>`` reads it back."""
        import json

        # one _mu extent for edges AND totals: a dump taken while a
        # live thread still acquires must be self-consistent (the
        # reconciliation report treats it as ground truth)
        with self._mu:
            payload = {
                "edges": self._export_edges_locked(),
                "acquisitions": self.acquisitions,
                "dropped_edges": self.dropped_edges,
                "violations": len(self.violations),
            }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)


#: the process-wide witness; None = disabled (the common case — every
#: tracked acquisition pays exactly this read + an is-None check)
_WITNESS: Optional[LockWitness] = None


def witness() -> Optional[LockWitness]:
    return _WITNESS


def enable_witness(raise_on_cycle: bool = False,
                   max_edges: int = 4096) -> LockWitness:
    """Install (or return the already-installed) process witness."""
    global _WITNESS
    if _WITNESS is None:
        _WITNESS = LockWitness(max_edges=max_edges,
                               raise_on_cycle=raise_on_cycle)
        try:
            from netsdb_tpu.obs.metrics import registry

            registry().register_collector("analysis", _witness_stats)
        except Exception:  # noqa: BLE001 — obs must never break locking
            pass
    else:
        _WITNESS.raise_on_cycle = raise_on_cycle
    return _WITNESS


def disable_witness() -> None:
    global _WITNESS
    _WITNESS = None


@contextlib.contextmanager
def witness_scope(raise_on_cycle: bool = False, max_edges: int = 4096):
    """Temporarily install a FRESH witness and restore the previous
    one on exit — deterministic tests get a private graph without
    clobbering the session-wide witness the conftest installed."""
    global _WITNESS
    prev = _WITNESS
    w = LockWitness(max_edges=max_edges, raise_on_cycle=raise_on_cycle)
    _WITNESS = w
    try:
        yield w
    finally:
        _WITNESS = prev


def _witness_stats() -> dict:
    w = _WITNESS
    if w is None:
        return {"enabled": False}
    rep = w.report()
    return {"enabled": True, "edges": rep["edges"],
            "dropped_edges": rep["dropped_edges"],
            "acquisitions": rep["acquisitions"],
            "read_cycles_suppressed": rep["read_cycles_suppressed"],
            "violations": len(rep["violations"])}


def _call_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class TrackedLock:
    """``threading.Lock`` with a witness rank name.  Drop-in: context
    manager, ``acquire(blocking=, timeout=)``, ``release()``,
    ``locked()``."""

    _factory = staticmethod(threading.Lock)
    __slots__ = ("_lk", "name", "_count")

    def __init__(self, name: str):
        self._lk = self._factory()
        self.name = name
        # recursion depth of the current holder (mutated only while
        # the lock is held, so no extra synchronization): the RLock
        # ``locked()`` probe — try-acquire would succeed reentrantly
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1,
                _site_depth: int = 2) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._count += 1
            w = _WITNESS
            if w is not None:
                try:
                    w.note_acquire(self.name, _call_site(_site_depth))
                except BaseException:
                    # raise-mode violation: hand the lock BACK before
                    # propagating — the detector must never leave the
                    # flagged lock wedged
                    self._count -= 1
                    self._lk.release()
                    raise
        return ok

    def release(self) -> None:
        self._count -= 1
        self._lk.release()
        w = _WITNESS
        if w is not None:
            w.note_release(self.name)

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire(_site_depth=3)
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class TrackedRLock(TrackedLock):
    """``threading.RLock`` with a witness rank name."""

    _factory = staticmethod(threading.RLock)
    __slots__ = ()

    def locked(self) -> bool:  # RLock has no locked() pre-3.12, and a
        # try-acquire probe would succeed reentrantly for the holder
        return self._count > 0


class RWLock:
    """Readers-preference shared/exclusive lock.

    The arena pin-refcount pattern (``native/pagestore.cpp``) at Python
    granularity: many concurrent page streams may read one paged
    relation while mutations (append / drop) wait for the readers to
    drain. Readers-preference deliberately: a stream that opens a
    nested stream of the same relation (grace-hash self-probe) must not
    deadlock behind a queued writer, and at this layer's scale writer
    starvation is not a realistic load.

    ``name`` is the witness RANK (default ``"RWLock"`` — every
    relation lock is one level in the hierarchy; see the module
    docstring).  Read and write acquisitions both witness the same
    rank: the ordering hazard is which LEVEL nests inside which, not
    the share mode.
    """

    def __init__(self, name: str = "RWLock"):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self.name = name

    @contextlib.contextmanager
    def read(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1
        w = _WITNESS
        if w is not None:
            try:
                w.note_acquire(self.name, _call_site(3), mode="r")
            except BaseException:
                with self._cond:  # undo the read before propagating
                    self._readers -= 1
                    if self._readers == 0:
                        self._cond.notify_all()
                raise
        try:
            yield
        finally:
            if w is not None:
                w.note_release(self.name)
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True
        w = _WITNESS
        if w is not None:
            try:
                w.note_acquire(self.name, _call_site(3), mode="w")
            except BaseException:
                with self._cond:  # undo the write before propagating
                    self._writer = False
                    self._cond.notify_all()
                raise
        try:
            yield
        finally:
            if w is not None:
                w.note_release(self.name)
            with self._cond:
                self._writer = False
                self._cond.notify_all()
