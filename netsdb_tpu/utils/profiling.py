"""Tracing / profiling / logging — the reference's observability kit.

Reference: ``-DPROFILING`` wall-clock spans around planning and each
pipeline phase (``QuerySchedulerServer.cc:1336-1341``,
``PipelineStage.cc:1084-1101``), ``CacheStats`` counters, and the
pthread-safe ``PDBLogger`` file logger (``src/pdbServer/headers/
PDBLogger.h``). Here: a StageTimer span collector (always on — spans
are cheap), a ``jax.profiler`` trace context for real device profiles,
and stdlib logging configured PDBLogger-style.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from typing import Dict, Iterator, List, Optional


class StageTimer:
    """Named wall-clock spans with summary stats (the -DPROFILING spans,
    queryable instead of printed)."""

    def __init__(self):
        self.spans: Dict[str, List[float]] = defaultdict(list)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.spans[name].append(time.perf_counter() - t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, times in self.spans.items():
            out[name] = {"count": len(times), "total_s": sum(times),
                         "mean_s": sum(times) / len(times),
                         "max_s": max(times)}
        return out

    def reset(self) -> None:
        self.spans.clear()


# process-global timer used by the executor
GLOBAL_TIMER = StageTimer()


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Capture a device profile viewable in TensorBoard/XProf — the
    capability the reference approximates with printf spans."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


def get_logger(name: str = "netsdb_tpu", level: Optional[str] = None,
               log_file: Optional[str] = None) -> logging.Logger:
    """PDBLogger equivalent: per-component, optionally file-backed."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = (logging.FileHandler(log_file) if log_file
                   else logging.StreamHandler())
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
    if level:
        logger.setLevel(level)
    return logger
