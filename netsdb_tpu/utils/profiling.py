"""Tracing / profiling / logging — the reference's observability kit.

Reference: ``-DPROFILING`` wall-clock spans around planning and each
pipeline phase (``QuerySchedulerServer.cc:1336-1341``,
``PipelineStage.cc:1084-1101``), ``CacheStats`` counters, and the
pthread-safe ``PDBLogger`` file logger (``src/pdbServer/headers/
PDBLogger.h``). Here: a StageTimer span collector (always on — spans
are cheap), a ``jax.profiler`` trace context for real device profiles,
and stdlib logging configured PDBLogger-style.

Query-scoped structured tracing lives in ``netsdb_tpu/obs/`` — the
StageTimer remains as the simple named-span aggregator (per-name
distributions, no query identity) and reports into the central metrics
registry alongside it.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, Iterator, Optional

from netsdb_tpu.obs import metrics as _metrics


class StageTimer:
    """Named wall-clock spans with summary stats (the -DPROFILING
    spans, queryable instead of printed).

    BOUNDED per name: each name keeps exact ``count``/``total_s``/
    ``max_s`` forever, plus a fixed-size sample ring for percentiles
    (:class:`netsdb_tpu.obs.metrics.Histogram`). The old version
    appended every duration to a list — a long-lived daemon timing its
    per-request stages grew that without bound; now a year of spans
    holds the same few KB per name."""

    def __init__(self, max_samples: int = 512):
        self._mu = threading.Lock()
        self._max_samples = max_samples
        self._hists: Dict[str, _metrics.Histogram] = {}

    def _hist(self, name: str) -> _metrics.Histogram:
        with self._mu:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _metrics.Histogram(
                    self._max_samples)
            return h

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._hist(name).observe(time.perf_counter() - t0)

    def sample_count(self, name: str) -> int:
        """Retained samples for ``name`` (≤ ``max_samples`` no matter
        how many spans ran) — the boundedness the tests pin."""
        return self._hist(name).sample_count

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Same shape as always (``count``/``total_s``/``mean_s``/
        ``max_s`` — exact), plus bounded-sample percentiles."""
        with self._mu:
            hists = dict(self._hists)
        out = {}
        for name, h in hists.items():
            s = h.summary()
            if not s["count"]:
                continue
            out[name] = {"count": s["count"], "total_s": s["total"],
                         "mean_s": s["mean"], "max_s": s["max"],
                         "p50_s": s["p50"], "p95_s": s["p95"],
                         "p99_s": s["p99"]}
        return out

    def reset(self) -> None:
        with self._mu:
            self._hists.clear()


# process-global timer used by the executor; its summary reports into
# the central metrics registry (COLLECT_STATS "metrics" → "stages")
GLOBAL_TIMER = StageTimer()
_metrics.REGISTRY.register_collector("stages", GLOBAL_TIMER.summary)


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Capture a device profile viewable in TensorBoard/XProf — the
    capability the reference approximates with printf spans."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def qid_profile_session(qid: str, log_dir: str) -> Iterator[str]:
    """Per-QUERY device profile: a ``jax.profiler`` session keyed by
    the query id, written to ``<log_dir>/<qid>`` — the REAL device
    half of one traced query, joinable with its ``GET_TRACE`` span
    profile by directory name. Opt-in
    (``config.obs_device_profile_dir``) and serialized by the caller
    (jax supports one session per process at a time — the serve layer
    skips, never queues, when one is live). Yields the session
    directory (the value the trace's ``meta.device_profile``
    carries)."""
    import os

    import jax

    path = os.path.join(log_dir, str(qid))
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield path


def get_logger(name: str = "netsdb_tpu", level: Optional[str] = None,
               log_file: Optional[str] = None) -> logging.Logger:
    """PDBLogger equivalent: per-component, optionally file-backed."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = (logging.FileHandler(log_file) if log_file
                   else logging.StreamHandler())
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
    if level:
        logger.setLevel(level)
    return logger
