from netsdb_tpu.utils.profiling import StageTimer, profile_trace, get_logger

__all__ = ["StageTimer", "profile_trace", "get_logger"]
