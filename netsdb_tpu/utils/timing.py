"""Device-throughput timing over a high-latency controller link.

Per-dispatch wall times through the axon tunnel carry tens-to-hundreds
of ms of NOISY fixed overhead, so steady-state device time is measured
as the SLOPE between a short and a long on-device loop: the caller
wraps its workload in a ``lax.scan`` whose carry depends on each
iteration's full output (so XLA can neither hoist the body nor
slice-push it down to a single element), and the fixed dispatch+sync
overhead cancels in the subtraction.

Used by ``bench.py`` (flagship FF metric) and
``netsdb_tpu/workloads/conv_bench.py`` — one implementation so the
protocol cannot diverge between benchmarks.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


# --- clock discipline (serve layer) ------------------------------------
# Deadlines and intervals MUST use the monotonic clock: time.time() can
# jump (NTP step, manual set), which once broke the serve layer's 30 s
# follower dial-retry loop. tests/test_static_checks.py enforces that
# serve/ never calls time.time(); the one legitimate wall-clock use —
# a human-readable timestamp in job records — goes through wall_now()
# here so the intent is explicit at every call site.

def wall_now() -> float:
    """Wall-clock seconds since the epoch — DISPLAY ONLY (job-record
    timestamps, logs). Never compare this against a deadline; use
    :func:`deadline_after`/:func:`seconds_left` instead."""
    return time.time()


def deadline_after(seconds: float) -> float:
    """A deadline ``seconds`` from now on the monotonic clock."""
    return time.monotonic() + seconds


def seconds_left(deadline: float) -> float:
    """Seconds remaining until a :func:`deadline_after` deadline
    (negative once expired)."""
    return deadline - time.monotonic()


def device_seconds(run: Callable[[int], None], lo: int = 4, hi: int = 20,
                   **kw) -> Optional[float]:
    """Seconds-per-iteration via :func:`scan_slope_seconds`, or None when
    the signal never clears controller noise (callers must then fall
    back to a wall-time upper bound, never a clamped denominator)."""
    res = scan_slope_seconds(run, lo=lo, hi=hi, **kw)
    return res["seconds_per_iter"] if not res["below_noise"] else None


def scan_slope_seconds(run: Callable[[int], None], lo: int, hi: int,
                       repeats: int = 3, max_escalations: int = 4,
                       min_delta_seconds: float = 0.2) -> Dict[str, object]:
    """Median seconds-per-iteration of ``run(n)`` (an n-iteration
    on-device loop that blocks until complete).

    The slope is only trustworthy when the long loop takes measurably
    longer than the short one RELATIVE TO controller noise (~tens of
    ms): if the median (t_hi - t_lo) delta is below
    ``min_delta_seconds`` — or non-positive — the loop lengths are
    escalated (``hi`` x4, recompiling) up to ``max_escalations`` times.
    Without this, a fast kernel measured with short loops reports
    noise as throughput (observed: an LSTM "measured" at 6x the chip's
    peak FLOP/s with hi=20). If escalation runs out,
    ``below_noise=True`` is returned and ``seconds_per_iter`` is None
    so callers fall back to a wall-time upper bound instead of
    reporting an astronomical number from a noise denominator.
    """
    for attempt in range(max_escalations + 1):
        for n in (lo, hi):
            run(n)  # compile + warm this pair of lengths
        deltas: List[float] = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            run(lo)
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            run(hi)
            t_hi = time.perf_counter() - t0
            deltas.append(t_hi - t_lo)
        med_delta = sorted(deltas)[len(deltas) // 2]
        if med_delta >= min_delta_seconds:
            return {"seconds_per_iter": med_delta / (hi - lo),
                    "slopes": [d / (hi - lo) for d in deltas],
                    "below_noise": False, "lo": lo, "hi": hi}
        hi *= 4
    return {"seconds_per_iter": None,
            "slopes": [d / (hi // 4 - lo) for d in deltas],
            "below_noise": True, "lo": lo, "hi": hi // 4}
