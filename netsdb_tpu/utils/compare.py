"""Structural result comparison shared by the selftest CLI and the
engine-parity tests — one definition of "the two engines agree" so
tolerances cannot silently diverge between CI and the shipped selftest.
"""

from __future__ import annotations


def structurally_close(a, b, rtol: float = 2e-4, atol: float = 2e-3) -> bool:
    """Recursive equality over dict/list/tuple structures with float
    tolerance at the leaves (f32 device results vs f64 host oracles)."""
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(
            structurally_close(a[k], b[k], rtol, atol) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            structurally_close(x, y, rtol, atol) for x, y in zip(a, b))
    if isinstance(a, float) or isinstance(b, float):
        return abs(float(a) - float(b)) <= max(rtol * abs(float(b)), atol)
    return a == b
