"""Operator CLI — the reference's binaries + scripts layer, collapsed.

netsDB ships ``pdb-cluster``/``pdb-server`` binaries and a zoo of launch
scripts (``src/mainServer``, ``scripts/startMaster.sh``,
``startWorkers.sh``, ``startPseudoCluster.py`` — SURVEY layer 17).
Single-controller JAX needs no resident servers, so the operator surface
is one CLI:

    python -m netsdb_tpu info                 # cluster/devices (ResourceManager)
    python -m netsdb_tpu bench                # the benchmark harness
    python -m netsdb_tpu pdml PROG.pdml       # run a LA DSL program
    python -m netsdb_tpu demo-ff [...]        # FFTest.cc equivalent
    python -m netsdb_tpu tpch [--query q01]   # TPC-H demo queries
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _cmd_info(args) -> int:
    import jax

    from netsdb_tpu.parallel.distributed import cluster_info

    info = cluster_info()
    info["backend"] = jax.default_backend()
    print(json.dumps(info, indent=2))
    return 0


def _cmd_bench(args) -> int:
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    bench.main()
    return 0


def _cmd_pdml(args) -> int:
    from netsdb_tpu.dsl import run_pdml

    with open(args.file) as f:
        text = f.read()
    env = run_pdml(text)
    for name, tensor in env.items():
        print(f"{name}: shape={tensor.shape} block={tensor.meta.block_shape}")
        if args.print_values:
            import numpy as np

            print(np.asarray(tensor.to_dense()))
    return 0


def _cmd_demo_ff(args) -> int:
    import numpy as np

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.models.ff import FFModel

    client = Client(Configuration())
    block = (args.block, args.block)
    model = FFModel(block=block)
    model.setup(client)
    model.load_random_weights(client, args.features, args.hidden, args.labels)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((args.batch, args.features)).astype(np.float32)
    model.load_inputs(client, x)
    t0 = time.perf_counter()
    out = model.inference(client)
    probs = np.asarray(out.to_dense())
    dt = time.perf_counter() - t0
    print(json.dumps({
        "batch": args.batch, "features": args.features,
        "hidden": args.hidden, "labels": args.labels,
        "output_shape": list(probs.shape),
        "cols_sum_to_one": bool(np.allclose(probs.sum(0), 1.0, atol=1e-3)),
        "elapsed_s": round(dt, 4),
    }))
    return 0


def _cmd_tpch(args) -> int:
    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.workloads import tpch

    client = Client(Configuration())
    tpch.load_tables(client, scale=args.scale)
    queries = [args.query] if args.query else list(tpch.QUERIES)
    for q in queries:
        t0 = time.perf_counter()
        rows = tpch.run_query(client, q)
        dt = time.perf_counter() - t0
        n = len(rows) if hasattr(rows, "__len__") else 1
        print(f"{q}: {n} rows in {dt*1e3:.1f} ms")
        if args.print_values:
            print(rows)
    return 0


def _cmd_tpch_bench(args) -> int:
    """Columnar TPC-H on device at dbgen scale — the perf counterpart of
    ``tpch`` (reference baseline: BASELINE.md query times)."""
    import json

    from netsdb_tpu.relational import bench

    res = bench.main(sf=args.sf, iters=args.iters)
    print(json.dumps(res, indent=2))
    return 0


def _cmd_transformer_bench(args) -> int:
    from netsdb_tpu.workloads.transformer_bench import bench_transformer_layer

    print(json.dumps(bench_transformer_layer(
        seq_lens=tuple(args.seq), batch=args.batch, embed=args.embed,
        heads=args.heads)))
    return 0


def _cmd_reddit_bench(args) -> int:
    from netsdb_tpu.workloads.reddit_columnar import bench_label_propagation

    print(json.dumps(bench_label_propagation(rows=args.rows,
                                             n_authors=args.authors)))
    return 0


def _cmd_ooc_bench(args) -> int:
    from netsdb_tpu.relational.outofcore import bench_out_of_core

    print(json.dumps(bench_out_of_core(rows=args.rows,
                                       pool_bytes=args.pool_mb << 20)))
    return 0


def _cmd_paged_api_bench(args) -> int:
    from netsdb_tpu.relational.outofcore import bench_paged_set_api

    print(json.dumps(bench_paged_set_api(rows=args.rows,
                                         pool_bytes=args.pool_mb << 20),
                     default=str))
    return 0


def _cmd_lsh_bench(args) -> int:
    from netsdb_tpu.dedup.lsh import bench_lsh_zoo

    print(json.dumps(bench_lsh_zoo(n_models=args.models)))
    return 0


def _cmd_ab_bench(args) -> int:
    from netsdb_tpu.learning.ab_bench import bench_placement_ab

    print(json.dumps(bench_placement_ab(rounds=args.rounds,
                                        advisor_kind=args.advisor)))
    return 0


def _cmd_autotune(args) -> int:
    """Measure the physical-strategy crossovers on the live backend and
    persist them per device kind (the planner reads them back;
    ``netsdb_tpu.relational.tuning``)."""
    from netsdb_tpu.config import DEFAULT_CONFIG, enable_compilation_cache
    from netsdb_tpu.relational import tuning

    # dozens of (strategy, size) probe programs: without the persistent
    # compile cache each one cold-compiles over the tunnel (~10 s each)
    # and the sweep takes tens of minutes instead of a few
    enable_compilation_cache(DEFAULT_CONFIG)

    measured = tuning.autotune(persist=not args.no_persist)
    print(json.dumps({"device_kind": tuning.device_kind(), **measured}))
    return 0


def _cmd_selftest(args) -> int:
    """Scripted integration sequence — the reference's
    ``scripts/integratedTests.py:72-240`` (boot pseudo-cluster, then run
    selection, aggregation, LDA, FF, LSTM drivers checking exit codes).
    Here: the same workload sequence in-process, each step validated."""
    import numpy as np

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration

    client = Client(Configuration())
    rng = np.random.default_rng(0)
    failures = []

    def check(cond, what):
        # explicit raise (not assert): must still fail under python -O,
        # since this command's whole job is exit-code-checked validation
        if not cond:
            raise RuntimeError(f"check failed: {what}")

    def step(name, fn):
        t0 = time.perf_counter()
        try:
            fn()
            print(f"[ok]   {name} ({time.perf_counter() - t0:.2f}s)")
        except Exception as e:  # mirror exit-code checking: keep going
            failures.append(name)
            print(f"[FAIL] {name}: {type(e).__name__}: {e}")

    def selection():  # bin/test74-style selection over an object set
        from netsdb_tpu.plan.computations import Filter, ScanSet, WriteSet

        client.create_database("st")
        client.create_set("st", "emps")
        client.send_data("st", "emps",
                         [{"id": i, "salary": i * 100} for i in range(100)])
        res = client.execute_computations(
            WriteSet(Filter(ScanSet("st", "emps"),
                            lambda r: r["salary"] > 5000), "st", "rich"),
            job_name="selftest-selection")
        check(len(next(iter(res.values()))) == 49, "selection row count")

    def aggregation():  # bin/test90-style group-by
        from netsdb_tpu.plan.computations import (Aggregate, ScanSet,
                                                  WriteSet)

        res = client.execute_computations(
            WriteSet(Aggregate(ScanSet("st", "emps"),
                               key=lambda r: r["id"] % 5,
                               value=lambda r: r["salary"],
                               combine=lambda a, b: a + b),
                     "st", "by_dept"), job_name="selftest-agg")
        out = next(iter(res.values()))
        check(len(out) == 5 and sum(out.values()) == sum(
            i * 100 for i in range(100)), "aggregation groups/total")

    def lda():
        from netsdb_tpu.workloads.lda import lda_em

        counts = rng.integers(0, 5, size=(20, 30)).astype(np.float32)
        state = lda_em(np.asarray(counts), k=3, iters=5)
        check(bool(np.all(np.isfinite(np.asarray(state.doc_topic)))),
              "lda finite doc_topic")

    def ff():  # FFTest 100 100
        from netsdb_tpu.models.ff import FFModel

        m = FFModel(db="stff", block=(32, 32))
        m.setup(client)
        m.load_random_weights(client, 100, 100, 10)
        m.load_inputs(client,
                      rng.standard_normal((64, 100)).astype(np.float32))
        probs = np.asarray(m.inference(client).to_dense())
        check(bool(np.allclose(probs.sum(0), 1.0, atol=1e-3)),
              "ff softmax columns sum to 1")

    def lstm():
        from netsdb_tpu.models.lstm_model import LSTMModel

        nin, nh, batch, T = 16, 16, 8, 4
        m = LSTMModel(db="stlstm", block=(8, 8))
        m.setup(client)
        w = {}
        for g in "ifco":
            w[f"w_{g}"] = rng.standard_normal((nh, nin)).astype(np.float32) * 0.3
            w[f"u_{g}"] = rng.standard_normal((nh, nh)).astype(np.float32) * 0.3
            w[f"b_{g}"] = rng.standard_normal(nh).astype(np.float32) * 0.1
        m.load_weights(client, w)
        m.load_state(client, np.zeros((nh, batch), np.float32),
                     np.zeros((nh, batch), np.float32))
        xs = rng.standard_normal((T, nin, batch)).astype(np.float32)
        hT, _cT, _hs = m.run_sequence(client, xs)
        check(bool(np.all(np.isfinite(np.asarray(hT.to_dense())))),
              "lstm finite hidden state")

    def conv():  # Conv2dProjTest shapes, numpy differential oracle
        from netsdb_tpu.ops.conv import conv2d_direct, conv2d_im2col

        x = rng.standard_normal((2, 3, 16, 16)).astype(np.float32)
        k = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)
        d = np.asarray(conv2d_direct(x, k))
        m = np.asarray(conv2d_im2col(x, k))
        check(bool(np.allclose(d, m, rtol=1e-4, atol=1e-4)),
              "conv direct vs im2col agree")

    def tpch_columnar():  # columnar engine vs host row engine, Q01/Q06
        from netsdb_tpu.relational.queries import (COLUMNAR_QUERIES,
                                                   tables_from_rows)
        from netsdb_tpu.workloads import tpch as row_engine

        from netsdb_tpu.utils.compare import structurally_close

        data = row_engine.generate(scale=1, seed=4)
        tabs = tables_from_rows(data)
        row_engine.load_tables(client, tables=data)
        for qn in ("q01", "q06"):
            rows = sorted(row_engine.run_query(client, qn), key=str)
            col = sorted(COLUMNAR_QUERIES[qn](tabs), key=str)
            check(structurally_close(col, rows),
                  f"columnar {qn} equals row engine")

    def pdml():  # LA DSL program (TestLA-style)
        from netsdb_tpu.dsl.interp import run_pdml

        env = run_pdml("A = ones(4,4,2,2)\nB = identity(4,2)\n"
                       "C = (A + B) %*% B\nD = rowSum(C)")
        check(env["D"].shape == (8, 1), "pdml rowSum shape")

    def dedup():  # shared-weight block fingerprinting
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.dedup.detector import block_fingerprints

        t = rng.standard_normal((16, 16)).astype(np.float32)
        bt = BlockedTensor.from_dense(t, (8, 8))
        fps = block_fingerprints(bt)
        check(len(fps) == 4, "dedup fingerprints one per block")

    def planner_stats():  # stats-driven join choice (round 2)
        from netsdb_tpu.relational import planner as PLN
        from netsdb_tpu.relational.table import ColumnTable
        import jax.numpy as jnp

        dense = ColumnTable({"k": jnp.arange(512, dtype=jnp.int32)})
        probe = ColumnTable({"fk": jnp.arange(512, dtype=jnp.int32)})
        sparse = ColumnTable({"k": jnp.asarray(
            np.linspace(0, 4e8, 64).astype(np.int32))})
        check(PLN.plan_join(dense, "k", probe, "fk").strategy == "lut",
              "planner picks LUT for dense keys")
        check(PLN.plan_join(sparse, "k", probe, "fk").strategy == "sort",
              "planner picks sort for sparse keys")

    def outofcore():  # paged q06 vs in-memory (round 2)
        import shutil
        import tempfile

        from netsdb_tpu.relational import outofcore as O
        from netsdb_tpu.relational.queries import cq06, tables_from_rows
        from netsdb_tpu.storage.paged import PagedTensorStore
        from netsdb_tpu.workloads import tpch as row_engine

        data = row_engine.generate(scale=1, seed=6)
        tabs = tables_from_rows(data)
        root = tempfile.mkdtemp(prefix="selftest_ooc_")
        try:
            store = PagedTensorStore(Configuration(
                root_dir=root, page_size_bytes=1 << 14))
            pc = O.PagedColumns.from_table(store, "li",
                                           tabs["lineitem"],
                                           O.Q06_COLUMNS)
            got = O.ooc_q06(pc)[0][1]
            want = cq06(tabs)[0][1]
            store.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
        check(abs(got - want) <= max(1e-4 * abs(want), 1e-2),
              "out-of-core q06 equals in-memory")

    def reddit_columnar():  # device label propagation (round 2)
        from netsdb_tpu.workloads import reddit as R
        from netsdb_tpu.workloads import reddit_columnar as RC

        cm, au, su = R.generate(num_comments=150, num_authors=12,
                                num_subs=4, seed=2)
        tabs = RC.columnarize(cm, au, su)
        prop = np.asarray(RC.propagate_labels(tabs["comments"]))
        pos = {c.author for c in cm if c.label == 1}
        want = np.array([1 if c.author in pos else 0 for c in cm])
        check(bool((prop == want).all()), "reddit propagation oracle")

    def placement_api():  # distribution through the set API (round 3)
        from netsdb_tpu.parallel.placement import Placement
        from netsdb_tpu.relational import dag as rdag
        from netsdb_tpu.relational.queries import cq01, tables_from_rows
        from netsdb_tpu.workloads import tpch as row_engine

        data = row_engine.generate(scale=1, seed=8)
        client.create_database("stp")
        client.create_set("stp", "lineitem", type_name="table",
                          placement=Placement.data_parallel(ndim=1))
        client.send_table("stp", "lineitem", data["lineitem"])
        got = rdag.run_query(
            client, rdag.q01_sink("stp", output_set="q01o")).to_rows()
        want = cq01(tables_from_rows(data))
        check(len(got) == len(want) and all(
            g["count"] == v["count"] for g, (_, v) in zip(got, want)),
            "placement-set q01 equals columnar engine")

    def ooc_join():  # streamed-probe join (round 3)
        import shutil
        import tempfile

        from netsdb_tpu.relational import outofcore as O
        from netsdb_tpu.relational.queries import cq03, tables_from_rows
        from netsdb_tpu.relational.table import date_to_int
        from netsdb_tpu.storage.paged import PagedTensorStore
        from netsdb_tpu.workloads import tpch as row_engine

        data = row_engine.generate(scale=1, seed=9)
        tabs = tables_from_rows(data)
        root = tempfile.mkdtemp(prefix="selftest_oocj_")
        try:
            store = PagedTensorStore(Configuration(
                root_dir=root, page_size_bytes=1 << 14))
            pc = O.PagedColumns.from_table(store, "li", tabs["lineitem"],
                                           O.Q03_COLUMNS)
            orders = {n: np.asarray(tabs["orders"][n]) for n in
                      ("o_orderkey", "o_custkey", "o_orderdate",
                       "o_shippriority")}
            cust = {n: np.asarray(tabs["customer"][n]) for n in
                    ("c_custkey", "c_mktsegment")}
            n_keys = int(orders["o_orderkey"].max()) + 1
            O.build_q03_side(store, orders, cust,
                             tabs["customer"].code("c_mktsegment",
                                                   "BUILDING"),
                             date_to_int("1995-03-15"),
                             max(1, n_keys // 3))
            got = O.ooc_q03(pc, store)
            store.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
        want = cq03(tabs)
        check([r["okey"] for r in got] == [r["okey"] for r in want],
              "out-of-core q03 join equals in-memory")

    def autojoin():  # automatic string-key device join (round 3)
        from netsdb_tpu.relational.autojoin import (equijoin,
                                                    table_from_objects)
        from netsdb_tpu.workloads import reddit as R

        cm, au, _su = R.generate(num_comments=120, num_authors=10,
                                 num_subs=3, seed=3)
        j = equijoin(table_from_objects(cm), "author",
                     table_from_objects(au), "author",
                     take=["author_id"])
        by = {a.author: a.author_id for a in au}
        got = sorted((r["id"], r["author_id"]) for r in j.to_rows())
        check(got == sorted((c.id, by[c.author]) for c in cm),
              "autojoin equals host hash join")

    def dedup_pool():  # serve-time HBM dedup (round 3)
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.dedup.pool import pool_models

        base = rng.standard_normal((64, 64)).astype(np.float32)
        variant = base.copy()
        variant[:16, :16] += 1.0
        pooled, rep = pool_models(
            {"a": BlockedTensor.from_dense(base, (16, 16)),
             "b": BlockedTensor.from_dense(variant, (16, 16))})
        check(rep["shared_block_refs"] == 15
              and bool(np.array_equal(
                  np.asarray(pooled["b"].assemble().data), variant)),
              "dedup pool shares identical blocks, assembly exact")

    def paged_set_api():  # round 4: out-of-core as a SET property
        import tempfile

        from netsdb_tpu.relational import dag as rdag
        from netsdb_tpu.relational.queries import cq06, tables_from_rows
        from netsdb_tpu.workloads import tpch

        tabs = tables_from_rows(tpch.generate(scale=4, seed=2))
        pc = Client(Configuration(
            root_dir=tempfile.mkdtemp(prefix="st_paged_"),
            page_size_bytes=4096, page_pool_bytes=16384))
        pc.create_database("d")
        for n, t in tabs.items():
            pc.create_set("d", n, type_name="table",
                          storage="paged" if n == "lineitem" else "memory")
            pc.send_table("d", n, t)
        out = rdag.run_query(pc, rdag.q06_sink("d"))
        ref = dict(cq06(tabs))["revenue"]
        store = pc.store.page_store()
        check(abs(float(np.asarray(out["revenue"])[0]) - ref)
              <= 1e-5 * max(abs(ref), 1)
              and (not store.native or store.stats()["spills"] > 0),
              "paged q06 matches resident (spills>0 when native)")

    def placement_arm():  # round 4: the advisor decides SHARDING
        from netsdb_tpu.learning.ab_bench import bench_distribution_ab

        out = bench_distribution_ab(scale=4, rounds=2,
                                    advisor_kind="rule")
        check(len(out["applied"]) == 2
              and all(v is not None for v in out["mean_s"].values()),
              "placement arms applied by create_set and measured")

    def paged_matmul():  # round 4: larger-than-pool weights stream
        import tempfile

        pc = Client(Configuration(
            root_dir=tempfile.mkdtemp(prefix="st_pm_"),
            page_size_bytes=65536, page_pool_bytes=262144))
        pc.create_database("d")
        pc.create_set("d", "w", storage="paged")
        w = rng.standard_normal((2048, 128)).astype(np.float32)
        x = rng.standard_normal((128, 32)).astype(np.float32)
        pc.send_matrix("d", "w", w)
        out = pc.paged_matmul("d", "w", x)
        store = pc.store.page_store()
        check(np.allclose(out, w @ x, rtol=2e-4, atol=2e-4)
              and (not store.native or store.stats()["spills"] > 0),
              "paged matmul matches numpy (spills>0 when native)")

    def paged_weights():  # round 5: inference over PAGED weight sets
        import tempfile

        from netsdb_tpu.models.ff import FFModel

        def run(storages):
            pc = Client(Configuration(
                root_dir=tempfile.mkdtemp(prefix="st_pw_"),
                page_size_bytes=4096, page_pool_bytes=16384))
            m = FFModel(db="ff", block=(32, 32))
            m.setup(pc, storages=storages)
            m.load_random_weights(pc, 96, 128, 10, seed=0)
            m.load_inputs(pc, rng.standard_normal(
                (32, 96)).astype(np.float32))
            return (np.asarray(m.inference(pc).to_dense()),
                    pc.store.page_store() if storages else None)

        # deterministic inputs: same rng state both runs
        state = rng.bit_generator.state
        ref, _ = run(None)
        rng.bit_generator.state = state
        out, store = run({"w1": "paged", "wo": "paged"})
        check(bool(np.array_equal(ref, out))
              and (not store.native or store.stats()["spills"] > 0),
              "FF inference over paged weight sets bit-matches resident "
              "(spills>0 when native)")

    steps = [("selection", selection), ("aggregation", aggregation),
             ("lda", lda), ("ff", ff), ("lstm", lstm), ("conv", conv),
             ("tpch-columnar", tpch_columnar), ("pdml", pdml),
             ("dedup", dedup), ("planner-stats", planner_stats),
             ("out-of-core", outofcore),
             ("reddit-columnar", reddit_columnar),
             ("placement-api", placement_api), ("ooc-join", ooc_join),
             ("autojoin", autojoin), ("dedup-pool", dedup_pool),
             ("paged-set-api", paged_set_api),
             ("placement-arm", placement_arm),
             ("paged-matmul", paged_matmul),
             ("paged-weights", paged_weights)]
    for name, fn in steps:
        step(name, fn)
    print(f"{len(steps) - len(failures)}/{len(steps)} passed")
    return 1 if failures else 0


def _cmd_la_bench(args) -> int:
    """The reference's headline LA tasks (Gram / linreg / matmul at
    200000x1000 scale — BASELINE.md rows 1-3) via the PDML DSL."""
    from netsdb_tpu.workloads import la_tasks

    tasks = list(la_tasks.TASKS) if args.task == "all" else [args.task]
    for t in tasks:
        res = la_tasks.run_task(t, rows=args.rows, cols=args.cols,
                                block=args.block, iters=args.iters)
        print(json.dumps(res))
    return 0


def _cmd_conv_bench(args) -> int:
    """Conv2d batch-latency p50 (both modes) vs the reference's ATen
    CPU path at its documented shapes."""
    from netsdb_tpu.workloads.conv_bench import run_conv_bench

    print(json.dumps(run_conv_bench(
        batch=args.batch, hw=args.hw, cin=args.cin, cout=args.cout,
        k=args.k, iters=args.iters,
        compute_dtype=args.compute_dtype), indent=2))
    return 0


def _cmd_model_bench(args) -> int:
    """word2vec / LSTM / text-classifier inference throughput vs the
    netsDB-equivalent CPU path (no reference-published numbers exist)."""
    from netsdb_tpu.workloads.model_bench import run_model_bench

    print(json.dumps(run_model_bench(scale=args.scale), indent=2))
    return 0


def _cmd_attention_bench(args) -> int:
    """Long-context flash-vs-naive attention (beyond-reference)."""
    from netsdb_tpu.workloads.attention_bench import bench_attention

    seqs = [int(s) for s in args.seqs.split(",")]
    print(json.dumps(bench_attention(seq_lens=seqs, batch=args.batch,
                                     heads=args.heads,
                                     head_dim=args.head_dim), indent=2))
    return 0


def _cmd_micro_bench(args) -> int:
    if getattr(args, "summa", False):
        # the SUMMA A/B needs a mesh: on a single-accelerator (or
        # CPU-only) box, force the virtual host-platform mesh BEFORE
        # jax initializes its backends — the same fixture tier-1 uses
        import os as _os

        # jax reads XLA_FLAGS at BACKEND initialization (the first
        # devices()/computation), not at import — setting it here is
        # early enough as long as nothing above dispatched to a device
        _flags = _os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            _os.environ.setdefault("JAX_PLATFORMS", "cpu")
            _os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    from netsdb_tpu.workloads import micro_bench

    if getattr(args, "staging", False):
        import json

        print(json.dumps(micro_bench.bench_staging(), indent=2))
        return 0
    if getattr(args, "bucket_sweep", False):
        import json

        print(json.dumps(micro_bench.bench_bucket_sweep(), indent=2))
        return 0
    if getattr(args, "obs_overhead", False):
        import json

        print(json.dumps(micro_bench.bench_obs_overhead(), indent=2))
        return 0
    if getattr(args, "explain_overhead", False):
        import json

        print(json.dumps(micro_bench.bench_explain_overhead(), indent=2))
        return 0
    if getattr(args, "lint_overhead", False):
        import json

        print(json.dumps(micro_bench.bench_lint_overhead(), indent=2))
        return 0
    if getattr(args, "fusion", False):
        import json

        print(json.dumps(micro_bench.bench_fusion(), indent=2))
        return 0
    if getattr(args, "summa", False):
        import json

        print(json.dumps(micro_bench.bench_summa(), indent=2,
                         default=str))
        return 0
    names = None
    if args.only is not None:
        names = [n.strip() for n in args.only.split(",") if n.strip()]
        if not names:
            print(f"--only given but no benchmark names; available: "
                  f"{', '.join(micro_bench.BENCHMARKS)}", file=sys.stderr)
            return 2
        unknown = [n for n in names if n not in micro_bench.BENCHMARKS]
        if unknown:
            print(f"unknown benchmark(s) {unknown}; available: "
                  f"{', '.join(micro_bench.BENCHMARKS)}", file=sys.stderr)
            return 2
    micro_bench.run_all(names=names)
    return 0


def _cmd_serve(args) -> int:
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
    from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
    from netsdb_tpu.serve.server import run_daemon

    overrides = {}
    if args.root:
        overrides["root_dir"] = args.root
    if getattr(args, "device_cache_mb", None) is not None:
        overrides["device_cache_bytes"] = args.device_cache_mb << 20
    if getattr(args, "page_pool_mb", None) is not None:
        overrides["page_pool_bytes"] = args.page_pool_mb << 20
    if getattr(args, "page_kb", None) is not None:
        overrides["page_size_bytes"] = args.page_kb << 10
    if getattr(args, "rebalance", False):
        overrides["rebalance"] = True
    config = Configuration(**overrides) if overrides else DEFAULT_CONFIG
    followers = ([a.strip() for a in args.followers.split(",") if a.strip()]
                 if getattr(args, "followers", None) else None)
    workers = ([a.strip() for a in args.workers.split(",") if a.strip()]
               if getattr(args, "workers", None) else None)
    return run_daemon(config, host=args.host, port=args.port,
                      token=args.token, max_jobs=args.max_jobs,
                      followers=followers, workers=workers)


def _print_obs(stats, traces) -> None:
    """Human-readable observability readout (the --json flag skips
    this and dumps the raw payloads)."""
    m = stats.get("metrics") or {}
    if m or stats.get("device_cache") or stats.get("followers"):
        print("== metrics ==")
    for k, v in sorted((m.get("counters") or {}).items()):
        print(f"  {k:<44} {v}")
    for k, v in sorted((m.get("gauges") or {}).items()):
        print(f"  {k:<44} {v}")
    for k, h in sorted((m.get("histograms") or {}).items()):
        if not h.get("count"):
            continue
        print(f"  {k:<44} n={h['count']} mean={h['mean']:.4g} "
              f"p50={h['p50']:.4g} p95={h['p95']:.4g} "
              f"p99={h['p99']:.4g} max={h['max']:.4g}")
    for section in ("compile", "staging", "stages"):
        if m.get(section):
            print(f"  -- {section}: {json.dumps(m[section])}")
    if stats.get("device_cache"):
        print(f"  -- device_cache: {json.dumps(stats['device_cache'])}")
    for addr, f in sorted((stats.get("followers") or {}).items()):
        dc = f.get("device_cache") if isinstance(f, dict) else None
        print(f"  -- follower {addr}: "
              f"{json.dumps(dc if dc is not None else f)}")

    profiles = traces.get("profiles") or []
    print(f"== traces ({len(profiles)} profile(s), newest last) ==")

    def show(prof, indent=""):
        total = prof.get("total_s") or 0.0
        print(f"{indent}{prof.get('qid')} [{prof.get('origin')}] "
              f"total={total * 1e3:.2f}ms "
              f"counters={prof.get('counters') or {}}")
        hd = prof.get("host_device")
        if hd:
            print(f"{indent}  host/device: "
                  f"host={hd['host_s'] * 1e3:.2f}ms "
                  f"device_est={hd['device_est_s'] * 1e3:.2f}ms")
        if prof.get("meta"):
            print(f"{indent}  meta: {json.dumps(prof['meta'])}")
        for sp in prof.get("spans") or ():
            pad = indent + "  " * (sp.get("depth", 0) + 1)
            extra = f"  {sp['counters']}" if sp.get("counters") else ""
            print(f"{pad}{sp['name']} +{sp['start_s'] * 1e3:.2f}ms "
                  f"{sp['duration_s'] * 1e3:.3f}ms{extra}")
        client_prof = prof.get("client")
        if client_prof:
            # the PUT_TRACE-shipped client half of the same qid
            print(f"{indent}  client:")
            show(client_prof, indent + "    ")
        for addr, fprofs in sorted((prof.get("followers") or {}).items()):
            print(f"{indent}  follower {addr}:")
            for fp in fprofs:
                show(fp, indent + "    ")

    for prof in profiles:
        show(prof)


def _print_health(health) -> None:
    """Human-readable SLO/health readout (the HEALTH frame)."""
    def show_section(h, indent=""):
        for o in h.get("objectives") or ():
            state = "BREACHED" if o.get("breached") else "ok"
            val = o.get("value")
            val_s = f"{val:.4g}" if isinstance(val, (int, float)) else "-"
            burn = o.get("worst_burn_rate")
            burn_s = f"{burn:.3g}" if isinstance(burn, (int, float)) \
                else "-"
            print(f"{indent}  {o['name']:<24} [{state}] "
                  f"value={val_s} target={o['target']} "
                  f"worst_burn={burn_s}  ({o['kind']})")
            for wname, w in sorted((o.get("windows") or {}).items()):
                wv = w.get("value")
                wv_s = f"{wv:.4g}" if isinstance(wv, (int, float)) else "-"
                wb = w.get("burn_rate")
                wb_s = f"{wb:.3g}" if isinstance(wb, (int, float)) else "-"
                print(f"{indent}      {wname:<10} value={wv_s} "
                      f"burn={wb_s} [{w.get('scope')}]")
        for ev in (h.get("events") or ())[-5:]:
            print(f"{indent}  event: {json.dumps(ev, default=str)}")
        sl = h.get("slowlog") or {}
        print(f"{indent}  slowlog: {sl.get('entries', 0)} entries "
              f"(threshold {sl.get('threshold_s')}s, "
              f"newest {sl.get('newest')})")

    print("== health ==")
    show_section(health)
    for addr, f in sorted((health.get("followers") or {}).items()):
        print(f"  follower {addr}:")
        if isinstance(f, dict) and "objectives" in f:
            show_section(f, "  ")
        else:
            print(f"    {json.dumps(f, default=str)}")


def _render_explain(prof) -> None:
    """Render one profile's per-operator tree — the classic EXPLAIN
    ANALYZE readout, per-node % of the plan total."""
    from netsdb_tpu.obs import operators

    tree = prof.get("operators")
    qid = prof.get("qid")
    if not tree:
        print(f"{qid}: profile has no operator tree (obs_explain off, "
              f"or the plan ran before this daemon enabled it)")
        return
    print(f"qid={qid} [{prof.get('origin')}] "
          f"total={1e3 * (prof.get('total_s') or 0.0):.2f}ms")
    print(operators.render_tree(tree, total_s=prof.get("total_s")))
    shard_ops = prof.get("shard_operators")
    if shard_ops:
        # the distributed region tree: the coordinator's regions above,
        # each shard's region forest below, all under one qid
        print(operators.render_shard_forest(
            shard_ops, total_s=prof.get("total_s")))
    for addr, fprofs in sorted((prof.get("followers") or {}).items()):
        for fp in fprofs:
            if fp.get("operators"):
                print(f"-- follower {addr}:")
                print(operators.render_tree(
                    fp["operators"], total_s=fp.get("total_s")))


def _cmd_obs_explain(c, args) -> int:
    """`obs --explain <qid>`: the per-operator EXPLAIN ANALYZE tree of
    one traced query — in-memory ring first, slowlog fallback."""
    reply = c.get_trace(qid=args.explain)
    profiles = [p for p in reply.get("profiles") or ()]
    if not profiles:
        reply = c.get_trace(qid=args.explain, slow=True)
        profiles = [p for p in reply.get("profiles") or ()]
    if not profiles:
        print(f"no profile for qid {args.explain!r} (ring rotated, or "
              f"the query was never traced)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(profiles, indent=2, default=str))
        return 0
    for prof in profiles:
        _render_explain(prof)
    return 0


def _render_top(payload) -> str:
    """One `obs --top` frame: derived rates from the daemon's
    telemetry history plus the busiest (client, set) attribution rows.
    Pure text-in/text-out so tests can pin the shape."""
    lines = []
    hist = payload.get("history") or {}
    deltas = payload.get("deltas") or {}
    lines.append(f"== top (history: {hist.get('readings', 0)} readings"
                 f" / {hist.get('span_s', 0.0):.0f}s span, "
                 f"window {deltas.get('dt_s', 0.0):.1f}s) ==")
    derived = deltas.get("derived") or {}
    for k in ("qps", "staged_mb_s", "staged_chunks_s",
              "devcache_hit_rate", "availability",
              "devcache_installs_s"):
        v = derived.get(k)
        v_s = f"{v:.4g}" if isinstance(v, (int, float)) else "-"
        lines.append(f"  {k:<22} {v_s}")
    rates = deltas.get("rates") or {}
    moving = sorted(rates.items(), key=lambda kv: -abs(kv[1]))[:8]
    if moving:
        lines.append("  -- moving counters (per second):")
        for name, rate in moving:
            lines.append(f"     {name:<40} {rate:.4g}/s")
    attribution = ((payload.get("metrics") or {})
                   .get("attribution") or {})
    rows = []
    for client, scopes in attribution.items():
        if not isinstance(scopes, dict):
            continue
        for scope, metrics in scopes.items():
            rows.append((client, scope,
                         metrics.get("requests", 0),
                         metrics.get("staged_bytes", 0)))
    rows.sort(key=lambda r: (-r[2], -r[3]))
    if rows:
        lines.append("  -- clients (requests / staged MB):")
        for client, scope, reqs, sb in rows[:8]:
            lines.append(f"     {client:<16} {scope:<24} "
                         f"{int(reqs):>8} {sb / 1e6:>10.1f}")
    return "\n".join(lines)


def _cmd_obs_top(c, args) -> int:
    """`obs --top`: live terminal view refreshing from the daemon's
    history deltas (bounded iterations for scripting/tests; default
    runs until interrupted)."""
    import time as _time

    n = args.iterations
    i = 0
    try:
        while True:
            payload = c.get_metrics(window_s=args.interval * 5)
            if args.json:
                print(json.dumps({"history": payload.get("history"),
                                  "deltas": payload.get("deltas")},
                                 indent=2, default=str))
            else:
                print(_render_top(payload))
            i += 1
            if n and i >= n:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _sched_view(stats) -> dict:
    """The scheduler slice of one COLLECT_STATS reply — the ONE
    extractor both `obs --sched` renderings (pretty and --json)
    consume, so the two outputs cannot drift."""
    m = stats.get("metrics") or {}
    return {
        "sched": m.get("sched") or {},
        "counters": {k: v for k, v in (m.get("counters") or {}).items()
                     if k.startswith("sched.")},
        "queue_wait_s": (m.get("histograms") or {})
        .get("sched.queue_wait_s"),
    }


def _print_sched(view) -> None:
    """The `obs --sched` readout: the scheduler's lane table (the
    registry's "sched" collector section) plus every sched.*
    instrument — admissions, rejections, coalesce and affinity
    decisions, queue-wait distribution."""
    sched = view["sched"]
    print(f"== scheduler (slots {sched.get('slots')}, free "
          f"{sched.get('free_slots')}, queued {sched.get('queued')}, "
          f"quota {sched.get('quota') or 'off'}, aging every "
          f"{sched.get('aging_every') or 'off'}, coalesce "
          f"{'on' if sched.get('coalesce_enabled') else 'off'}, "
          f"affinity "
          f"{'on' if sched.get('affinity_enabled') else 'off'}) ==")
    lanes = sched.get("lanes") or {}
    for name, ln in sorted(lanes.items()):
        w = ln.get("wait") or {}
        line = (f"  lane {name:<20} weight={ln.get('weight'):<6} "
                f"depth={ln.get('depth'):<4} served={ln.get('served')}")
        if w.get("p50") is not None:
            line += (f" wait_p50={w['p50'] * 1e3:.2f}ms"
                     f" wait_p99={w['p99'] * 1e3:.2f}ms")
        print(line)
    for k, v in sorted(view["counters"].items()):
        print(f"  {k:<44} {v}")
    h = view["queue_wait_s"]
    if h and h.get("count"):
        print(f"  sched.queue_wait_s  n={h['count']} "
              f"mean={h['mean'] * 1e3:.2f}ms p50={h['p50'] * 1e3:.2f}ms "
              f"p99={h['p99'] * 1e3:.2f}ms max={h['max'] * 1e3:.2f}ms")


def _sessions_view(stats) -> dict:
    """The session/decode slice of COLLECT_STATS — one extractor for
    both `obs --sessions` renderings (pretty and --json)."""
    m = stats.get("metrics") or {}
    counters = m.get("counters") or {}
    gauges = m.get("gauges") or {}
    return {
        "sessions": stats.get("sessions") or {},
        "counters": {k: v for k, v in counters.items()
                     if k.startswith("session.")},
        "gauges": {k: v for k, v in gauges.items()
                   if k.startswith(("session.", "dedup."))},
    }


def _print_sessions(view) -> None:
    """The `obs --sessions` readout: the open-session table (owner,
    step counts), batcher coalescing stats, arena spill accounting,
    decode program/trace counts, resident-state bytes, and — when
    model_dedup pooled anything — the per-model page attribution."""
    s = view["sessions"]
    batcher = s.get("batcher") or {}
    arena = s.get("arena") or {}
    dec = s.get("decode") or {}
    print(f"== sessions (open {s.get('open', 0)}, resident "
          f"{s.get('resident_bytes', 0)} B) ==")
    for row in s.get("sessions") or []:
        print(f"  session {row['sid'][:12]:<14} db={row['db']:<12} "
              f"steps={row['steps']:<6} owner={row['owner']}")
    print(f"  batcher batches={batcher.get('batches', 0)} "
          f"coalesced={batcher.get('coalesced', 0)} "
          f"max_occupancy={batcher.get('max_occupancy', 0)} "
          f"pending={batcher.get('pending', 0)}")
    print(f"  arena entries={arena.get('entries', 0)} "
          f"reads={arena.get('reads', 0)} "
          f"writes={arena.get('writes', 0)} "
          f"bytes={arena.get('bytes', 0)}")
    print(f"  decode programs={dec.get('programs', 0)} "
          f"traces={dec.get('traces', 0)} "
          f"batches={dec.get('batches', 0)} "
          f"steps={dec.get('steps', 0)} "
          f"pad_rows={dec.get('pad_rows', 0)}")
    rep = s.get("residency")
    if rep:
        print(f"  dedup models={rep.get('models', 0)} "
              f"unique_page_bytes={rep.get('unique_page_bytes', 0)} "
              f"undeduped={rep.get('total_page_bytes', 0)} "
              f"(pooling "
              f"{'on' if rep.get('model_dedup') else 'off'})")
        for name, b in sorted(
                (rep.get("charged_by_model") or {}).items()):
            print(f"    model {name:<16} charged_bytes={b}")
    for k, v in sorted(view["counters"].items()):
        print(f"  {k:<34} {v}")
    for k, v in sorted(view["gauges"].items()):
        print(f"  {k:<34} {v}")


def _print_placement(view) -> None:
    """The `obs --placement` readout: per-member heat/byte/slot
    totals, the per-slot ownership table for every sharded set, and
    the rebalancer's status + last-move log (serve/rebalance.py)."""
    st = view.get("status") or {}
    print(f"== placement (epoch {st.get('epoch')}, skew "
          f"{view.get('skew_ratio')}, rebalance "
          f"{'on' if st.get('enabled') else 'off'}, "
          f"{'running' if st.get('running') else 'idle'}, "
          f"streak {st.get('streak')}) ==")
    for m in view.get("members") or []:
        print(f"  member {m['addr']:<22} slots={m['slots']:<3} "
              f"heat={m['heat']:<10} bytes={m['nbytes']}")
    for s in view.get("sets") or []:
        print(f"  set {s['db']}:{s['set']} mode={s['mode']} "
              f"epoch={s['epoch']} heat={s['heat']}")
        for sl in s.get("slots") or []:
            print(f"    slot {sl['slot']:<3} {sl['addr']:<22} "
                  f"{sl['state']:<8} bytes={sl['nbytes']:<10} "
                  f"heat={sl['heat']}")
    moves = st.get("moves") or []
    if moves:
        print(f"  -- last {len(moves)} move(s) --")
        for mv in moves:
            print(f"    {mv.get('db')}:{mv.get('set')}[{mv.get('slot')}]"
                  f" {mv.get('src')} -> {mv.get('dst')} "
                  f"{'ok' if mv.get('ok') else 'ABORT'} "
                  f"bytes={mv.get('nbytes', 0)}"
                  + (f" ({mv.get('error')})" if mv.get('error')
                     else ""))


def _cmd_obs(args) -> int:
    """Pretty-print a running daemon's observability surface: the
    COLLECT_STATS "metrics" section (central registry), the last N
    completed query profiles (GET_TRACE), the SLO/health readout
    (--health), the scheduler's lane/coalesce/affinity view (--sched),
    the persisted slow-query ring (--slowlog), one query's
    per-operator tree (--explain), the Prometheus scrape text
    (--openmetrics), or the live rate view (--top)."""
    from netsdb_tpu.serve.client import RemoteClient

    c = RemoteClient(args.addr, token=args.token)
    try:
        if getattr(args, "explain", None):
            return _cmd_obs_explain(c, args)
        if getattr(args, "sched", False):
            view = _sched_view(c.collect_stats())
            if args.json:
                print(json.dumps(view, indent=2, default=str))
            else:
                _print_sched(view)
            return 0
        if getattr(args, "sessions", False):
            view = _sessions_view(c.collect_stats())
            if args.json:
                print(json.dumps(view, indent=2, default=str))
            else:
                _print_sessions(view)
            return 0
        if getattr(args, "placement", False):
            view = c.placement_view()
            if args.json:
                print(json.dumps(view, indent=2, default=str))
            else:
                _print_placement(view)
            return 0
        if getattr(args, "openmetrics", False):
            print(c.get_metrics(format="openmetrics")["text"], end="")
            return 0
        if getattr(args, "top", False):
            return _cmd_obs_top(c, args)
        if getattr(args, "health", False):
            health = c.health()
            if args.json:
                print(json.dumps(health, indent=2, default=str))
            else:
                _print_health(health)
            return 0
        if getattr(args, "slowlog", False):
            traces = c.get_trace(last=args.traces, qid=args.qid,
                                 slow=True)
            if args.json:
                print(json.dumps(traces, indent=2, default=str))
                return 0
            sl = traces.get("slowlog") or {}
            print(f"== slowlog ({sl.get('entries', 0)} persisted, "
                  f"threshold {sl.get('threshold_s')}s) ==")
            _print_obs({"metrics": {}}, traces)
            return 0
        stats = c.collect_stats()
        traces = c.get_trace(last=args.traces, qid=args.qid)
    finally:
        c.close()
    if args.json:
        print(json.dumps({"stats": stats, "traces": traces}, indent=2,
                         default=str))
        return 0
    _print_obs(stats, traces)
    return 0


def _cmd_lint(args) -> int:
    """``cli lint`` — the one static-analysis entry point CI and
    humans share (netsdb_tpu/analysis/): file:line:col diagnostics,
    ``--json`` for scripting, exit 1 on any finding. Runs without
    importing jax, so a lint gate costs seconds."""
    from netsdb_tpu.analysis import lint as L

    if args.list_rules:
        for rule in L.all_rules():
            print(f"{rule.id:<22} {rule.rationale}")
        return 0
    if getattr(args, "witness_coverage", None):
        from netsdb_tpu.analysis import witnesscov as W

        try:
            dyn = W.load_witness_dump(args.witness_coverage)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"witness-coverage: cannot read "
                  f"{args.witness_coverage}: {e}", file=sys.stderr)
            return 2
        report = W.coverage(dyn)
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            print(W.render(report))
        return 0  # a coverage REPORT, not a gate: no false failures
    if getattr(args, "fix", False):
        from netsdb_tpu.analysis import fix as F

        res = F.run_fix(paths=args.paths or None,
                        dry_run=getattr(args, "dry_run", False))
        if getattr(args, "dry_run", False):
            if res["diff"]:
                print(res["diff"], end="")
            print(f"lint --fix --dry-run: {res['fixed']} fix(es) in "
                  f"{len(res['files'])} file(s), {res['skipped']} "
                  f"skipped (safety gates)")
            return 0
        print(f"lint --fix: applied {res['fixed']} fix(es) in "
              f"{len(res['files'])} file(s), {res['skipped']} "
              f"skipped (safety gates)")
        for rel in res["files"]:
            print(f"  fixed: {rel}")
        # fall through: report what remains after the rewrite
    try:
        diags = L.run_lint(paths=args.paths or None,
                           rules=args.rule or None)
    except ValueError as e:  # unknown rule id
        print(str(e), file=sys.stderr)
        return 2
    accepted = []
    if getattr(args, "write_baseline", False) \
            and not getattr(args, "baseline", None):
        print("--write-baseline requires --baseline FILE (where to "
              "record the accepted findings)", file=sys.stderr)
        return 2
    if getattr(args, "baseline", None):
        from netsdb_tpu.analysis import baseline as B

        if getattr(args, "write_baseline", False):
            n = B.write(diags, args.baseline)
            print(f"lint: wrote {n} accepted finding(s) to "
                  f"{args.baseline}")
            return 0
        diags, accepted = B.apply(diags, args.baseline)
    if args.json:
        print(json.dumps(L.to_json(diags), indent=2))
    else:
        for d in diags:
            print(str(d))
        tail = f", {len(accepted)} baselined" if accepted else ""
        print(f"lint: {'FAIL' if diags else 'ok'} "
              f"({len(diags)} finding(s), "
              f"{len(L.rule_ids())} rule(s){tail})")
    return 1 if diags else 0


def _cmd_serve_bench(args) -> int:
    if getattr(args, "fusion_distributed", False):
        from netsdb_tpu.workloads.serve_bench import (
            run_fusion_distributed_bench)

        out = run_fusion_distributed_bench(
            daemons=getattr(args, "daemons", 4))
    elif getattr(args, "scale", False):
        from netsdb_tpu.workloads.serve_bench import run_scaleout_bench

        out = run_scaleout_bench(daemons=getattr(args, "daemons", 4))
    elif getattr(args, "rebalance", False):
        from netsdb_tpu.workloads.serve_bench import run_rebalance_bench

        out = run_rebalance_bench(daemons=getattr(args, "daemons", 4))
    elif getattr(args, "scheduler", False):
        from netsdb_tpu.workloads.serve_bench import run_scheduler_bench

        out = run_scheduler_bench(
            clients=args.clients if args.clients is not None else 8)
    elif getattr(args, "partial_cache", False):
        from netsdb_tpu.workloads.serve_bench import run_partial_cache_bench

        out = run_partial_cache_bench()
    elif getattr(args, "device_cache", False):
        from netsdb_tpu.workloads.serve_bench import run_device_cache_bench

        out = run_device_cache_bench()
    elif getattr(args, "data_plane", False):
        from netsdb_tpu.workloads.serve_bench import run_data_plane_bench

        out = run_data_plane_bench(table_mb=args.table_mb)
    else:
        from netsdb_tpu.workloads.serve_bench import run_serve_bench

        out = run_serve_bench(clients=args.clients
                              if args.clients is not None else 2,
                              jobs_per_client=args.jobs,
                              batch=args.batch, port=args.port,
                              platform=args.platform)
    print(json.dumps(out, indent=2))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="netsdb_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("info", help="cluster and device info")
    sub.add_parser("bench", help="run the benchmark harness")

    p = sub.add_parser("pdml", help="run a PDML linear-algebra program")
    p.add_argument("file")
    p.add_argument("--print-values", action="store_true")

    p = sub.add_parser("demo-ff", help="FF inference demo (FFTest shape)")
    p.add_argument("--batch", type=int, default=1000)
    p.add_argument("--features", type=int, default=512)
    p.add_argument("--hidden", type=int, default=1024)
    p.add_argument("--labels", type=int, default=10)
    p.add_argument("--block", type=int, default=256)

    p = sub.add_parser("la-bench",
                       help="headline LA tasks (Gram/linreg/matmul) vs "
                            "the reference's published numbers")
    p.add_argument("--task", default="all",
                   choices=["all", "gram", "linreg", "matmul"])
    p.add_argument("--rows", type=int, default=200000)
    p.add_argument("--cols", type=int, default=1000)
    p.add_argument("--block", type=int, default=1000)
    p.add_argument("--iters", type=int, default=5)

    p = sub.add_parser("conv-bench",
                       help="conv2d batch latency p50 vs ATen CPU path")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--hw", type=int, default=112)
    p.add_argument("--cin", type=int, default=3)
    p.add_argument("--cout", type=int, default=64)
    p.add_argument("--k", type=int, default=7)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--compute-dtype", default=None)

    p = sub.add_parser("model-bench",
                       help="word2vec/LSTM/text-classifier throughput "
                            "vs netsDB-equivalent CPU path")
    p.add_argument("--scale", type=float, default=1.0,
                   help="multiplier on all benchmark dimensions")

    p = sub.add_parser("attention-bench",
                       help="flash vs naive attention at long seq lens")
    p.add_argument("--seqs", default="1024,2048,4096,8192")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)

    p = sub.add_parser("micro-bench",
                       help="runtime micro-benchmarks (serviceBenchmarks)")
    p.add_argument("--only", default=None,
                   help="comma-separated benchmark names")
    p.add_argument("--staging", action="store_true",
                   help="overlapped vs synchronous device staging on "
                        "the out-of-core matmul and fold streams")
    p.add_argument("--bucket-sweep", action="store_true",
                   help="pad-waste vs trace-count per shape-ladder "
                        "density (the bucket_density knob: 2 vs 4 "
                        "buckets per octave)")
    p.add_argument("--obs-overhead", action="store_true",
                   help="cost of always-on query tracing on the staged "
                        "fold stream (traced vs untraced; < 3%% is the "
                        "budget)")
    p.add_argument("--explain-overhead", action="store_true",
                   help="cost of per-node operator attribution on the "
                        "staged fold stream (explain on vs off; < 1%% "
                        "budget, ~0 when off)")
    p.add_argument("--lint-overhead", action="store_true",
                   help="cost of the runtime lock-order witness on "
                        "the staged fold stream (witness on vs off; "
                        "< 2%% budget, ~0 when off)")
    p.add_argument("--fusion", action="store_true",
                   help="fusion-aware plan compilation paired A/B "
                        "(plan_fusion on vs off on the staged fold "
                        "stream + a resident-spine mixed plan; "
                        "reports plan_fusion_speedup + trace counts)")
    p.add_argument("--summa", action="store_true",
                   help="distributed linear algebra paired A/B: SUMMA "
                        "panel staging vs replicated operands on the "
                        "virtual mesh (per-host staged bytes ~1/N, "
                        "byte-equality gated) + reshard-via-"
                        "collectives vs re-stage-from-arena (zero "
                        "arena reads proof)")

    sub.add_parser("selftest",
                   help="scripted integration sequence (integratedTests.py)")

    p = sub.add_parser("tpch", help="run TPC-H demo queries")
    p.add_argument("--query", default=None,
                   choices=["q01", "q02", "q03", "q04", "q06", "q12", "q13",
                            "q14", "q17", "q22"])
    p.add_argument("--scale", type=int, default=1)
    p.add_argument("--print-values", action="store_true")

    p = sub.add_parser("tpch-bench",
                       help="columnar TPC-H device benchmark (dbgen scale)")
    p.add_argument("--sf", type=float, default=0.1,
                   help="TPC-H scale factor (lineitem ≈ 6M rows at sf=1)")
    p.add_argument("--iters", type=int, default=10)

    p = sub.add_parser("serve", help="run the resident controller daemon "
                       "(ref MasterMain: the server that owns the device "
                       "and keeps model sets loaded across clients)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8108)
    p.add_argument("--root", default=None, help="database root dir")
    p.add_argument("--token", default=None, help="shared auth token")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="concurrent job admission cap (default num_threads)")
    p.add_argument("--followers", default=None,
                   help="comma-separated worker daemon addresses: fan "
                        "every mutating/job frame out for multi-host "
                        "SPMD (init jax.distributed in every process)")
    p.add_argument("--workers", default=None,
                   help="comma-separated shard daemon addresses "
                        "forming this leader's partitioned worker "
                        "pool (horizontal scale-out: sets created "
                        "with placement='hash'/'range' partition "
                        "across the pool)")
    p.add_argument("--device-cache-mb", type=int, default=None,
                   help="override config.device_cache_bytes (MB); "
                        "0 disables the device cache")
    p.add_argument("--page-pool-mb", type=int, default=None,
                   help="override config.page_pool_bytes (MB) — the "
                        "paged-set arena cap")
    p.add_argument("--page-kb", type=int, default=None,
                   help="override config.page_size_bytes (KB)")
    p.add_argument("--rebalance", action="store_true",
                   help="enable live shard rebalancing on this "
                        "daemon (config.rebalance): the leader's "
                        "skew detector moves slot ownership between "
                        "pool members with zero client-visible "
                        "downtime")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu) — env overrides "
                   "are ignored by the ambient plugin, only jax.config "
                   "works, so the daemon must set it itself")

    p = sub.add_parser("serve-bench",
                       help="FF inference throughput over the RPC hop, "
                       "concurrent client processes against one daemon")
    p.add_argument("--clients", type=int, default=None,
                   help="concurrent clients (default: 2, or 8 for "
                        "--scheduler); explicit values always win")
    p.add_argument("--jobs", type=int, default=8,
                   help="inference jobs per client")
    p.add_argument("--batch", type=int, default=16384)
    p.add_argument("--port", type=int, default=0,
                   help="0 = spawn a private daemon on an ephemeral port")
    p.add_argument("--platform", default=None,
                   help="jax platform for the spawned daemon (e.g. cpu)")
    p.add_argument("--data-plane", action="store_true",
                   help="v3 data-plane numbers instead: single-frame vs "
                   "streamed pipelined ingest MB/s, scan MB/s, zero-copy "
                   "tensor push/pull, hedged-read p99")
    p.add_argument("--table-mb", type=int, default=64)
    p.add_argument("--device-cache", action="store_true",
                   help="cold vs warm EXECUTE latency over a "
                        "device-cache-resident paged set instead "
                        "(hit/miss counters included)")
    p.add_argument("--partial-cache", action="store_true",
                   help="partial-run caching paired A/B instead: "
                        "warm re-query after a 1%% append under "
                        "dirty-range vs whole-run invalidation")
    p.add_argument("--scheduler", action="store_true",
                   help="query-scheduler paired A/B instead: N "
                        "concurrent identical cold EXECUTEs, "
                        "scheduler on vs off (executions run, "
                        "devcache installs, coalesce hits, p50/p99)")
    p.add_argument("--scale", action="store_true",
                   help="horizontal scale-out instead: paired 1 vs N "
                        "daemon arm — aggregate routed-ingest MB/s, "
                        "cold scatter-gather q01 QPS and "
                        "byte-equality incl. a distributed-shuffle "
                        "join")
    p.add_argument("--daemons", type=int, default=4,
                   help="pool size for --scale (leader + N-1 shards)")
    p.add_argument("--rebalance", action="store_true",
                   help="self-rebalancing paired A/B instead: a "
                        "4-daemon pool under an 80/20 skewed mix "
                        "registers a 5th daemon mid-run — rebalance "
                        "on vs frozen (recovery throughput ratio, "
                        "zero failed requests, exact totals)")
    p.add_argument("--fusion-distributed", action="store_true",
                   help="distributed fusion paired A/B instead: "
                        "4-daemon scatter q01 + 3-sink fan under "
                        "the optimal mapper vs greedy vs "
                        "plan_fusion=off — one-program-per-shard, "
                        "one-subplan fan and byte-equality gates")

    p = sub.add_parser("obs",
                       help="observability readout of a running daemon: "
                            "central metrics (COLLECT_STATS) + the last "
                            "query trace profiles (GET_TRACE)")
    p.add_argument("--addr", default="127.0.0.1:8108",
                   help="daemon address host:port")
    p.add_argument("--token", default=None, help="shared auth token")
    p.add_argument("--traces", type=int, default=5,
                   help="how many completed query profiles to show")
    p.add_argument("--qid", default=None,
                   help="show only the profile(s) of one query id")
    p.add_argument("--health", action="store_true",
                   help="SLO/health readout instead (HEALTH frame): "
                        "every objective with multi-window burn rates, "
                        "recent breach/recovery events, slowlog "
                        "summary; leaders merge follower sections")
    p.add_argument("--sched", action="store_true",
                   help="the query scheduler's view instead: lane "
                        "table (weights, depths, queue-wait "
                        "percentiles) + admission/coalesce/affinity "
                        "counters")
    p.add_argument("--placement", action="store_true",
                   help="the leader's live placement table instead: "
                        "per-slot owner/state/bytes/heat for every "
                        "sharded set, per-member totals, skew ratio, "
                        "rebalancer status + last-move log")
    p.add_argument("--sessions", action="store_true",
                   help="the stateful-serving view instead: open "
                        "decode sessions (owner, steps), batch "
                        "coalescing stats, arena spill accounting, "
                        "resident-state bytes and the dedup page "
                        "attribution")
    p.add_argument("--slowlog", action="store_true",
                   help="the persisted slow-query ring instead "
                        "(<root>/slowlog/ — outliers that survived "
                        "ring rotation and restarts)")
    p.add_argument("--explain", default=None, metavar="QID",
                   help="render one traced query's per-operator "
                        "EXPLAIN ANALYZE tree (per-node wall/device "
                        "time, rows, cache + compile counters, %% of "
                        "total); falls back to the slowlog when the "
                        "ring rotated")
    p.add_argument("--openmetrics", action="store_true",
                   help="print the Prometheus text exposition "
                        "(GET_METRICS format=openmetrics) — the "
                        "scrape-endpoint payload, leader-merged")
    p.add_argument("--top", action="store_true",
                   help="live rate view refreshing from the daemon's "
                        "telemetry history deltas (QPS, staged MB/s, "
                        "hit-rate trend, busiest clients)")
    p.add_argument("--iterations", type=int, default=0,
                   help="--top refresh count (0 = until interrupted)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--top refresh period seconds")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the pretty readout")

    p = sub.add_parser("lint",
                       help="static concurrency-correctness analysis "
                            "(netsdb_tpu/analysis/): AST rules — lock "
                            "ordering, blocking-under-lock, resource "
                            "discipline, and every ported guard — "
                            "over the package tree; exit 1 on any "
                            "finding")
    p.add_argument("paths", nargs="*",
                   help="explicit files to lint (default: the whole "
                        "netsdb_tpu/ package; per-rule directory "
                        "scoping applies either way)")
    p.add_argument("--rule", action="append", metavar="RULE_ID",
                   help="run only this rule (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog (id + rationale)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable diagnostics")
    p.add_argument("--fix", action="store_true",
                   help="auto-apply the mechanical iter-close fixes "
                        "(wrap directly-iterated stream producers in "
                        "contextlib.closing) before reporting; "
                        "idempotent — a second run changes nothing")
    p.add_argument("--dry-run", action="store_true",
                   help="with --fix: print the unified diff instead "
                        "of writing files")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="findings ratchet (docs/lint_baseline.json): "
                        "findings recorded there are accepted, new "
                        "findings fail, and a stale entry is itself "
                        "a finding — the file only shrinks")
    p.add_argument("--write-baseline", action="store_true",
                   help="with --baseline: record the current "
                        "findings as the new accepted baseline and "
                        "exit")
    p.add_argument("--witness-coverage", metavar="DUMP", default=None,
                   help="reconcile the static lock-order graph with "
                        "a runtime witness dump (utils/locks."
                        "LockWitness.dump, written by the tier-1 "
                        "conftest under NETSDB_WITNESS_DUMP): "
                        "statically-possible-but-never-exercised "
                        "edges report as untested concurrency, "
                        "runtime edges the static graph missed as "
                        "blind spots; always exits 0")

    p = sub.add_parser("autotune",
                       help="measure physical-strategy crossovers "
                       "(dense-vs-scatter segments, LUT-vs-sort joins) on "
                       "the live backend and persist per device kind")
    p.add_argument("--no-persist", action="store_true")

    p = sub.add_parser("transformer-bench",
                       help="set-backed long-context transformer layer "
                       "forward (flash attention), tokens/s + TFLOP/s")
    p.add_argument("--seq", type=int, nargs="+", default=[4096, 8192])
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--embed", type=int, default=1024)
    p.add_argument("--heads", type=int, default=8)

    p = sub.add_parser("reddit-bench",
                       help="columnar reddit label propagation at scale")
    p.add_argument("--rows", type=int, default=1_000_000)
    p.add_argument("--authors", type=int, default=50_000)

    p = sub.add_parser("ooc-bench",
                       help="out-of-core TPC-H q01/q06 through the paged "
                       "store under a pool cap")
    p.add_argument("--rows", type=int, default=60_000_000)
    p.add_argument("--pool-mb", type=int, default=1024)

    p = sub.add_parser("paged-api-bench",
                       help="SF10-scale q01 + one-pass grace q03 through "
                            "the SET-API paged path (create_set(storage="
                            "'paged') + suite/q03 sinks) under a pool cap")
    p.add_argument("--rows", type=int, default=60_000_000)
    p.add_argument("--pool-mb", type=int, default=1024)

    p = sub.add_parser("lsh-bench",
                       help="LSH dedup index over a synthetic model zoo")
    p.add_argument("--models", type=int, default=100)

    p = sub.add_parser("ab-bench",
                       help="live placement-advisor A/B (Lachesis loop)")
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--advisor", choices=["rule", "drl"], default="rule",
                   help="rule-based bandit or live actor-critic (DRL)")

    args = parser.parse_args(argv)
    if args.cmd != "lint":  # lint must not import jax (speed + CI)
        from netsdb_tpu.config import enable_compilation_cache

        enable_compilation_cache()  # every CLI path shares the plan cache
    return {"info": _cmd_info, "bench": _cmd_bench, "pdml": _cmd_pdml,
            "lint": _cmd_lint,
            "autotune": _cmd_autotune,
            "transformer-bench": _cmd_transformer_bench,
            "reddit-bench": _cmd_reddit_bench,
            "ooc-bench": _cmd_ooc_bench,
            "paged-api-bench": _cmd_paged_api_bench,
            "lsh-bench": _cmd_lsh_bench,
            "ab-bench": _cmd_ab_bench,
            "serve": _cmd_serve, "serve-bench": _cmd_serve_bench,
            "obs": _cmd_obs,
            "demo-ff": _cmd_demo_ff, "tpch": _cmd_tpch,
            "micro-bench": _cmd_micro_bench, "tpch-bench": _cmd_tpch_bench,
            "model-bench": _cmd_model_bench,
            "attention-bench": _cmd_attention_bench,
            "la-bench": _cmd_la_bench, "conv-bench": _cmd_conv_bench,
            "selftest": _cmd_selftest}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
