from netsdb_tpu.parallel.collectives import (
    all_to_all_resharding,
    matmul_allgather,
    matmul_psum,
    matmul_psum_scatter,
)
from netsdb_tpu.parallel.distributed import (
    cluster_info,
    hybrid_mesh,
    initialize_cluster,
)
from netsdb_tpu.parallel.mesh import (
    default_mesh,
    make_mesh,
    replicate,
    shard_blocked,
)
from netsdb_tpu.parallel.pipeline import pipeline_apply
from netsdb_tpu.parallel.reshard import (
    plan_steps,
    reshard_set,
)
from netsdb_tpu.parallel.ring import ring_attention, ulysses_attention
from netsdb_tpu.parallel.summa import (
    summa_matmul_resident,
    summa_matmul_streamed,
)

__all__ = [
    "default_mesh", "make_mesh", "shard_blocked", "replicate",
    "matmul_psum", "matmul_psum_scatter", "matmul_allgather",
    "all_to_all_resharding", "ring_attention", "ulysses_attention",
    "initialize_cluster", "hybrid_mesh", "cluster_info", "pipeline_apply",
    "summa_matmul_streamed", "summa_matmul_resident", "plan_steps",
    "reshard_set",
]
