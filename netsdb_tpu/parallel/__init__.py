from netsdb_tpu.parallel.mesh import (
    default_mesh,
    make_mesh,
    shard_blocked,
    replicate,
)

__all__ = ["default_mesh", "make_mesh", "shard_blocked", "replicate"]
