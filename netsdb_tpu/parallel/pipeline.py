"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

The reference has no cross-node pipelining (SURVEY §2.6: sequential
JobStages with materialized intermediates); this module adds it as a
first-class strategy: layer stages sharded over a ``pp`` mesh axis,
microbatch activations rotated stage-to-stage with ``ppermute`` under
``shard_map``. The schedule is the plain GPipe fill-drain loop:
``n_micro + n_stages - 1`` steps, stage i processing microbatch t-i at
step t; outputs accumulate at the last stage and are psum-broadcast at
the end (one small collective).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params, xs, *, stage_fn, axis_name: str):
    """Per-device body. ``stage_params``: this device's stage slice
    (leading dim 1). ``xs``: (n_micro, ...) microbatches, replicated."""
    n_stages = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    params = jax.tree_util.tree_map(lambda t: t[0], stage_params)

    steps = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def step(t, carry):
        buf, outs = carry
        # stage 0 ingests microbatch t (clamped; masked out when t >= n_micro)
        mb_in = jax.lax.dynamic_index_in_dim(
            xs, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
        inp = jnp.where(my_idx == 0, mb_in, buf)
        y = stage_fn(params, inp)
        # collect at the last stage: step t finishes microbatch t-(n-1)
        out_idx = t - (n_stages - 1)
        valid = (my_idx == n_stages - 1) & (out_idx >= 0)
        updated = jax.lax.dynamic_update_index_in_dim(
            outs, y, jnp.maximum(out_idx, 0), axis=0)
        outs = jnp.where(valid, updated, outs)
        # hand activations to the next stage
        buf = jax.lax.ppermute(y, axis_name, perm)
        return buf, outs

    # initial carries must carry the mesh-axis "varying" tag the loop
    # body produces (ppermute/axis_index outputs vary per device)
    buf0 = jax.lax.pcast(jnp.zeros_like(xs[0]), axis_name, to="varying")
    outs0 = jax.lax.pcast(jnp.zeros_like(xs), axis_name, to="varying")
    _, outs = jax.lax.fori_loop(0, steps, step, (buf0, outs0))
    # only the last stage holds real outputs; broadcast to all
    mine = jnp.where(my_idx == n_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(mine, axis_name)


def pipeline_apply(stage_fn: Callable, stacked_params, xs: jax.Array,
                   mesh: Mesh, axis: str = "pp") -> jax.Array:
    """Run ``n_stages`` sequential stages over ``n_micro`` microbatches.

    ``stage_fn(params, x) -> y`` applies ONE stage (x and y same shape).
    ``stacked_params``: pytree whose leaves have leading dim n_stages ==
    mesh axis size (stage i's weights at index i — sharded so each
    device holds exactly its stage). ``xs``: (n_micro, ...) microbatches.
    Returns (n_micro, ...) outputs, replicated."""
    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        dim = leaf.shape[0] if getattr(leaf, "ndim", 0) else None
        if dim != n_stages:
            raise ValueError(
                f"stacked params leading dim {dim} != pipeline "
                f"stages {n_stages}")
    param_specs = jax.tree_util.tree_map(
        lambda t: P(axis, *([None] * (t.ndim - 1))), stacked_params)
    fn = jax.shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis),
        mesh=mesh,
        in_specs=(param_specs, P(*([None] * xs.ndim))),
        out_specs=P(*([None] * xs.ndim)),
    )
    return fn(stacked_params, xs)
