"""Explicit collective matmuls under shard_map — the shuffle, spelled out.

``pjit`` + ``NamedSharding`` lets XLA choose collectives automatically
(:mod:`netsdb_tpu.parallel.mesh`); this module is the explicit form for
when the schedule matters, mirroring the reference's hand-built data
movement 1:1 (SURVEY §2.6):

- reference hash-repartition shuffle + combiners
  (``PipelineStage.cc:1215-1516``) → ``matmul_psum`` /
  ``matmul_psum_scatter`` (contraction-sharded partial products reduced
  over ICI);
- reference broadcast join (``PipelineStage.cc:1518-1650``) →
  ``matmul_allgather`` (gather the small side, compute locally).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_HI = jax.lax.Precision.HIGHEST


def _dot(a, b):
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               precision=_HI,
                               preferred_element_type=jnp.float32)


def matmul_psum(a: jax.Array, b: jax.Array, mesh: Mesh,
                axis: str = "model") -> jax.Array:
    """C = A·B with the CONTRACTION dim sharded: each device multiplies
    its k-slice, then one psum combines partial products — exactly the
    reference's join-on-block-index + FFAggMatrix shuffle, as one ICI
    all-reduce. Output replicated."""

    def local(a_blk, b_blk):
        return jax.lax.psum(_dot(a_blk, b_blk), axis)

    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(None, None))
    return fn(a, b)


def matmul_psum_scatter(a: jax.Array, b: jax.Array, mesh: Mesh,
                        axis: str = "model") -> jax.Array:
    """Same contraction sharding, but the reduction scatters: each device
    keeps one row-shard of C (reduce_scatter ≈ the reference's
    per-destination-node combiner threads, which shipped each partition
    to its owner instead of replicating)."""

    def local(a_blk, b_blk):
        part = _dot(a_blk, b_blk)
        return jax.lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)

    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(axis, None))
    return fn(a, b)


def matmul_allgather(a: jax.Array, b: jax.Array, mesh: Mesh,
                     axis: str = "model") -> jax.Array:
    """C = A·B with A row-sharded and B small: all-gather B (the
    broadcast join's replicated hash table), multiply locally, keep the
    row shard. One all-gather of the small side, no reduction."""

    def local(a_blk, b_blk):
        b_full = jax.lax.all_gather(b_blk, axis, axis=0, tiled=True)
        return _dot(a_blk, b_full)

    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(P(axis, None), P(axis, None)),
                       out_specs=P(axis, None))
    return fn(a, b)


def all_to_all_resharding(x: jax.Array, mesh: Mesh, axis: str,
                          from_dim: int, to_dim: int) -> jax.Array:
    """Re-shard an array from one dim to another with a single
    all-to-all — the primitive under Ulysses sequence parallelism and
    the analogue of the reference's full-shuffle repartition."""

    def local(blk):
        return jax.lax.all_to_all(blk, axis, split_axis=to_dim,
                                  concat_axis=from_dim, tiled=True)

    in_spec = [None] * x.ndim
    in_spec[from_dim] = axis
    out_spec = [None] * x.ndim
    out_spec[to_dim] = axis
    fn = jax.shard_map(local, mesh=mesh, in_specs=(P(*in_spec),),
                       out_specs=P(*out_spec))
    return fn(x)
