"""SUMMA-streamed distributed blocked matmul — pod-scale linear algebra.

Per *Large Scale Distributed Linear Algebra With TPUs* (arxiv
2112.09017), a matmul whose operands exceed one chip's HBM scales by
keeping each mesh participant's PANEL local and moving only one
broadcast panel per step over the interconnect. The reference netsDB
expresses the same algorithm as join-on-block-index + cluster
aggregation shuffled over TCP; ``ops/matmul.py`` collapses it to one
``dot_general`` when the operands fit — this module is the form for
when they DON'T: the left operand lives as arena pages
(``storage/paged.py``) and each participant stages ONLY its own panel
through the bounded ``plan/staging.stage_stream`` pipeline.

Algorithm (1-d mesh of N participants, C = A·B):

* A's row blocks are dealt round-robin to participants (block *i* →
  participant ``i % N``): each stages 1/N of A, host→device, through
  the existing prefetch→upload pipeline.
* B is split into N contraction PANELS (k-slices); participant *d*
  stages only panel *d* (1/N of B).
* Each round dispatches ONE compiled program over the mesh: a scan of
  N SUMMA steps, each broadcasting one participant's B panel over the
  mesh axis (a ``psum`` of the masked panel — the netsDB per-stage
  broadcast, as one collective) and accumulating
  ``A_local[:, panel] @ B_panel`` into the carried C tile. The
  accumulator lives in the scan carry, so XLA updates it in place
  (donation discipline: staged A blocks may be device-CACHE entries
  and are never donated; only the carried C tile is).
* Output C rows land row-sharded like A; each participant's tile is
  pulled per shard and stitched into the host result in block order.

Staged bytes per participant ≈ (|A| + |B|) / N — the panel-staging
proof ``micro_bench --summa`` measures against the replicated-operand
baseline (every participant stages everything).

Device-cache integration: staged A blocks ride the SAME block-granular
:class:`~netsdb_tpu.storage.devcache.DeviceBlockCache` entries as every
other stream — base key ``(scope, "summa", bucket, mesh-label)`` with
the mesh label carrying the participant count and axis, so a warm
re-run under the same mesh serves every panel from HBM with zero arena
reads, and a different mesh shape can never alias.

Runs unchanged on the virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) — the tier-1
fixture (``tests/conftest.py`` ``mesh4``) — and on a real TPU pod.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu import obs

#: stream kind for device-cache keys (a SUMMA panel block is placed on
#: ONE owner device — never interchangeable with a "trows" block)
CACHE_KIND = "summa"


def mesh_label(axis: str, devices) -> str:
    """The sharding component of SUMMA cache keys: axis name AND the
    participant device ids — cached panel blocks are committed to
    specific physical devices, so two device sets of the same SIZE
    must still key apart (a warm run over a different quartet would
    otherwise stitch blocks resident on the wrong devices)."""
    ids = ",".join(str(getattr(d, "id", d)) for d in devices)
    return f"summa[{axis}={ids}]"


def _mesh_over(devices: Sequence, axis: str):
    from jax.sharding import Mesh

    return Mesh(np.asarray(list(devices)), (axis,))


@functools.lru_cache(maxsize=32)
def _round_program(mesh, axis: str, n: int, kp: int):
    """ONE compiled SUMMA round: a scan of ``n`` panel-broadcast +
    accumulate steps under ``shard_map``. Cached per (mesh, shapes)
    so every round of every stream with the same bucket reuses one
    XLA program."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def local(a_blk, b_blk):
        # a_blk: (bucket, n*kp) — this participant's A block, all
        # panel columns; b_blk: (kp, cols) — this participant's B panel
        idx = jax.lax.axis_index(axis)

        def step(c, s):
            # the SUMMA broadcast: participant s's panel to everyone,
            # as one psum of the masked panel (netsDB's per-stage
            # broadcast-to-all-nodes, QuerySchedulerServer.cc:216-330,
            # collapsed to a single collective)
            panel = jax.lax.psum(
                jnp.where(s == idx, b_blk, jnp.zeros_like(b_blk)), axis)
            a_cols = jax.lax.dynamic_slice_in_dim(a_blk, s * kp, kp, 1)
            part = jax.lax.dot_general(
                a_cols, panel, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
            # the C tile accumulates IN PLACE: the carry is dead after
            # each step (immediately rebound), so XLA reuses its buffer
            return c + part, None

        c0 = jnp.zeros((a_blk.shape[0], b_blk.shape[1]), jnp.float32)
        c, _ = jax.lax.scan(step, c0, jnp.arange(n))
        return c

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis, None), P(axis, None)),
                   out_specs=P(axis, None), check_rep=False)
    return jax.jit(fn)


def _stage_b_panels(rhs: np.ndarray, devices: Sequence, axis: str,
                    mesh, staged_bytes: Dict[int, int]):
    """Split B into N contraction panels and stage panel *d* onto
    participant *d* ONLY (1/N of B per host), then assemble the
    k-sharded global — the multi-host
    ``make_array_from_single_device_arrays`` idiom from 2112.09017
    (each process contributes just its addressable shard)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.sharding import SingleDeviceSharding

    from netsdb_tpu.storage.devcache import to_device

    n = len(devices)
    k = rhs.shape[0]
    kp = -(-k // n)  # panel rows (ceil)
    k_pad = kp * n
    if k_pad > k:
        rhs = np.pad(rhs, ((0, k_pad - k), (0, 0)))
    parts = []
    for d in range(n):
        panel = np.ascontiguousarray(rhs[d * kp:(d + 1) * kp])
        parts.append(to_device(panel, SingleDeviceSharding(devices[d])))
        staged_bytes[d] = staged_bytes.get(d, 0) + panel.nbytes
    b_global = jax.make_array_from_single_device_arrays(
        (k_pad, rhs.shape[1]), NamedSharding(mesh, P(axis, None)), parts)
    return b_global, kp, k_pad


def summa_matmul_streamed(store, name: str, rhs: np.ndarray,
                          devices: Optional[Sequence] = None,
                          axis: str = "data",
                          stage_depth: Optional[int] = None,
                          cache=None, cache_scope: Optional[str] = None,
                          stats_out: Optional[Dict[str, Any]] = None
                          ) -> np.ndarray:
    """``out = M @ rhs`` with M streamed from the page arena and the
    compute SUMMA-distributed over ``devices`` (default: every device).

    ``store`` is a :class:`~netsdb_tpu.storage.paged.PagedTensorStore`
    holding matrix ``name``. Each participant stages only its own
    panel (see module docstring); the whole stream runs ONE compiled
    round program. ``cache``/``cache_scope`` opt the staged A blocks
    into the block-granular device cache (partial mode) under the
    SUMMA mesh label; ``stats_out`` (a dict) receives the run's
    per-participant staged-byte table and round/broadcast counts —
    the bench's panel-staging proof."""
    import contextlib

    import jax
    from jax.sharding import SingleDeviceSharding

    from netsdb_tpu.plan import staging
    from netsdb_tpu.storage.devcache import to_device

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n < 2:
        raise ValueError("SUMMA needs >= 2 mesh participants; "
                         "use matmul_streamed on one device")
    rhs = np.asarray(rhs)
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs = rhs[:, None]
    (rows, k), (rb, _), _dtype = store.meta(name)
    if rhs.shape[0] != k:
        raise ValueError(f"matmul contraction mismatch: {name} is "
                         f"{rows}x{k}, rhs {rhs.shape}")
    mesh = _mesh_over(devices, axis)
    cfg = store.config
    depth = getattr(cfg, "stage_depth", 2) if stage_depth is None \
        else stage_depth
    bucketing = getattr(cfg, "shape_bucketing", True)
    density = getattr(cfg, "bucket_density", 2)
    bucket = staging.pad_rows_target(rb, bucketing, density=density)

    staged_bytes: Dict[int, int] = {}
    b_global, kp, k_pad = _stage_b_panels(rhs, devices, axis, mesh,
                                          staged_bytes)
    program = _round_program(mesh, axis, n, kp)

    ranges = store.block_ranges(name)
    start_to_idx = {s: i for i, (s, _e) in enumerate(ranges)}

    def place(item):
        """Pad one host block to (bucket, k_pad) and upload it to its
        PANEL OWNER's device only — the per-shard upload leg. Runs on
        the staging thread (bounded pipeline)."""
        s0, block = item
        i = start_to_idx[s0]
        d = i % n
        nrows = block.shape[0]
        pad_r = bucket - nrows
        pad_c = k_pad - block.shape[1]
        if pad_r or pad_c:
            block = np.pad(block, ((0, max(pad_r, 0)), (0, pad_c)))
        placed = to_device(block, SingleDeviceSharding(devices[d]))
        staged_bytes[d] = staged_bytes.get(d, 0) + block.nbytes
        return i, nrows, placed

    partial = None
    if cache is not None and cache_scope is not None \
            and getattr(cache, "partial", False) and cache.enabled \
            and ranges:
        partial = staging.PartialPlan(
            cache, (str(cache_scope), CACHE_KIND, bucket,
                    mesh_label(axis, devices)), ranges,
            lambda idxs: store.stream_blocks(name, blocks=idxs))

    out = np.zeros((rows, rhs.shape[1]), np.float32)
    zeros_for: Dict[int, Any] = {}  # tail-round filler, one per device

    def filler(d):
        if d not in zeros_for:
            zeros_for[d] = to_device(
                np.zeros((bucket, k_pad), np.float32),
                SingleDeviceSharding(devices[d]))
        return zeros_for[d]

    rounds = bcasts = 0
    compute_s = 0.0
    stream = staging.stage_stream(
        store.stream_blocks(name) if partial is None else None,
        place, depth=depth, name=f"summa:{name}", partial=partial,
        scope=str(cache_scope) if cache_scope is not None else None)
    with contextlib.closing(stream):
        batch: List[Tuple[int, int, Any]] = []

        def run_round(batch):
            nonlocal rounds, bcasts, compute_s
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            per_dev = {i % n: (i, nv, arr) for i, nv, arr in batch}
            parts = [per_dev[d][2] if d in per_dev else filler(d)
                     for d in range(n)]
            a_global = _jax.make_array_from_single_device_arrays(
                (n * bucket, k_pad), NamedSharding(mesh, P(axis, None)),
                parts)
            t0 = time.perf_counter()
            c = program(a_global, b_global)
            shards = {sh.index[0].start // bucket: sh
                      for sh in c.addressable_shards}
            for d, (i, nv, _arr) in per_dev.items():
                s0, _e0 = ranges[i]
                out[s0:s0 + nv] = np.asarray(shards[d].data)[:nv]
            compute_s += time.perf_counter() - t0
            rounds += 1
            bcasts += n
            obs.REGISTRY.counter("summa.rounds").inc()
            obs.REGISTRY.counter("summa.panel_bcasts").inc(n)
            obs.REGISTRY.counter("summa.panel_bytes").inc(
                n * int(b_global.nbytes // n))
            # the per-step operator record: EXPLAIN decomposes a SUMMA
            # node into panel broadcasts vs compute
            obs.operators.op_add("summa.rounds")
            obs.operators.op_add("summa.panel_bcasts", n)
            obs.operators.op_add("summa.compute_s",
                                 time.perf_counter() - t0)

        for item in stream:
            batch.append(item)
            if len(batch) == n:
                run_round(batch)
                batch = []
        if batch:
            run_round(batch)

    total_staged = sum(staged_bytes.values())
    obs.REGISTRY.counter("summa.staged_bytes").inc(total_staged)
    if stats_out is not None:
        stats_out.update({
            "participants": n, "rounds": rounds,
            "panel_bcasts": bcasts, "compute_s": compute_s,
            "staged_bytes_per_participant": dict(staged_bytes),
            "staged_bytes_total": total_staged,
            "operand_bytes": int(rows * k * 4 + k * rhs.shape[1] * 4),
        })
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------
# 2-d grid SUMMA (arxiv 2112.09017 §III: the true processor-grid form)
# ---------------------------------------------------------------------

#: mesh axis names of the 2-d grid (rows × columns of the processor
#: grid — NOT matrix rows/cols; each device owns one (row, col) tile)
GRID_AXES = ("gr", "gc")


def grid_shape(config, num_devices: int) -> Optional[Tuple[int, int]]:
    """Parse the ``config.summa_grid`` knob ("PRxPC" string or a
    (pr, pc) pair) into a processor-grid shape, or None when the knob
    is unset / the device set cannot fill the grid. A malformed value
    raises — a typo'd grid silently running 1-d would invalidate every
    staging-fraction expectation downstream."""
    raw = getattr(config, "summa_grid", None)
    if not raw:
        return None
    if isinstance(raw, str):
        try:
            pr, pc = (int(p) for p in raw.lower().split("x"))
        except ValueError:
            raise ValueError(f"summa_grid must be 'PRxPC', got {raw!r}")
    else:
        pr, pc = (int(p) for p in raw)
    if pr < 1 or pc < 1 or pr * pc < 2:
        raise ValueError(f"summa_grid needs >= 2 participants, got "
                         f"{pr}x{pc}")
    if pr * pc > num_devices:
        return None  # grid does not fit this host's device set
    return pr, pc


def grid_label(devices, pr: int, pc: int) -> str:
    """Cache-key sharding component for grid layouts — carries the grid
    SHAPE and the participant device ids, so a 2x2 layout can never
    alias a 1x4 (or a different quartet's 2x2): each caches blocks
    split and committed to different physical devices."""
    ids = ",".join(str(getattr(d, "id", d)) for d in devices)
    return f"summa[{pr}x{pc}={ids}]"


def _grid_mesh(devices: Sequence, pr: int, pc: int):
    from jax.sharding import Mesh

    return Mesh(np.asarray(list(devices)[:pr * pc]).reshape(pr, pc),
                GRID_AXES)


@functools.lru_cache(maxsize=32)
def _grid_program(mesh, pr: int, pc: int, kp: int):
    """ONE compiled 2-d SUMMA round: a scan of ``pr*pc`` steps, each
    broadcasting one kp-slice of A along the grid's COLUMN axis and
    one kp-slice of B along its ROW axis (two masked psums — the dual
    of the 1-d panel broadcast), then accumulating the local C tile.
    Per 2112.09017 both matrix dimensions distribute: a device holds
    1/(pr·pc) of A, of B and of C."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    steps = pr * pc

    def local(a_blk, b_blk):
        # a_blk: (rows_local, pr*kp) — this device's tile of A (grid
        # column c owns contraction panels c*pr .. c*pr+pr-1);
        # b_blk: (pc*kp, cols_local) — its tile of B (grid row r owns
        # panels r*pc .. r*pc+pc-1)
        r = jax.lax.axis_index(GRID_AXES[0])
        c = jax.lax.axis_index(GRID_AXES[1])

        def step(acc, s):
            # panel s of A lives on grid column s//pr at local column
            # offset (s%pr)*kp: broadcast it across the column axis
            a_sl = jax.lax.psum(
                jnp.where(s // pr == c,
                          jax.lax.dynamic_slice_in_dim(
                              a_blk, (s % pr) * kp, kp, 1),
                          jnp.zeros((a_blk.shape[0], kp), a_blk.dtype)),
                GRID_AXES[1])
            # panel s of B lives on grid row s//pc at local row offset
            # (s%pc)*kp: broadcast it across the row axis
            b_sl = jax.lax.psum(
                jnp.where(s // pc == r,
                          jax.lax.dynamic_slice_in_dim(
                              b_blk, (s % pc) * kp, kp, 0),
                          jnp.zeros((kp, b_blk.shape[1]), b_blk.dtype)),
                GRID_AXES[0])
            part = jax.lax.dot_general(
                a_sl, b_sl, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
            return acc + part, None

        acc0 = jnp.zeros((a_blk.shape[0], b_blk.shape[1]), jnp.float32)
        acc, _ = jax.lax.scan(step, acc0, jnp.arange(steps))
        return acc

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(*GRID_AXES), P(*GRID_AXES)),
                   out_specs=P(*GRID_AXES), check_rep=False)
    return jax.jit(fn)


def _stage_b_grid(rhs: np.ndarray, devices: Sequence, mesh,
                  pr: int, pc: int, kp: int,
                  staged_bytes: Dict[int, int]):
    """Tile B over the full grid: device (r, c) stages only rows
    ``[r·pc·kp, (r+1)·pc·kp)`` × its 1/pc column slice — 1/(pr·pc) of
    B per device, the both-dims-exceed-one-host layout."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.sharding import SingleDeviceSharding

    from netsdb_tpu.storage.devcache import to_device

    k_pad = pr * pc * kp
    cols = rhs.shape[1]
    cpc = -(-cols // pc)
    cols_pad = cpc * pc
    pad = ((0, k_pad - rhs.shape[0]), (0, cols_pad - cols))
    if any(p for _s, p in pad):
        rhs = np.pad(rhs, pad)
    parts = []
    rows_per = pc * kp
    for r in range(pr):
        for c in range(pc):
            tile = np.ascontiguousarray(
                rhs[r * rows_per:(r + 1) * rows_per,
                    c * cpc:(c + 1) * cpc])
            d = r * pc + c
            parts.append(to_device(tile,
                                   SingleDeviceSharding(devices[d])))
            staged_bytes[d] = staged_bytes.get(d, 0) + tile.nbytes
    b_global = jax.make_array_from_single_device_arrays(
        (k_pad, cols_pad), NamedSharding(mesh, P(*GRID_AXES)), parts)
    return b_global, cols_pad, cpc


def summa_grid_matmul_streamed(store, name: str, rhs: np.ndarray,
                               devices: Optional[Sequence] = None,
                               grid: Tuple[int, int] = (2, 2),
                               stage_depth: Optional[int] = None,
                               cache=None,
                               cache_scope: Optional[str] = None,
                               stats_out: Optional[Dict[str, Any]] = None
                               ) -> np.ndarray:
    """``out = M @ rhs`` over a true 2-d processor grid (2112.09017
    §III): A's row blocks deal round-robin over GRID ROWS and split
    column-wise over GRID COLUMNS, B tiles over the whole grid — every
    device stages ~1/(pr·pc) of each operand, the layout for operands
    whose BOTH dims exceed one host. Each round runs ONE compiled scan
    of pr·pc dual-broadcast steps (``_grid_program``). Staged A tiles
    ride the block-granular device cache under the grid label — a
    layout change (1-d ↔ 2-d) re-keys, and ``parallel/reshard.py``
    moves the cached blocks between layouts instead of re-staging."""
    import contextlib

    import jax
    from jax.sharding import SingleDeviceSharding

    from netsdb_tpu.plan import staging
    from netsdb_tpu.storage.devcache import to_device

    pr, pc = int(grid[0]), int(grid[1])
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < pr * pc:
        raise ValueError(f"summa grid {pr}x{pc} needs {pr * pc} "
                         f"devices, have {len(devices)}")
    devices = devices[:pr * pc]
    rhs = np.asarray(rhs)
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs = rhs[:, None]
    (rows, k), (rb, _), _dtype = store.meta(name)
    if rhs.shape[0] != k:
        raise ValueError(f"matmul contraction mismatch: {name} is "
                         f"{rows}x{k}, rhs {rhs.shape}")
    mesh = _grid_mesh(devices, pr, pc)
    cfg = store.config
    depth = getattr(cfg, "stage_depth", 2) if stage_depth is None \
        else stage_depth
    bucketing = getattr(cfg, "shape_bucketing", True)
    density = getattr(cfg, "bucket_density", 2)
    bucket = staging.pad_rows_target(rb, bucketing, density=density)

    steps = pr * pc
    kp = -(-k // steps)
    k_pad = steps * kp
    apc = pr * kp  # A columns per grid column
    staged_bytes: Dict[int, int] = {}
    b_global, cols_pad, cpc = _stage_b_grid(rhs, devices, mesh, pr, pc,
                                            kp, staged_bytes)
    program = _grid_program(mesh, pr, pc, kp)

    ranges = store.block_ranges(name)
    start_to_idx = {s: i for i, (s, _e) in enumerate(ranges)}

    def place(item):
        """Pad one host block to (bucket, k_pad), split it into pc
        column tiles and upload tile c to grid device (i % pr, c) —
        each device receives 1/(pr·pc) of A. Runs on the staging
        thread; the tuple of placed tiles is what the partial cache
        records per block range."""
        s0, block = item
        i = start_to_idx[s0]
        r = i % pr
        nrows = block.shape[0]
        pad_r = bucket - nrows
        pad_c = k_pad - block.shape[1]
        if pad_r > 0 or pad_c:
            block = np.pad(block, ((0, max(pad_r, 0)), (0, pad_c)))
        tiles = []
        for c in range(pc):
            tile = np.ascontiguousarray(block[:, c * apc:(c + 1) * apc])
            d = r * pc + c
            tiles.append(to_device(tile,
                                   SingleDeviceSharding(devices[d])))
            staged_bytes[d] = staged_bytes.get(d, 0) + tile.nbytes
        return i, nrows, tuple(tiles)

    partial = None
    if cache is not None and cache_scope is not None \
            and getattr(cache, "partial", False) and cache.enabled \
            and ranges:
        partial = staging.PartialPlan(
            cache, (str(cache_scope), CACHE_KIND, bucket,
                    grid_label(devices, pr, pc)), ranges,
            lambda idxs: store.stream_blocks(name, blocks=idxs))

    out = np.zeros((rows, rhs.shape[1]), np.float32)
    zeros_for: Dict[int, Any] = {}

    def filler(d):
        if d not in zeros_for:
            zeros_for[d] = to_device(
                np.zeros((bucket, apc), np.float32),
                SingleDeviceSharding(devices[d]))
        return zeros_for[d]

    rounds = nsteps = 0
    compute_s = 0.0
    out_cols = rhs.shape[1]

    def run_round(batch):
        nonlocal rounds, nsteps, compute_s
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        per_row = {i % pr: (i, nv, tiles) for i, nv, tiles in batch}
        parts = []
        for r in range(pr):
            for c in range(pc):
                if r in per_row:
                    parts.append(per_row[r][2][c])
                else:
                    parts.append(filler(r * pc + c))
        a_global = _jax.make_array_from_single_device_arrays(
            (pr * bucket, k_pad),
            NamedSharding(mesh, P(*GRID_AXES)), parts)
        t0 = time.perf_counter()
        cg = program(a_global, b_global)
        # stitch: row block i owns grid-row i%pr's pc column shards
        by_tile = {(sh.index[0].start // bucket,
                    sh.index[1].start // cpc): sh
                   for sh in cg.addressable_shards}
        for r, (i, nv, _tiles) in per_row.items():
            s0, _e0 = ranges[i]
            row = np.concatenate(
                [np.asarray(by_tile[(r, c)].data) for c in range(pc)],
                axis=1)
            out[s0:s0 + nv] = row[:nv, :out_cols]
        compute_s += time.perf_counter() - t0
        rounds += 1
        nsteps += steps
        obs.REGISTRY.counter("summa.grid_rounds").inc()
        obs.REGISTRY.counter("summa.grid_steps").inc(steps)
        # each step broadcasts one A slice (column axis) and one B
        # slice (row axis): the dual of the 1-d panel broadcast
        obs.REGISTRY.counter("summa.grid_panel_bcasts").inc(2 * steps)
        obs.operators.op_add("summa.grid_rounds")
        obs.operators.op_add("summa.grid_panel_bcasts", 2 * steps)
        obs.operators.op_add("summa.compute_s",
                             time.perf_counter() - t0)

    stream = staging.stage_stream(
        store.stream_blocks(name) if partial is None else None,
        place, depth=depth, name=f"summa2d:{name}", partial=partial,
        scope=str(cache_scope) if cache_scope is not None else None)
    with contextlib.closing(stream):
        batch: List[Tuple[int, int, Any]] = []
        for item in stream:
            batch.append(item)
            if len(batch) == pr:
                run_round(batch)
                batch = []
        if batch:
            run_round(batch)

    total_staged = sum(staged_bytes.values())
    obs.REGISTRY.counter("summa.grid_staged_bytes").inc(total_staged)
    if stats_out is not None:
        stats_out.update({
            "participants": pr * pc, "grid": (pr, pc),
            "rounds": rounds, "steps": nsteps,
            "panel_bcasts": 2 * nsteps, "compute_s": compute_s,
            "staged_bytes_per_participant": dict(staged_bytes),
            "staged_bytes_total": total_staged,
            "operand_bytes": int(rows * k * 4 + k * rhs.shape[1] * 4),
        })
    return out[:, 0] if squeeze else out


def summa_matmul_resident(a, b, devices: Optional[Sequence] = None,
                          axis: str = "data"):
    """C = A·B for RESIDENT arrays through one SUMMA round — the
    ``ops/matmul.py`` leg of the ``distributed_matmul`` knob: A's rows
    shard over the mesh, B splits into contraction panels, one scan of
    panel broadcasts accumulates each participant's C tile in place.
    Returns a row-sharded global jax array of logical shape
    ``(A.rows, B.cols)`` (f32 accumulation, like the blocked engine)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mesh = _mesh_over(devices, axis)
    m, k = a.shape
    k2, cols = b.shape
    if k != k2:
        raise ValueError(f"matmul contraction mismatch {a.shape} x "
                         f"{b.shape}")
    kp = -(-k // n)
    mp = -(-m // n)
    a = jnp.pad(jnp.asarray(a), ((0, mp * n - m), (0, kp * n - k)))
    b = jnp.pad(jnp.asarray(b), ((0, kp * n - k2), (0, 0)))
    a = jax.device_put(a, NamedSharding(mesh, P(axis, None)))
    b = jax.device_put(b, NamedSharding(mesh, P(axis, None)))
    program = _round_program(mesh, axis, n, kp)
    obs.REGISTRY.counter("summa.rounds").inc()
    obs.REGISTRY.counter("summa.panel_bcasts").inc(n)
    obs.operators.op_add("summa.rounds")
    obs.operators.op_add("summa.panel_bcasts", n)
    return program(a, b)[:m, :cols]
