"""Device mesh runtime — the distributed layer, TPU-native.

What the reference builds with a hand-written socket runtime — dispatcher
partitioning across workers (``src/dispatcher/headers/PartitionPolicy.h:29``),
per-stage broadcast to all nodes (``QuerySchedulerServer.cc:216-330``),
hash-repartition shuffle with combiner threads + snappy over TCP
(``PipelineStage.cc:1215-1516``), broadcast-join replication
(``PipelineStage.cc:1518-1650``) — is here a ``jax.sharding.Mesh`` plus
``NamedSharding`` placements: XLA inserts the all-gathers / psums /
all-to-alls over ICI/DCN that those threads implemented by hand
(SURVEY §2.6 mapping table).

Mesh convention: axes ``("data", "model")`` — batch rows shard over
``data`` (the dispatcher's round-robin across workers), weight rows/cols
shard over ``model`` (the hash-partitioned join side); replication over
an axis is the broadcast join. Multi-host: call
``jax.distributed.initialize`` before building the mesh; same code runs
on a virtual CPU mesh for tests (the pseudo-cluster analogue).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from netsdb_tpu.core.blocked import BlockedTensor

_default_mesh: Optional[Mesh] = None


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Tuple[str, ...] = ("data", "model"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over available devices. Default shape: all devices on
    ``data`` (pure data parallelism, the reference's only cross-node
    strategy), 1 on ``model``."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def _divisible_spec(t: BlockedTensor, mesh: Mesh, spec: P) -> P:
    """Drop sharding on dims the padded shape can't divide evenly —
    mirrors the dispatcher falling back to DEFAULT policy when a set
    can't be partitioned by the preferred lambda."""
    fixed = []
    for dim, axis in enumerate(spec):
        if axis is None:
            fixed.append(None)
            continue
        size = np.prod([mesh.shape[a] for a in (axis if isinstance(axis, tuple) else (axis,))])
        if t.meta.padded_shape[dim] % size == 0:
            fixed.append(axis)
        else:
            fixed.append(None)
    return P(*fixed)


def shard_blocked(t: BlockedTensor, mesh: Optional[Mesh] = None,
                  spec: Optional[P] = None) -> BlockedTensor:
    """Place a blocked tensor on the mesh with a NamedSharding. The block
    grid is the natural granularity: padded dims are whole multiples of
    the block, so any mesh axis dividing the grid gives block-aligned
    shards (netsDB's "blocks live on the node that hashed them")."""
    mesh = mesh or default_mesh()
    if spec is None:
        spec = P(*([None] * t.meta.rank))
    spec = _divisible_spec(t, mesh, spec)
    sharding = NamedSharding(mesh, spec)
    return t.with_data(jax.device_put(t.data, sharding))


def replicate(t: BlockedTensor, mesh: Optional[Mesh] = None) -> BlockedTensor:
    """Replicate across the mesh — the broadcast-join placement
    (``BroadcastJoinBuildHTJobStage``: model weights on every node)."""
    mesh = mesh or default_mesh()
    return t.with_data(
        jax.device_put(t.data, NamedSharding(mesh, P(*([None] * t.meta.rank))))
    )
