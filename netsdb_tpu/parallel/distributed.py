"""Multi-host runtime — the master/worker cluster layer, TPU-native.

The reference runs a hand-rolled cluster: ``conf/serverlist``, ssh'd
worker launches, TCP control RPC, static membership (SURVEY §3.1, §5 —
no failure handling). On TPU pods the control plane is JAX's
single-controller runtime: ``jax.distributed.initialize`` connects the
per-host processes, devices form one global mesh, and XLA routes
collectives over ICI within a slice and DCN across slices. This module
is the thin layer that replaces ``startMaster.sh``/``startWorkers.sh``:

- :func:`initialize_cluster` — per-host bring-up (coordinator address ≈
  the master line of ``conf/serverlist``);
- :func:`hybrid_mesh` — (dcn, ici) two-level mesh so cross-host axes
  only carry DCN-friendly traffic (data parallelism outer, model/
  sequence parallelism inner);
- :func:`cluster_info` — the ResourceManager's getAllResources
  equivalent.

Single-process multi-device (the CI/virtual-device case) skips
initialize and still produces correct meshes, mirroring the
pseudo-cluster fixture.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def initialize_cluster(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> bool:
    """Connect this host into the cluster. No-ops (returns False) when
    single-process. Args fall back to the standard env vars, so launch
    scripts stay trivial (the startWorkers.sh role)."""
    coordinator_address = coordinator_address or os.environ.get(
        "NETSDB_TPU_COORDINATOR")
    if coordinator_address is None and num_processes is None:
        return False  # single-controller, single-host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def hybrid_mesh(ici_shape: Sequence[int],
                ici_axes: Sequence[str] = ("data", "model"),
                dcn_axis: str = "hosts") -> Mesh:
    """Mesh with the slowest (DCN) dimension outermost: hosts × ici.
    Shard batch over ``hosts`` (pure data parallelism — one gradient
    all-reduce over DCN per step) and tensors over the ici axes."""
    n_hosts = jax.process_count()
    if n_hosts > 1:
        # never fall back silently here: a hosts=1 mesh over global
        # devices would route model/sequence collectives over DCN
        from jax.experimental import mesh_utils

        # The DCN granule is the slice on real pods (devices carry
        # distinct slice_index) and the process otherwise (e.g. the
        # two-process CPU smoke test). Decide UP FRONT from the device
        # topology — a blanket exception fallback would mask genuine
        # shape errors — and size the DCN dimension by GRANULE count
        # (on a 2-slice pod with 2 hosts/slice that is 2, not 4).
        n_slices = len({getattr(d, "slice_index", None)
                        for d in jax.devices()})
        by_process = n_slices <= 1
        n_granules = n_hosts if by_process else n_slices
        dcn_shape = (n_granules,) + (1,) * (len(ici_shape) - 1)
        devs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_shape), dcn_mesh_shape=dcn_shape,
            process_is_granule=by_process)
        return Mesh(devs.reshape((n_granules,) + tuple(ici_shape)),
                    (dcn_axis,) + tuple(ici_axes))
    devices = jax.devices()
    total = int(np.prod(ici_shape))
    if total != len(devices):
        raise ValueError(f"ici shape {ici_shape} != {len(devices)} devices")
    arr = np.asarray(devices).reshape((1,) + tuple(ici_shape))
    return Mesh(arr, (dcn_axis,) + tuple(ici_axes))


def cluster_info() -> Dict:
    """getAllResources equivalent (reference
    ``ResourceManagerServer.h:16-33``)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": [str(d) for d in jax.local_devices()],
        "global_device_count": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind if jax.devices() else None,
    }
