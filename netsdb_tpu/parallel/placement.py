"""Declarative set placement — distribution through the database API.

In the reference, distribution is a property of the *set*: ingest
partitions every set across workers by a PartitionPolicy chosen at
``createSet`` (``src/dispatcher/headers/PartitionPolicy.h:27-50``), and
every scheduled stage then runs against local partitions on all nodes
(``src/serverFunctionalities/source/QuerySchedulerServer.cc:216-330``).
The TPU-native equivalent of "which worker holds which partition" is a
``jax.sharding.NamedSharding``: this module gives sets a *declarative*,
catalog-serializable placement — mesh axes + a PartitionSpec — that
``Client.create_set(placement=...)`` records and the data path applies,
so every downstream jit (the query executor) sees committed shardings
and XLA inserts the collectives the reference's shuffle threads
implemented by hand.

Why declarative rather than a live ``Mesh`` object: placements live in
the catalog (sqlite JSON meta) and travel over the serve protocol
(msgpack), so they must be data, not device handles. ``mesh()``
materializes the same ``Mesh`` for equal axis descriptions (cached), so
NamedShardings built from one Placement compare equal across calls —
a requirement for jit cache hits.

Degraded-hardware rule: if the process has fewer devices than the
declared mesh (the single-chip bench vs the 8-device test mesh), the
placement collapses to the trivial single-device mesh — the same
fallback the reference dispatcher makes when a set cannot be
partitioned by the preferred policy (``PartitionPolicy.h:40``,
DefaultPolicy). Data stays correct; parallelism degrades.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _canon_axis(entry: Any) -> Any:
    """Spec entry → hashable canonical form (None | str | tuple[str])."""
    if entry is None or isinstance(entry, str):
        return entry
    return tuple(entry)


@dataclasses.dataclass(frozen=True)
class Placement:
    """Mesh axes + per-dimension PartitionSpec for one set.

    ``axes``: ((name, size), ...) — size 0 means "all devices on this
    axis" (resolved at ``mesh()`` time, like the dispatcher's
    round-robin over however many workers are registered).
    ``spec``: one entry per tensor dimension: ``None`` (replicated),
    an axis name, or a tuple of axis names. For a :class:`ColumnTable`
    set the spec has one entry — the row dimension.
    """

    axes: Tuple[Tuple[str, int], ...]
    spec: Tuple[Any, ...]

    # --- constructors -------------------------------------------------
    @staticmethod
    def data_parallel(ndim: int = 1, n_devices: int = 0,
                      axis: str = "data") -> "Placement":
        """Rows over ``axis``, everything else replicated — the
        reference's RoundRobin/Hash partitioning of a set's pages."""
        return Placement(((axis, n_devices),),
                         (axis,) + (None,) * (ndim - 1))

    @staticmethod
    def replicated(ndim: int = 2, n_devices: int = 0,
                   axis: str = "data") -> "Placement":
        """Whole copy on every device — the broadcast-join placement
        (small dim tables / model weights on every node)."""
        return Placement(((axis, n_devices),), (None,) * ndim)

    # --- catalog round-trip -------------------------------------------
    def to_meta(self) -> Dict[str, Any]:
        spec = [list(s) if isinstance(s, tuple) else s for s in self.spec]
        return {"axes": [list(a) for a in self.axes], "spec": spec}

    @staticmethod
    def from_meta(meta: Optional[Dict[str, Any]]) -> Optional["Placement"]:
        if not meta:
            return None
        axes = tuple((str(n), int(s)) for n, s in meta["axes"])
        spec = tuple(_canon_axis(s) for s in meta["spec"])
        return Placement(axes, spec)

    # --- materialization ----------------------------------------------
    def resolved_axes(self,
                      n_devices: Optional[int] = None) -> Tuple[Tuple[str, int], ...]:
        """Axis sizes with 0 resolved to "the remaining devices" and the
        whole shape collapsed to 1s when the process can't supply enough
        devices (degraded-hardware rule in the module docstring)."""
        n = n_devices if n_devices is not None else len(jax.devices())
        fixed = int(np.prod([s for _, s in self.axes if s > 0] or [1]))
        free = sum(1 for _, s in self.axes if s == 0)
        if free > 1:
            # "all remaining devices" on two axes is ambiguous — there is
            # no canonical factorization of the remainder. The reference
            # dispatcher has the same rule: a set either names its
            # partition counts or takes the single DEFAULT policy
            # (PartitionPolicy.h:29); it never guesses a 2-d split.
            raise ValueError(
                f"placement axes {self.axes}: at most one axis may have "
                f"size 0 (= all remaining devices); {free} do")
        remaining = n // fixed if fixed <= n else 0
        out = []
        for name, size in self.axes:
            if size == 0:
                size = max(1, remaining)
            out.append((name, size))
        if int(np.prod([s for _, s in out])) > n:
            return tuple((name, 1) for name, _ in self.axes)
        return tuple(out)

    def mesh(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = tuple(devices if devices is not None else jax.devices())
        axes = self.resolved_axes(len(devices))
        return _cached_mesh(axes, devices)

    def sharding(self, devices: Optional[Sequence[jax.Device]] = None
                 ) -> NamedSharding:
        return NamedSharding(self.mesh(devices), P(*self.spec))

    def axis_size(self, devices: Optional[Sequence[jax.Device]] = None) -> int:
        """Total number of shards along the sharded dimensions — the
        row-padding granularity for ColumnTables."""
        mesh = self.mesh(devices)
        total = 1
        for entry in self.spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                total *= mesh.shape[ax]
        return total

    def label(self) -> str:
        """Human/history-DB form, e.g. ``data=8:P('data',None)``."""
        ax = ",".join(f"{n}={s}" for n, s in self.axes)
        sp = ",".join("None" if s is None else str(s) for s in self.spec)
        return f"mesh[{ax}]:P({sp})"

    # --- data placement ----------------------------------------------
    def apply(self, value: Any) -> Any:
        """Place a stored value on this placement's mesh. Dispatches on
        value kind: BlockedTensor (block-grid divisibility fallback,
        like the dispatcher's DEFAULT policy), ColumnTable (rows padded
        to the shard granularity with ``valid=False`` — filters never
        shrink arrays, so padding rides the existing mask algebra),
        bare arrays."""
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.parallel.mesh import shard_blocked
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(value, BlockedTensor):
            return shard_blocked(value, self.mesh(), P(*self.spec))
        if isinstance(value, ColumnTable):
            return shard_table(value, self)
        if isinstance(value, (jax.Array, np.ndarray)):
            return jax.device_put(value, self.sharding())
        return value


def shard_table(table, placement: Placement):
    """Mesh-shard a ColumnTable's rows: pad to the shard granularity
    with invalid rows (``device_put`` requires even division), then
    place every column and the validity mask with the placement's
    sharding. The padded rows are masked out of every aggregate by the
    table's existing validity algebra (``table.py`` design rule:
    filters never shrink arrays)."""
    import jax.numpy as jnp

    from netsdb_tpu.relational.table import ColumnTable

    if len(placement.spec) != 1:
        raise ValueError(
            f"table placement needs a 1-d spec (rows); got {placement.spec}")
    n = table.num_rows
    div = placement.axis_size()
    pad = (-n) % div
    sharding = placement.sharding()
    cols = {}
    for name, col in table.cols.items():
        if pad:
            col = jnp.concatenate(
                [col, jnp.zeros((pad,) + col.shape[1:], col.dtype)])
        cols[name] = jax.device_put(col, sharding)
    valid = table.mask()
    if pad:
        valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
    elif table.valid is None and div == 1:
        valid = None  # unpadded single-shard: keep the fast no-mask path
    if valid is not None:
        valid = jax.device_put(valid, sharding)
    return ColumnTable(cols, table.dicts, valid)


# --- mesh cache -------------------------------------------------------
# Same axes + same devices must yield the SAME Mesh object so that
# NamedShardings compare equal and jit caches hit across jobs.
_mesh_cache: Dict[Tuple, Mesh] = {}
_mesh_lock = threading.Lock()


def _cached_mesh(axes: Tuple[Tuple[str, int], ...],
                 devices: Tuple[jax.Device, ...]) -> Mesh:
    need = int(np.prod([s for _, s in axes]))
    if need > len(devices):
        raise ValueError(f"placement axes {axes} need {need} devices, "
                         f"have {len(devices)}")
    key = (axes, tuple(d.id for d in devices[:need]))
    with _mesh_lock:
        mesh = _mesh_cache.get(key)
        if mesh is None:
            arr = np.asarray(devices[:need]).reshape([s for _, s in axes])
            mesh = Mesh(arr, tuple(n for n, _ in axes))
            _mesh_cache[key] = mesh
        return mesh
