"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference's only "scale the big dimension" mechanism is its
relational SUMMA shuffle (SURVEY §2.6/§5); the TPU framework makes long
sequences first-class with the two standard schemes:

- **Ring attention** (`ring_attention`): q/k/v sharded on the sequence
  axis; k/v blocks rotate around the mesh ring with ``ppermute`` while
  each device accumulates its queries' online-softmax state — ICI
  transfers overlap compute, sequence length scales with the number of
  devices. Causal masking uses global block offsets.
- **Ulysses / all-to-all** (`ulysses_attention`): ``all_to_all``
  re-shards from sequence-parallel to head-parallel, runs full local
  attention per head group, and re-shards back — two collectives,
  no ring.

Both run under ``shard_map`` over a named mesh axis and are validated
against single-device attention on the virtual CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from netsdb_tpu.ops.attention import NEG_INF, _block_attn, attention_dispatch


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: float):
    """Per-device body: rotate k/v around the ring, fold each arriving
    block into the online-softmax accumulator (naive XLA fold — the
    off-TPU / odd-shape fallback)."""
    n_dev = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    q = q * scale

    q_pos = (my_idx * s_local + jnp.arange(s_local))[:, None]

    def step(i, carry):
        num, den, mx, k_cur, v_cur = carry
        # rotation sends j→j+1, so after i steps device m holds the block
        # that ORIGINATED at device (m - i) % n
        src = (my_idx - i) % n_dev
        k_pos = (src * s_local + jnp.arange(s_local))[None, :]
        mask = (q_pos >= k_pos) if causal else jnp.ones(
            (s_local, s_local), jnp.bool_)
        num, den, mx = _block_attn(q, k_cur, v_cur, num, den, mx, mask)
        # rotate: pass k/v to the next device in the ring
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return num, den, mx, k_nxt, v_nxt

    # derive initial carries from q so they inherit its varying manual
    # axis (a plain zeros() is axis-invariant and fails scan's carry check)
    num0 = jnp.zeros_like(q)
    den0 = jnp.zeros_like(q[..., :1])
    max0 = jnp.full_like(q[..., :1], NEG_INF)
    num, den, _, _, _ = jax.lax.fori_loop(
        0, n_dev, step, (num0, den0, max0, k, v))
    return num / jnp.maximum(den, 1e-30)


def _ring_attention_flash_local(q, k, v, axis_name: str, causal: bool,
                                scale: float):
    """Per-device ring body folding each arriving k/v chunk with the
    pallas flash-carry kernel (``ops.pallas_kernels.flash_attention_step``)
    instead of the naive XLA fold — per BASELINE.md the naive block fold
    runs ~30 TFLOP/s where flash runs ~110, so this is where round 1
    left ~3.5x on the table inside every ring step."""
    from netsdb_tpu.ops.pallas_kernels import flash_attention_step

    n_dev = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s_local, d)
    kf = k.reshape(bh, s_local, d)
    vf = v.reshape(bh, s_local, d)

    # carries derive from qf so they inherit its varying manual axis
    acc0 = jnp.zeros_like(qf, dtype=jnp.float32)
    pad = jnp.zeros((128,), jnp.float32)
    l0 = jnp.zeros_like(qf[:, :, :1], dtype=jnp.float32) + pad
    m0 = jnp.full_like(qf[:, :, :1], NEG_INF, dtype=jnp.float32) + pad

    def step(i, carry):
        acc, l, m, k_cur, v_cur = carry
        src = (my_idx - i) % n_dev
        acc, l, m = flash_attention_step(
            qf, k_cur, v_cur, acc, l, m,
            q_offset=my_idx * s_local, k_offset=src * s_local,
            causal=causal, scale=scale)
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, l, m, k_nxt, v_nxt

    acc, l, _, _, _ = jax.lax.fori_loop(
        0, n_dev, step, (acc0, l0, m0, kf, vf))
    out = acc / jnp.maximum(l[:, :, :1], 1e-30)
    return out.astype(q.dtype).reshape(b, h, s_local, d)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "data", causal: bool = True,
                   scale: Optional[float] = None,
                   impl: Optional[str] = None) -> jax.Array:
    """q/k/v (B, H, S, D) sequence-sharded over ``axis``; returns the
    exact attention output with the same sharding.

    ``impl``: None auto-selects — the pallas flash-carry fold on TPU
    when the local chunk is lane-aligned, the naive XLA fold otherwise;
    'flash' / 'naive' force a path.
    """
    from netsdb_tpu.ops.common import on_tpu

    d = q.shape[-1]
    s_local = q.shape[2] // mesh.shape[axis]
    scale = scale if scale is not None else d ** -0.5
    if impl is None:
        impl = ("flash" if on_tpu() and s_local % 128 == 0 and d % 128 == 0
                else "naive")
    body = (_ring_attention_flash_local if impl == "flash"
            else _ring_attention_local)
    spec = P(None, None, axis, None)
    # the flash body feeds device-varying ring offsets into the pallas
    # kernel as an operand, which the static varying-axes inference
    # cannot type (jax suggests check_vma=False for exactly this); the
    # in/out specs still pin every array's sharding explicitly
    fn = jax.shard_map(
        functools.partial(body, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=(impl != "flash"))
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool, scale):
    """seq-sharded → all_to_all → head-sharded full attention → back."""
    n_dev = jax.lax.psum(1, axis_name)

    def seq_to_heads(t):  # (B, H, S/n, D) → (B, H/n, S, D)
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(t):  # (B, H/n, S, D) → (B, H, S/n, D)
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # after the re-shard each device holds the FULL sequence for its
    # head group, so the local attention is where the (S, S) memory
    # blow-up would happen — dispatch picks the pallas flash kernel on
    # TPU (VMEM accumulators, no (S,S) in HBM), full attention on CPU;
    # out_vma tells shard_map's vma check the kernel output varies over
    # this mesh axis (pallas out_shape carries no annotation by itself)
    out = attention_dispatch(qh, kh, vh, causal=causal, scale=scale,
                             out_vma={axis_name})
    return heads_to_seq(out)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis: str = "data", causal: bool = True,
                      scale: Optional[float] = None) -> jax.Array:
    """Ulysses sequence parallelism: heads must divide the axis size."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"heads {q.shape[1]} not divisible by mesh axis "
                         f"{axis}={n}")
    spec = P(None, None, axis, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
