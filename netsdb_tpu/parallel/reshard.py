"""Collective-step resharding — move a placed set between layouts
WITHOUT a host round-trip.

Per *Memory-efficient array redistribution* (arxiv 2112.01075), any
layout change decomposes into a bounded sequence of collective steps —
all-to-alls, all-gathers, local slices — each moving at most
shard-sized (or, for a gather, array-sized) messages device-to-device.
Before this module, changing a placed set's sharding meant re-staging
every page from the host arena (``SetStore.create_set(placement=...)``
re-places and ``_touch`` drops every cached device block); now
:func:`reshard_set` PLANS the minimal step schedule, executes it over
the device-resident blocks the partial-run cache already holds, and
installs the moved blocks under the NEW layout's cache key — the warm
re-query under the new sharding performs ZERO arena reads.

The planner (:func:`plan_steps`) covers the redistribution lattice a
1-axis mesh needs:

* same spec → no steps;
* sharded → replicated → one ``all_gather`` (tiled — each device
  receives N-1 shard-sized messages);
* replicated → sharded → one ``local_slice`` (zero communication:
  every device already holds its piece);
* sharded(dim i) → sharded(dim j) over the SAME axis → one
  ``all_to_all`` (shard-sized messages, never a full replica — the
  paper's memory-efficient case);
* anything else (axis/mesh changes) → ``all_gather`` then
  ``local_slice``/``replace`` — the bounded two-step fallback (one
  transient replica, noted in the step's ``peak`` estimate).

Devcache integration rides the PR 14 dirty-range path: the moved
ranges are invalidated under the old layout (bumping the scope epoch,
so racing installs of the old layout are refused) and the transformed
blocks install under the new layout's base key as they land — block by
block, bounded memory.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Tuple

from netsdb_tpu import obs

# ---------------------------------------------------------------------
# the step schedule
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Step:
    """One collective step of a reshard schedule.

    ``kind``: ``all_gather`` | ``local_slice`` | ``all_to_all`` |
    ``replace``. ``dim``/``dim_to`` are tensor dims, ``axis`` the mesh
    axis, ``peak`` the per-device transient-bytes FACTOR relative to
    one shard — the bounded-memory annotation from 2112.01075:
    1 = shard-sized messages (the memory-efficient case), the axis
    SIZE = a full replica (all_gather / replace; 0 when the planner
    was not given the mesh sizes and cannot resolve it)."""

    kind: str
    dim: int = 0
    dim_to: int = 0
    axis: str = ""
    peak: int = 1

    def label(self) -> str:
        if self.kind == "all_to_all":
            return f"all_to_all[{self.axis}:{self.dim}->{self.dim_to}]"
        if self.kind in ("all_gather", "local_slice"):
            return f"{self.kind}[{self.axis}:{self.dim}]"
        return self.kind


def _sharded_dims(spec: Tuple, ndim: int) -> List[Tuple[int, Any]]:
    out = []
    for i in range(ndim):
        entry = spec[i] if i < len(spec) else None
        if entry is not None:
            out.append((i, entry))
    return out


def plan_steps(src_spec: Tuple, dst_spec: Tuple, ndim: int,
               same_mesh: bool = True,
               axis_sizes: Optional[Dict[str, int]] = None
               ) -> List[Step]:
    """The minimal collective-step schedule turning ``src_spec`` into
    ``dst_spec`` over ``ndim``-rank values (specs are PartitionSpec
    tuples; missing trailing entries mean replicated). ``same_mesh``
    False (the two placements resolve different device sets) forces
    the gather → replace fallback — cross-mesh single collectives
    don't exist. ``axis_sizes`` (axis name → mesh size) resolves the
    full-replica ``peak`` annotation on gather steps; without it
    those report ``peak=0`` (unknown — a full replica)."""
    src_spec = tuple(src_spec or ())
    dst_spec = tuple(dst_spec or ())
    norm = lambda sp: tuple((sp[i] if i < len(sp) else None)  # noqa: E731
                            for i in range(ndim))
    s, d = norm(src_spec), norm(dst_spec)
    if s == d and same_mesh:
        return []  # an identical spec on a DIFFERENT mesh still moves
    ssh, dsh = _sharded_dims(s, ndim), _sharded_dims(d, ndim)
    if same_mesh and len(ssh) == 1 and len(dsh) == 1 \
            and ssh[0][1] == dsh[0][1] and ssh[0][0] != dsh[0][0]:
        # the paper's headline case: one tiled all-to-all, shard-sized
        # messages, no transient replica
        axis = ssh[0][1]
        axis = axis if isinstance(axis, str) else "+".join(axis)
        return [Step("all_to_all", dim=ssh[0][0], dim_to=dsh[0][0],
                     axis=axis, peak=1)]
    steps: List[Step] = []
    for i, axis in ssh:  # undo the source sharding
        a = axis if isinstance(axis, str) else "+".join(axis)
        # a gather materializes a full replica per device: peak = the
        # axis size (the bounded-memory worst case the planner admits)
        steps.append(Step("all_gather", dim=i, axis=a,
                          peak=(axis_sizes or {}).get(a, 0)))
    if dsh:
        if same_mesh and len(dsh) == 1:
            i, axis = dsh[0]
            a = axis if isinstance(axis, str) else "+".join(axis)
            steps.append(Step("local_slice", dim=i, axis=a, peak=1))
        else:
            # different mesh (or multi-axis target): one device-to-
            # device re-place — still no host round-trip
            steps.append(Step("replace", peak=1))
    elif not same_mesh:
        steps.append(Step("replace", peak=1))
    return steps


# ---------------------------------------------------------------------
# step execution
# ---------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _step_program(kind: str, mesh, axis: str, dim: int, dim_to: int,
                  ndim: int, shard: int):
    """ONE jitted collective program per (step shape, mesh) — a
    reshard applies its schedule to every column of every block, so
    building the shard_map per call would retrace per block (the
    difference between a collective move and a compile storm)."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def spec_at(d):
        entries = [None] * ndim
        entries[d] = axis
        return P(*entries)

    if kind == "all_gather":
        fn = shard_map(
            lambda v: jax.lax.all_gather(v, axis, axis=dim, tiled=True),
            mesh=mesh, in_specs=(spec_at(dim),),
            out_specs=P(*([None] * ndim)), check_rep=False)
    elif kind == "local_slice":
        def slice_local(v):
            idx = jax.lax.axis_index(axis)
            return jax.lax.dynamic_slice_in_dim(v, idx * shard, shard,
                                                dim)

        fn = shard_map(slice_local, mesh=mesh,
                       in_specs=(P(*([None] * ndim)),),
                       out_specs=spec_at(dim), check_rep=False)
    else:  # all_to_all
        fn = shard_map(
            lambda v: jax.lax.all_to_all(v, axis, split_axis=dim_to,
                                         concat_axis=dim, tiled=True),
            mesh=mesh, in_specs=(spec_at(dim),),
            out_specs=spec_at(dim_to), check_rep=False)
    return jax.jit(fn)


def _run_step(x, step: Step, src_mesh, dst_mesh, dst_sharding):
    import jax

    ndim = x.ndim
    if step.kind == "all_gather":
        return _step_program("all_gather", src_mesh, step.axis,
                             step.dim, 0, ndim, 0)(x)
    if step.kind == "local_slice":
        size = dst_mesh.shape[step.axis]
        if x.shape[step.dim] % size:
            # indivisible: fall through to the re-place fallback (the
            # planner's divisibility assumption broke on a ragged tail)
            return jax.device_put(x, dst_sharding)
        shard = x.shape[step.dim] // size
        # the value must be addressable on the DESTINATION mesh's
        # devices first (device-to-device broadcast, no host trip)
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(
            x, NamedSharding(dst_mesh, P(*([None] * ndim))))
        return _step_program("local_slice", dst_mesh, step.axis,
                             step.dim, 0, ndim, shard)(x)
    if step.kind == "all_to_all":
        return _step_program("all_to_all", src_mesh, step.axis,
                             step.dim, step.dim_to, ndim, 0)(x)
    # "replace": one device-to-device re-place under the target
    # sharding (jax moves shards directly; the host never sees bytes)
    return jax.device_put(x, dst_sharding)


def execute_steps(x, steps: List[Step], src_placement, dst_placement):
    """Run one value through a schedule, finishing with a normalizing
    re-place under the destination sharding (ensures the result's
    committed sharding compares EQUAL to what a fresh placement would
    produce — the jit-cache-hit requirement)."""
    import jax

    src_mesh = src_placement.mesh() if src_placement is not None else None
    dst_mesh = dst_placement.mesh() if dst_placement is not None else None
    nd = getattr(x, "ndim", 0)
    dst_sharding = None
    if dst_placement is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = tuple(dst_placement.spec)[:nd]
        spec = spec + (None,) * (nd - len(spec))
        dst_sharding = NamedSharding(dst_mesh, P(*spec))
    for step in steps:
        x = _run_step(x, step, src_mesh, dst_mesh, dst_sharding)
        obs.REGISTRY.counter("reshard.steps").inc()
        obs.operators.op_add("reshard.steps")
    if dst_sharding is not None:
        sh = getattr(x, "sharding", None)
        if sh is None or not sh.is_equivalent_to(dst_sharding, nd):
            x = jax.device_put(x, dst_sharding)
    return x


def _spec_for(placement, ndim: int) -> Tuple:
    if placement is None:
        return (None,) * ndim
    spec = tuple(placement.spec)
    return spec[:ndim] + (None,) * max(ndim - len(spec), 0)


def _axis_sizes(placement) -> Optional[Dict[str, int]]:
    """axis name → mesh size for the peak annotation (None when the
    placement cannot resolve a mesh on this process)."""
    if placement is None:
        return None
    try:
        return {name: int(size)
                for name, size in placement.mesh().shape.items()}
    except Exception:  # noqa: BLE001 — degraded hardware: no mesh
        return None


def _same_mesh(src, dst) -> bool:
    if src is None or dst is None:
        return False
    try:
        return src.mesh() is dst.mesh() or src.mesh() == dst.mesh()
    except Exception:  # degraded-hardware collapse etc.
        return False


def move_table(table, steps: List[Step], src_placement, dst_placement):
    """Apply a schedule to one cached chunk ColumnTable — every column
    plus the validity mask, column by column (bounded memory)."""
    from netsdb_tpu.relational.table import ColumnTable

    cols = {k: execute_steps(v, steps, src_placement, dst_placement)
            for k, v in table.cols.items()}
    valid = table.valid
    if valid is not None:
        valid = execute_steps(valid, steps, src_placement, dst_placement)
    return ColumnTable(cols, dict(table.dicts), valid)


# ---------------------------------------------------------------------
# the set-level primitive
# ---------------------------------------------------------------------


@dataclasses.dataclass
class ReshardReport:
    """What one :func:`reshard_set` did — steps planned, blocks moved
    device-to-device, bytes that never touched the host arena."""

    steps: List[Step]
    blocks_moved: int = 0
    bytes_moved: int = 0
    items_moved: int = 0
    elapsed_s: float = 0.0

    def labels(self) -> List[str]:
        return [s.label() for s in self.steps]


def _move_tensor_entry(val, steps: List[Step], src_placement,
                       dst_placement):
    """Run one cached tensor-stream entry through a schedule. Entries
    are the tuples the stream's ``place`` produced — ``(n, block)``
    for "trows", ``(start, block)`` for "treduce" — so only the 2-d
    array element moves; the bookkeeping scalars ride along."""
    out = []
    for el in (val if isinstance(val, (tuple, list)) else (val,)):
        if getattr(el, "ndim", None) == 2:
            el = execute_steps(el, steps, src_placement, dst_placement)
        out.append(el)
    if isinstance(val, (tuple, list)):
        return tuple(out)
    return out[0]


def _reshard_paged_tensor(store, ident, pm, src_placement,
                          dst_placement, report: ReshardReport) -> None:
    """The paged-TENSOR leg of :func:`reshard_set` (the ROADMAP
    carry-over: tables-only before this): the set's device-cached
    stream blocks — "trows"/"treduce" entries under the OLD
    placement's label — are invalidated through the dirty-range path,
    run through the collective schedule one block at a time, and
    installed under the NEW placement's key, so a warm re-stream under
    the new sharding performs zero arena reads. SUMMA panel entries
    (mesh-labelled, device-committed) move via
    :func:`reshard_summa_layout` instead — a placement change does not
    touch them."""
    from netsdb_tpu.storage.devcache import _value_nbytes

    ps = store.page_store()
    name = f"{pm.ident}.mat"
    steps = plan_steps(_spec_for(src_placement, 2),
                       _spec_for(dst_placement, 2), 2,
                       same_mesh=_same_mesh(src_placement, dst_placement),
                       axis_sizes=_axis_sizes(src_placement))
    report.steps = steps
    cache = store.device_cache()
    if cache is None or not getattr(cache, "partial", False) \
            or not cache.enabled:
        return
    cfg = store.config
    rb = ps.meta(name)[1][0]
    bucketing = getattr(cfg, "shape_bucketing", True)
    density = getattr(cfg, "bucket_density", 2)
    scope = str(ident)
    src_pl = src_placement.label() if src_placement is not None else None
    dst_pl = dst_placement.label() if dst_placement is not None else None
    ranges = ps.block_ranges(name)
    if not ranges:
        return
    # collect EVERY kind's covered map BEFORE invalidating: the
    # dirty-range drop is scope-wide, so reading after it would see
    # nothing to move
    covered_by = {}
    for kind in ("trows", "treduce"):
        src_key = (scope, kind, rb, bucketing, density, src_pl)
        _epoch, covered = cache.plan_ranges(src_key, ranges)
        if covered:
            covered_by[kind] = covered
    if not covered_by:
        return
    lo = min(r[0] for cov in covered_by.values() for r in cov)
    hi = max(r[1] for cov in covered_by.values() for r in cov)
    cache.invalidate_range(scope, lo, hi)
    epoch = cache.scope_epoch(scope)
    for kind, covered in covered_by.items():
        dst_key = (scope, kind, rb, bucketing, density, dst_pl)
        for rng in ranges:
            val = covered.get((int(rng[0]), int(rng[1])))
            if val is None:
                continue
            moved = _move_tensor_entry(val, steps, src_placement,
                                       dst_placement)
            if cache.install_block(dst_key, rng, moved, epoch=epoch):
                report.blocks_moved += 1
                report.bytes_moved += _value_nbytes(moved)


def reshard_summa_layout(store, ident, src_devices, dst_devices,
                         src_grid: Optional[Tuple[int, int]] = None,
                         dst_grid: Optional[Tuple[int, int]] = None,
                         axis: str = "data") -> ReshardReport:
    """Move a paged TENSOR set's cached SUMMA panel blocks between
    mesh LAYOUTS — 1-d row-dealt (``src_grid``/``dst_grid`` None) and
    2-d processor grids — without re-staging from the arena: each
    cached block re-places device-to-device (splitting into per-grid-
    column tiles or concatenating them as the layouts require) and
    installs under the destination layout's label, so the next
    distributed matmul under the new mesh serves every A panel from
    HBM. Both layouts must have the SAME participant count (the
    contraction padding ``k_pad`` is participant-derived; differing
    counts would need a host re-pad — callers re-stage instead)."""
    import jax
    from jax.sharding import SingleDeviceSharding

    from netsdb_tpu.parallel import summa as _summa
    from netsdb_tpu.plan import staging
    from netsdb_tpu.storage.devcache import _value_nbytes

    t0 = time.perf_counter()
    report = ReshardReport(steps=[Step("replace", peak=1)])
    obs.REGISTRY.counter("reshard.plans").inc()
    items = store.get_items(ident)
    pm = next((i for i in items
               if type(i).__name__ == "_PagedMatrix"), None)
    if pm is None:
        raise ValueError(f"reshard_summa_layout: {ident} holds no "
                         f"paged matrix")
    src_devices = list(src_devices)
    dst_devices = list(dst_devices)
    n_src = (src_grid[0] * src_grid[1] if src_grid is not None
             else len(src_devices))
    n_dst = (dst_grid[0] * dst_grid[1] if dst_grid is not None
             else len(dst_devices))
    if n_src != n_dst:
        raise ValueError(f"summa layout move needs equal participant "
                         f"counts (k padding), got {n_src} -> {n_dst}")
    src_devices = src_devices[:n_src]
    dst_devices = dst_devices[:n_dst]
    src_label = (_summa.grid_label(src_devices, *src_grid)
                 if src_grid is not None
                 else _summa.mesh_label(axis, src_devices))
    dst_label = (_summa.grid_label(dst_devices, *dst_grid)
                 if dst_grid is not None
                 else _summa.mesh_label(axis, dst_devices))
    cache = store.device_cache()
    if cache is None or not getattr(cache, "partial", False) \
            or not cache.enabled:
        report.elapsed_s = time.perf_counter() - t0
        return report
    ps = store.page_store()
    name = f"{pm.ident}.mat"
    cfg = store.config
    rb = ps.meta(name)[1][0]
    bucket = staging.pad_rows_target(
        rb, getattr(cfg, "shape_bucketing", True),
        density=getattr(cfg, "bucket_density", 2))
    scope = str(ident)
    src_key = (scope, _summa.CACHE_KIND, bucket, src_label)
    dst_key = (scope, _summa.CACHE_KIND, bucket, dst_label)
    ranges = ps.block_ranges(name)
    _epoch, covered = cache.plan_ranges(src_key, ranges)
    if not covered:
        report.elapsed_s = time.perf_counter() - t0
        return report
    lo = min(r[0] for r in covered)
    hi = max(r[1] for r in covered)
    cache.invalidate_range(scope, lo, hi)
    epoch = cache.scope_epoch(scope)
    import jax.numpy as jnp

    for rng in ranges:
        val = covered.get((int(rng[0]), int(rng[1])))
        if val is None:
            continue
        i, nrows, payload = val
        # normalize to the full (bucket, k_pad) block on ONE device —
        # grid tiles concatenate on their destination (device-side
        # concat, no host trip), 1-d panels are already whole
        if isinstance(payload, tuple):
            anchor = (dst_devices[(i % dst_grid[0]) * dst_grid[1]]
                      if dst_grid is not None
                      else dst_devices[i % n_dst])
            full = jnp.concatenate(
                [jax.device_put(t, SingleDeviceSharding(anchor))
                 for t in payload], axis=1)
        else:
            full = payload
        if dst_grid is not None:
            pr, pc = dst_grid
            r = i % pr
            apc = full.shape[1] // pc
            moved_payload = tuple(
                jax.device_put(full[:, c * apc:(c + 1) * apc],
                               SingleDeviceSharding(
                                   dst_devices[r * pc + c]))
                for c in range(pc))
        else:
            moved_payload = jax.device_put(
                full, SingleDeviceSharding(dst_devices[i % n_dst]))
        moved = (i, nrows, moved_payload)
        obs.REGISTRY.counter("reshard.steps").inc()
        if cache.install_block(dst_key, rng, moved, epoch=epoch):
            report.blocks_moved += 1
            report.bytes_moved += _value_nbytes(moved)
    report.elapsed_s = time.perf_counter() - t0
    obs.REGISTRY.counter("reshard.blocks_moved").inc(report.blocks_moved)
    obs.REGISTRY.counter("reshard.bytes_moved").inc(report.bytes_moved)
    obs.operators.op_add("reshard.blocks_moved", report.blocks_moved)
    return report


def reshard_set(store, ident, dst_placement,
                kind: str = "tables") -> ReshardReport:
    """Move set ``ident`` from its current placement to
    ``dst_placement`` through collective steps.

    * **memory sets** — every resident item's arrays run the schedule
      device-to-device and the set's declared placement swaps; the
      host never re-touches the data.
    * **paged sets** — the set's device-CACHED blocks (partial-run
      entries under the old layout's sharding-keyed base key) are
      invalidated via the dirty-range path, transformed through the
      schedule one block at a time, and installed under the NEW
      layout's key — a warm re-query under the new sharding serves
      entirely from HBM with zero arena reads. Blocks that were not
      resident simply stream (and install) cold on the next query, as
      always.

    Not safe against CONCURRENT streams of the same set — callers
    serialize like any other mutation (the serve layer's per-set
    locks); content is unchanged, so no dirty range is logged and the
    set's write version does not move."""
    from netsdb_tpu.relational.outofcore import PagedColumns

    t0 = time.perf_counter()
    src_placement = store.placement_of(ident)
    report = ReshardReport(steps=[])
    obs.REGISTRY.counter("reshard.plans").inc()

    if store.storage_of(ident) == "paged":
        items = store.get_items(ident)
        pc = next((i for i in items if isinstance(i, PagedColumns)), None)
        if pc is None:
            pm = next((i for i in items
                       if type(i).__name__ == "_PagedMatrix"), None)
            if pm is None:
                raise ValueError(f"reshard_set: {ident} holds no paged "
                                 f"relation or matrix")
            _reshard_paged_tensor(store, ident, pm, src_placement,
                                  dst_placement, report)
            store.set_placement(ident, dst_placement)
            report.elapsed_s = time.perf_counter() - t0
            obs.REGISTRY.counter("reshard.blocks_moved").inc(
                report.blocks_moved)
            obs.REGISTRY.counter("reshard.bytes_moved").inc(
                report.bytes_moved)
            obs.operators.op_add("reshard.blocks_moved",
                                 report.blocks_moved)
            return report
        steps = plan_steps(_spec_for(src_placement, 1),
                           _spec_for(dst_placement, 1), 1,
                           same_mesh=_same_mesh(src_placement,
                                                dst_placement),
                           axis_sizes=_axis_sizes(src_placement))
        report.steps = steps
        cache = pc.devcache
        scope = pc.cache_scope
        if cache is not None and scope is not None \
                and getattr(cache, "partial", False) and cache.enabled:
            ranges = pc.block_ranges()
            src_key = pc.partial_base_key(kind, src_placement)
            dst_key = pc.partial_base_key(kind, dst_placement)
            _epoch, covered = cache.plan_ranges(src_key, ranges)
            if covered:
                lo = min(r[0] for r in covered)
                hi = max(r[1] for r in covered)
                # PR 14 dirty-range invalidation: drops the old
                # layout's entries and bumps the scope epoch, so any
                # racing install planned under the old layout refuses
                cache.invalidate_range(scope, lo, hi)
                epoch = cache.scope_epoch(scope)
                for rng in ranges:
                    blk = covered.get((int(rng[0]), int(rng[1])))
                    if blk is None:
                        continue
                    moved = move_table(blk, steps, src_placement,
                                       dst_placement)
                    if cache.install_block(dst_key, rng, moved,
                                           epoch=epoch):
                        report.blocks_moved += 1
                        from netsdb_tpu.storage.devcache import \
                            _value_nbytes

                        report.bytes_moved += _value_nbytes(moved)
        store.set_placement(ident, dst_placement)
    else:
        moved_items = []
        same = _same_mesh(src_placement, dst_placement)

        def steps_for(nd):
            steps = plan_steps(_spec_for(src_placement, nd),
                               _spec_for(dst_placement, nd), nd,
                               same_mesh=same,
                               axis_sizes=_axis_sizes(src_placement))
            if not report.steps:
                report.steps = steps
            return steps

        for item in store.get_items(ident):
            if hasattr(item, "cols"):  # resident ColumnTable
                moved_items.append(
                    move_table(item, steps_for(1), src_placement,
                               dst_placement))
                report.items_moved += 1
                continue
            nd = getattr(item, "ndim", None)
            data = item
            is_blocked = hasattr(item, "meta") and hasattr(item, "data")
            if is_blocked:
                data = item.data
                nd = data.ndim
            if nd is None:  # host records: nothing device-resident
                moved_items.append(item)
                continue
            out = execute_steps(data, steps_for(nd), src_placement,
                                dst_placement)
            moved_items.append(item.with_data(out) if is_blocked
                               else out)
            report.items_moved += 1
        store.set_placement(ident, dst_placement, items=moved_items)

    report.elapsed_s = time.perf_counter() - t0
    obs.REGISTRY.counter("reshard.blocks_moved").inc(
        report.blocks_moved or report.items_moved)
    obs.REGISTRY.counter("reshard.bytes_moved").inc(report.bytes_moved)
    obs.operators.op_add("reshard.blocks_moved",
                         report.blocks_moved or report.items_moved)
    return report
