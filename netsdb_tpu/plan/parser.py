"""Plan-text parser — the round-trip half of the textual plan IR.

The reference ships computation DAGs as a textual TCAP string that the
worker re-parses with flex/bison into ``AtomicComputation`` nodes
(``src/logicalPlan/source/Lexer.l:50-70``, ``Parser.y``,
``headers/AtomicComputationClasses.h``) and then rebinds to the shipped
Computation objects (``ComputePlan.cc:20-56``). Our plans never cross a
process boundary, but the textual dump (``LogicalPlan.to_plan_string``)
is the same debuggability/test surface — and this module closes the
loop: ``parse_plan`` text → structural atoms (producer/consumer maps,
validation — the reference's ``LogicalPlanBuilder`` and the suites in
``src/logicalPlanTests``), and ``ParsedPlan.to_computations`` rebinds
atoms to Python lambdas from a registry keyed by label (the analogue of
rebinding TCAP to the shipped Computations at the worker).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List

from netsdb_tpu.plan.computations import (
    Aggregate, Apply, Computation, Filter, Join, MultiApply, Partition,
    ScanSet, WriteSet,
)

# name <= KIND(arg, arg, ...) ; args are bare identifiers or 'quoted'
_ATOM_RE = re.compile(r"^\s*(\S+)\s*<=\s*([A-Z]+)\((.*)\)\s*$")


@dataclasses.dataclass
class ParsedAtom:
    """One line of the dump — reference ``AtomicComputation``."""

    name: str
    kind: str             # SCAN/APPLY/FILTER/FLATTEN/JOIN/AGGREGATE/OUTPUT
    inputs: List[str]     # upstream atom names
    literals: List[str]   # quoted args (labels, db/set names)

    def __str__(self) -> str:
        args = list(self.inputs) + [f"'{l}'" for l in self.literals]
        return f"{self.name} <= {self.kind}({', '.join(args)})"


def _split_args(raw: str) -> List[str]:
    """Split a TCAP-ish arg list, honouring single quotes."""
    args, buf, in_q = [], [], False
    for ch in raw:
        if ch == "'":
            in_q = not in_q
            buf.append(ch)
        elif ch == "," and not in_q:
            args.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
    last = "".join(buf).strip()
    if last:
        args.append(last)
    return args


class PlanParseError(ValueError):
    pass


@dataclasses.dataclass
class ParsedPlan:
    """Structural plan — reference ``LogicalPlan`` +
    ``AtomicComputationList`` with producer/consumer maps."""

    atoms: List[ParsedAtom]

    def __post_init__(self):
        self.by_name: Dict[str, ParsedAtom] = {}
        self.consumers: Dict[str, List[ParsedAtom]] = {}
        for a in self.atoms:
            if a.name in self.by_name:
                raise PlanParseError(f"duplicate atom name {a.name!r}")
            self.by_name[a.name] = a
        for a in self.atoms:
            for src in a.inputs:
                if src not in self.by_name:
                    raise PlanParseError(
                        f"atom {a.name!r} consumes undefined {src!r}")
                self.consumers.setdefault(src, []).append(a)

    @property
    def scans(self) -> List[ParsedAtom]:
        return [a for a in self.atoms if a.kind == "SCAN"]

    @property
    def outputs(self) -> List[ParsedAtom]:
        return [a for a in self.atoms if a.kind == "OUTPUT"]

    def to_plan_string(self) -> str:
        return "\n".join(str(a) for a in self.atoms)

    # --- rebind to executable Computations ---------------------------
    def to_computations(self, registry: Dict[str, Any]) -> List[WriteSet]:
        """Rebuild an executable DAG: each APPLY/FILTER/FLATTEN/JOIN/
        AGGREGATE atom looks up its label in ``registry``. Values are
        the kwargs the node type takes (a bare callable is shorthand
        for the node's primary function). The reference analogue is
        ``ComputePlan``'s TCAP→executor binding against the shipped
        Computation objects (``ComputePlan.cc:258-283``). Atoms may
        appear in any order; they are built in dependency order."""
        built: Dict[str, Computation] = {}

        # topo-order the atoms (hand-written plan text need not be
        # ordered; __post_init__ already guarantees every input exists)
        order: List[ParsedAtom] = []
        state: Dict[str, int] = {}  # 0=visiting, 1=done

        def visit(atom: ParsedAtom) -> None:
            if state.get(atom.name) == 1:
                return
            if state.get(atom.name) == 0:
                raise PlanParseError(f"cycle through atom {atom.name!r}")
            state[atom.name] = 0
            for src in atom.inputs:
                visit(self.by_name[src])
            state[atom.name] = 1
            order.append(atom)

        for a in self.atoms:
            visit(a)

        def kwargs_for(atom: ParsedAtom) -> Dict[str, Any]:
            label = atom.literals[0] if atom.literals else ""
            if label not in registry:
                raise PlanParseError(
                    f"no registry entry for {atom.kind} label {label!r}")
            spec = registry[label]
            return dict(spec) if isinstance(spec, dict) else {"fn": spec}

        arity = {  # kind → (n_inputs, n_literals)
            "SCAN": (0, 2), "APPLY": (1, 1), "FILTER": (1, 1),
            "FLATTEN": (1, 1), "JOIN": (2, 1), "AGGREGATE": (1, 1),
            "PARTITION": (1, 1), "OUTPUT": (1, 2),
        }
        for a in order:
            if a.kind in arity:
                n_in, n_lit = arity[a.kind]
                if len(a.inputs) != n_in or len(a.literals) != n_lit:
                    raise PlanParseError(
                        f"atom {a.name!r}: {a.kind} takes {n_in} input(s) "
                        f"and {n_lit} literal(s), got {len(a.inputs)} and "
                        f"{len(a.literals)}")
            ins = [built[s] for s in a.inputs]
            if a.kind == "SCAN":
                built[a.name] = ScanSet(a.literals[0], a.literals[1])
            elif a.kind == "APPLY":
                built[a.name] = Apply(ins[0], label=a.literals[0],
                                      **kwargs_for(a))
            elif a.kind == "FILTER":
                kw = kwargs_for(a)
                pred = kw.pop("pred", None) or kw.pop("fn", None)
                built[a.name] = Filter(ins[0], pred, label=a.literals[0],
                                       **kw)
            elif a.kind == "FLATTEN":
                built[a.name] = MultiApply(ins[0], label=a.literals[0],
                                           **kwargs_for(a))
            elif a.kind == "JOIN":
                built[a.name] = Join(ins[0], ins[1], label=a.literals[0],
                                     **kwargs_for(a))
            elif a.kind == "AGGREGATE":
                built[a.name] = Aggregate(ins[0], label=a.literals[0],
                                          **kwargs_for(a))
            elif a.kind == "PARTITION":
                kw = kwargs_for(a)
                key_fn = kw.pop("key_fn", None)
                key_fn = key_fn or kw.pop("fn", None)
                kw.pop("fn", None)
                if key_fn is None or "num_partitions" not in kw:
                    raise PlanParseError(
                        f"PARTITION label {a.literals[0]!r}: registry entry "
                        f"must be a dict with 'key_fn' (or 'fn') and "
                        f"'num_partitions'")
                built[a.name] = Partition(ins[0], key_fn,
                                          label=a.literals[0], **kw)
            elif a.kind == "OUTPUT":
                built[a.name] = WriteSet(ins[0], a.literals[0],
                                         a.literals[1])
            else:
                raise PlanParseError(f"unknown atom kind {a.kind!r}")
        return [built[o.name] for o in self.outputs]


def parse_plan(text: str) -> ParsedPlan:
    """Parse a ``to_plan_string`` dump. Unknown kinds parse structurally
    (they only fail at ``to_computations``), matching the reference
    parser's separation of syntax from binding."""
    atoms = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _ATOM_RE.match(line)
        if not m:
            raise PlanParseError(f"line {lineno}: cannot parse {line!r}")
        name, kind, raw = m.groups()
        inputs, literals = [], []
        for arg in _split_args(raw):
            if arg.startswith("'") and arg.endswith("'"):
                literals.append(arg[1:-1])
            else:
                inputs.append(arg)
        atoms.append(ParsedAtom(name, kind, inputs, literals))
    return ParsedPlan(atoms)
