"""Overlapped device staging — the PageCircularBuffer for HBM uploads.

The reference overlaps page IO with pipeline compute by putting a
bounded ring buffer between the scan thread and the worker threads
(``src/storage/headers/PageCircularBuffer.h``): the scan thread pins
the NEXT page while the workers chew on the current one.  Our port had
that for the HOST read stage (``PagedTensorStore.stream_blocks``
prefetch readers) but not for the DEVICE stage: every out-of-core
consumer ran ``jax.device_put`` synchronously per chunk, so the
accelerator idled through every host→device copy.  On TPU-class
hardware hiding transfer latency dominates out-of-core throughput
(arxiv 2112.09017 §IV; arxiv 2301.13062) — this module is that hiding
layer.

:func:`stage_stream` wraps any host-side chunk iterator with a bounded
double buffer: a background thread runs the caller's ``place`` function
(pad + ``jax.device_put`` with the target sharding) ``depth`` items
ahead of the consumer, so the next block lands in HBM while the current
fold step computes.  The pipeline is therefore three stages deep
end-to-end::

    arena/disk --(prefetch readers)--> host chunk --(staging thread,
    place: pad+device_put)--> HBM block --(consumer)--> fold step

Discipline matches ``stream_blocks`` (the template this generalizes):

- the staging thread OWNS the source iterator: it is advanced and
  closed there, so read locks held by source generators are acquired
  and released on one thread and an abandoned consumer can never leak
  a lock until GC;
- any death of the staging thread (source raised, ``place`` raised)
  re-raises AT THE CONSUMER, never swallowed;
- ``close()`` (idempotent, also via ``contextlib.closing`` /
  ``__del__``) stops the thread, drains the queue and joins — the
  ``active_count``/``active_stagers`` registry exists so tests can
  assert no thread outlives its stream.

Shape-bucketed compilation rides the same module: :func:`bucket_rows`
rounds ragged row counts up to a small fixed set of bucket sizes
(powers of two and 1.5× powers of two — <50% pad waste worst case,
~20% typical), so a stream
with a ragged tail — or repeated serve ``EXECUTE``s over different row
counts — compiles once per bucket instead of once per distinct shape.
Padded rows ride the validity mask exactly like the pad-and-mask idiom
in ``parallel/placement.py`` (masks, not garbage rows).
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from netsdb_tpu import obs

# ---------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------

#: no bucket below this many rows — tiny chunks all share one shape
BUCKET_FLOOR = 8


def bucket_rows(n: int, density: int = 2) -> int:
    """Smallest bucket ≥ ``n`` from the fixed ladder. ``density`` is
    the ``config.bucket_density`` knob — buckets per octave:

    * ``2`` (default): {2^k, 3·2^(k-1)} (…, 8, 12, 16, 24, 32, 48, 64,
      96, 128, …) — padding <50% worst case (~20% typical). Buckets
      ≥ 16 are multiples of 8, so mesh-sharded chunks usually divide
      their shard count without a second padding round.
    * ``4``: 2^(k-1)·{1.25, 1.5, 1.75} plus 2^k — padding <25% worst
      case at twice the compile count (one XLA program per bucket).
      ``micro_bench --bucket-sweep`` measures the pad-waste vs
      trace-count trade per density (the ROADMAP ladder-tuning item).

    Every distinct row count inside a bucket's span compiles to the
    SAME XLA program either way."""
    if density not in (2, 4):
        # a typo'd knob silently behaving as the default would fragment
        # device-cache keys for no behavioral difference
        raise ValueError(f"bucket_density must be 2 or 4, got {density!r}")
    if n <= BUCKET_FLOOR:
        return BUCKET_FLOOR
    p = 1 << (n - 1).bit_length()  # next power of two ≥ n
    if density >= 4:
        for mul in (10, 12, 14):   # (p/2)·{1.25, 1.5, 1.75} = p·mul/16
            c = (p * mul) // 16
            if c >= n:
                return c
        return p
    half = (3 * p) // 4            # the 1.5× step below it
    return half if half >= n else p


def pad_rows_target(n: int, bucketing: bool, multiple: int = 1,
                    density: int = 2) -> int:
    """Row count a chunk of ``n`` valid rows pads to: its bucket when
    ``bucketing`` (``density`` = the config's buckets-per-octave knob),
    else ``n`` itself; then rounded up to ``multiple`` (a placement's
    shard granularity) so placed chunks shard without a second padding
    round."""
    target = bucket_rows(n, density) if bucketing else n
    if multiple > 1:
        target += (-target) % multiple
    return target


# ---------------------------------------------------------------------
# fold-buffer donation
# ---------------------------------------------------------------------

def fold_donate_argnums(config=None) -> tuple:
    """``(0,)`` when fold-step accumulators should be donated to XLA
    (``donate_argnums``), else ``()``.  Donating argument 0 — the
    carried state of ``step(state, chunk, *resident)`` — lets XLA
    update the per-stream accumulator in place instead of allocating a
    fresh HBM buffer every block (the state is dead after each step by
    construction: the loop immediately rebinds it).

    ``config.donate_fold_buffers``: True/False pins it; None (default)
    auto-enables only on backends that implement donation (TPU/GPU) —
    CPU ignores donation with a per-compile warning, so tier-1 CPU runs
    stay quiet.  Folds whose ``init`` returns a RESIDENT input array as
    part of the state must pin this off (donation would invalidate the
    resident for later steps)."""
    flag = getattr(config, "donate_fold_buffers", None)
    if flag is None:
        import jax

        flag = jax.default_backend() in ("tpu", "gpu")
    return (0,) if flag else ()


# ---------------------------------------------------------------------
# the staged stream
# ---------------------------------------------------------------------

_SENT_END = "end"
_SENT_ERR = "err"
_SENT_ITEM = "item"

# live staging threads — the leak registry tests assert on (the staging
# analogue of PagedTensorStore._readers). Guarded by _stagers_lock.
_stagers: list = []
_stagers_lock = threading.Lock()


def active_count() -> int:
    """Number of staging threads still alive (dead ones are pruned) —
    must be 0 once every stream is consumed or closed."""
    with _stagers_lock:
        _stagers[:] = [t for t in _stagers if t.is_alive()]
        return len(_stagers)


# the leak registry, absorbed into the central metrics snapshot (the
# accessor above keeps its callers; COLLECT_STATS "metrics" reports
# the same number under "staging")
obs.REGISTRY.register_collector(
    "staging", lambda: {"active_stagers": active_count()})


# --- event trace (tests only; production pays one bool check) ---------
# A flat ordered log of staging milestones: ("place", name, seq) when a
# stream's Nth item finishes placing (i.e. its upload completed),
# ("end", name) when a stream's source exhausts, ("close", name) when
# the consumer closes it, ("cache_hit", name) when a run is served from
# the device cache. The grace-hash overlap test asserts on the ORDER:
# pair i+1's build "place" must precede pair i's probe "close".
_events: list = []
_events_on = False
_events_lock = threading.Lock()


def trace_events(on: bool) -> None:
    """Enable/disable the staging event log (clearing it either way)."""
    global _events_on
    with _events_lock:
        _events.clear()
        _events_on = bool(on)


def events() -> list:
    """Snapshot of the event log in emission order."""
    with _events_lock:
        return list(_events)


def _emit(kind: str, name: str, seq: Optional[int] = None) -> None:
    if not _events_on:
        return
    with _events_lock:
        if _events_on:
            _events.append((kind, name, seq))


def _stage_put(q: "queue.Queue", stop: threading.Event, item) -> bool:
    """Bounded put that gives up when the consumer closed the stream
    (same pattern as ``stream_blocks``'s reader)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _stage_worker(source, place, q: "queue.Queue",
                  stop: threading.Event, name: str,
                  on_complete=None, want_nbytes: bool = False) -> None:
    """The staging thread body. DELIBERATELY a free function over
    explicit state, never a bound method: the Thread must not hold a
    reference to the StagedStream, or an abandoned stream could never
    be garbage-collected (its own worker would keep it alive) and the
    worker would spin in ``put`` until process exit.

    ``on_complete`` fires only on NATURAL source exhaustion (never on
    error or abandonment) — the device-cache install hook: only a FULL
    run may be installed, a truncated one never.

    ``want_nbytes``: byte-size each placed chunk HERE (shipped to the
    consumer alongside it) — device-array metadata reads cost ~4µs per
    XLA property, so a multi-column chunk is tens of µs to measure;
    on this thread the cost overlaps the consumer's compute instead of
    stalling it (the accounting the trace/attribution paths need)."""
    from netsdb_tpu.storage.devcache import _value_nbytes

    seq = 0
    try:
        try:
            for item in source:
                if stop.is_set():
                    return
                placed = place(item)
                _emit("place", name, seq)
                seq += 1
                nb = _value_nbytes(placed) if want_nbytes else None
                if not _stage_put(q, stop, (_SENT_ITEM, (placed, nb))):
                    return  # consumer abandoned the stream
        finally:
            # the worker owns the source: close it HERE so read locks
            # held by source generators release on the thread that
            # acquired them, promptly, even when the consumer
            # abandoned us mid-stream
            close = getattr(source, "close", None)
            if close is not None:
                close()
    except BaseException as e:  # ANY death must surface at consumer
        _stage_put(q, stop, (_SENT_ERR, e))
        return
    _emit("end", name)
    if on_complete is not None:
        try:
            on_complete()
        except Exception:  # a failed cache install must not kill the
            pass           # stream — the run simply stays uncached
    _stage_put(q, stop, (_SENT_END, None))


class StagedStream:
    """Iterator over ``place(item)`` for each item of ``source``, with
    ``place`` running up to ``depth`` items ahead on a background
    thread.  ``depth <= 0`` degenerates to the synchronous inline path
    (the baseline the staging bench compares against — no thread, no
    overlap, same results)."""

    def __init__(self, source: Iterable, place: Callable[[Any], Any],
                 depth: int = 2, name: str = "stage",
                 on_complete: Optional[Callable[[], None]] = None,
                 scope: Optional[str] = None):
        self._source = iter(source)
        self._place = place
        self._depth = int(depth)
        self._name = name
        self._closed = False
        self._on_complete = on_complete
        self._sync_seq = 0
        # query-scoped accounting: the trace AND the client identity
        # are captured HERE, on the consumer's thread (context vars
        # don't cross into the staging worker); the stream reports
        # COUNTERS only — cross-thread spans would misrepresent the
        # overlap this class exists for. ``scope`` is the set identity
        # ("db:set") the per-client resource ledger attributes staged
        # bytes to (None = unattributed temporaries).
        self._trace = obs.current_trace()
        self._scope = scope
        self._client = obs.attrib.current_client()
        # the per-operator explain record, likewise captured on the
        # consumer's thread (the plan node whose dispatch built this
        # stream): chunk/byte/wait ticks attribute to that node
        self._op = obs.operators.current_op()
        # byte-sizing placed chunks costs tens of µs of device-array
        # metadata reads — decide ONCE whether any accounting consumer
        # (ledger scope / active trace / explain op record) needs it,
        # and do it on the worker thread where it overlaps compute
        want_nbytes = (scope is not None or self._trace is not None
                       or self._op is not None)
        self._want_nbytes = want_nbytes
        self._thread: Optional[threading.Thread] = None
        if self._depth > 0:
            self._q: "queue.Queue" = queue.Queue(maxsize=self._depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=_stage_worker,
                args=(self._source, self._place, self._q, self._stop,
                      name, on_complete, want_nbytes),
                daemon=True, name=f"netsdb-stage-{name}")
            with _stagers_lock:
                _stagers[:] = [t for t in _stagers if t.is_alive()]
                _stagers.append(self._thread)
            self._thread.start()

    # --- consumer side ------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        return self

    def _account(self, nbytes: Optional[int], wait_s: float) -> None:
        """Per-chunk bookkeeping: one registry tick always (plus the
        wait histogram the staging-wait-fraction SLO reads and, for
        store-owned streams, the per-(client, set) resource ledger);
        bytes/wait additionally land on an active query trace (the
        profile's "bytes staged" and upload-wait counters). ``nbytes``
        was measured on the WORKER thread (overlapped, not here —
        device-array metadata reads are µs-expensive)."""
        obs.REGISTRY.counter("staging.chunks").inc()
        if nbytes:
            # cumulative staged bytes: the MB/s-staged rate feed the
            # telemetry history derives (obs/history.py)
            obs.REGISTRY.counter("staging.bytes").inc(int(nbytes))
        if wait_s > 0:
            # total-seconds feed for obs/slo.py "staging_wait_fraction"
            obs.REGISTRY.histogram("staging.wait_s").observe(wait_s)
        if self._op is not None:
            self._op.add("stage.chunks")
            if nbytes:
                self._op.add("stage.bytes", nbytes)
            if wait_s > 0:
                self._op.add("stage.wait_s", wait_s)
        if self._scope is not None:
            obs.attrib.account("staged_chunks", 1, scope=self._scope,
                               client=self._client)
            obs.attrib.account("staged_bytes", nbytes or 0,
                               scope=self._scope, client=self._client)
        tr = self._trace
        if tr is None:
            return
        tr.add("stage.chunks")
        tr.add("stage.bytes", nbytes or 0)
        if wait_s > 0:
            tr.add("stage.wait_s", wait_s)

    def __next__(self):
        if self._thread is None:  # synchronous inline mode
            if self._closed:
                raise StopIteration
            try:
                item = next(self._source)
            except StopIteration:
                _emit("end", self._name)
                if self._on_complete is not None:
                    try:
                        self._on_complete()
                    except Exception:  # install failure ≠ stream failure
                        pass
                self.close()
                raise
            placed = self._place(item)
            _emit("place", self._name, self._sync_seq)
            self._sync_seq += 1
            if self._want_nbytes:
                from netsdb_tpu.storage.devcache import _value_nbytes

                self._account(_value_nbytes(placed), 0.0)
            else:
                self._account(None, 0.0)
            return placed
        if self._closed:
            raise StopIteration
        t_wait = time.perf_counter()
        while True:
            try:
                kind, val = self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():  # died without a sentinel
                    self._closed = True
                    raise RuntimeError(
                        f"staging thread {self._name!r} died")
                continue
            if kind is _SENT_ERR:
                self._closed = True
                raise val
            if kind is _SENT_END:
                self._closed = True
                # the CONSUMER observed exhaustion — the "stream
                # finished" moment the overlap tests anchor on
                _emit("close", self._name)
                raise StopIteration
            placed, nbytes = val
            self._account(nbytes, time.perf_counter() - t_wait)
            return placed

    def close(self) -> None:
        """Stop + drain + join the staging thread (idempotent). After
        this the source iterator has been closed on the worker thread
        and no staging thread of this stream is alive."""
        if self._thread is None:
            if not self._closed:
                self._closed = True
                _emit("close", self._name)
                close = getattr(self._source, "close", None)
                if close is not None:
                    close()
            return
        if not self._closed:
            _emit("close", self._name)
        self._closed = True
        self._stop.set()
        # drain so a worker blocked in put() observes the stop quickly
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=30)
        with _stagers_lock:
            _stagers[:] = [t for t in _stagers if t.is_alive()]

    def __enter__(self) -> "StagedStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        # best-effort: an abandoned stream must not leak its thread (or
        # the read locks its source generator holds) until interpreter
        # exit — mirrors generator finalization semantics
        with contextlib.suppress(Exception):
            self.close()


class _CachedRun:
    """Iterator over a device-cached run — what :func:`stage_stream`
    returns on a cache hit: the blocks are ALREADY device-resident, so
    there is no source, no staging thread, no transfer. Supports the
    same ``close()`` discipline as :class:`StagedStream` so consumers
    under ``contextlib.closing`` need not care which they got."""

    def __init__(self, blocks, name: str):
        self._it = iter(blocks)
        self._name = name

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def close(self) -> None:
        self._it = iter(())

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _CacheRecorder:
    """Wraps a ``place`` function so a completed run installs into the
    device cache: every placed block is recorded, and ``complete`` —
    fired by the stream only on NATURAL source exhaustion — installs
    the full ordered run under ``key``. An abandoned or failed stream
    never installs (a truncated run must not masquerade as the set).

    Recording is BYTE-BOUNDED as it goes: the moment the accumulated
    run exceeds the cache budget, every held block is dropped and
    recording stops — a set bigger than the cache must stream with
    only ``depth`` blocks live (the out-of-core discipline), never
    hold its whole self device-resident waiting for an install that
    would be rejected anyway."""

    def __init__(self, cache, key, place, validator=None):
        from netsdb_tpu.storage.devcache import _value_nbytes

        self._cache = cache
        self._key = key
        self._place = place
        self._validator = validator
        self._nbytes_of = _value_nbytes
        self._blocks: list = []
        self._bytes = 0
        self._cap = cache.budget_bytes
        self._overflow = False
        # attribution identity, captured on the CONSUMER thread at
        # construction: ``complete`` fires on the staging worker, which
        # does not inherit the dispatch context var
        self._client = obs.attrib.current_client()

    def __call__(self, item):
        placed = self._place(item)
        if not self._overflow:
            self._bytes += self._nbytes_of(placed)
            if self._bytes > self._cap:
                self._overflow = True
                self._blocks = []  # release NOW, not at stream end
            else:
                self._blocks.append(placed)
                # evict AS the run grows: resident entries + this run
                # must together stay ~one budget, not spike to two at
                # install time
                self._cache.make_room(self._bytes)
        return placed

    def complete(self) -> None:
        if self._overflow:
            self._cache.reject_oversized()
            return
        # the validator runs INSIDE install's critical section: a
        # write racing this install either invalidates after it (normal
        # eviction) or bumps the version before it (validator rejects)
        # — either way no dead entry can squat on the budget
        self._cache.install(self._key, self._blocks,
                            validator=self._validator,
                            client=self._client)


class PartialPlan:
    """Everything :func:`stage_stream` needs to range-stitch one
    stream against the partial-run device cache (built by the caller,
    which knows the set's block layout):

    * ``cache`` — the :class:`~netsdb_tpu.storage.devcache.
      DeviceBlockCache` (must have ``partial`` on);
    * ``base_key`` — the composite ``(scope, kind, bucket, sharding)``
      block entries key under (scope FIRST — the invalidation index
      relies on it); NO write version — freshness is dirty-range
      invalidation's job;
    * ``ranges`` — the full ordered ``[(start_row, end_row)]`` block
      layout of the set (metadata only, zero arena reads);
    * ``source_for(gap_indices)`` — builds a host iterator yielding
      ONLY those block positions (the arena never reads pages whose
      chunks are already device-resident).
    """

    __slots__ = ("cache", "base_key", "ranges", "source_for")

    def __init__(self, cache, base_key, ranges, source_for):
        self.cache = cache
        self.base_key = tuple(base_key)
        self.ranges = [(int(s), int(e)) for s, e in ranges]
        self.source_for = source_for


class _BlockInstaller:
    """Wraps ``place`` so every placed GAP block installs into the
    partial cache as it streams — partial consumption caches the
    consumed prefix (an early-exit consumer keeps what it paid for,
    unlike the whole-run recorder which discarded everything). Runs on
    the staging thread; the attributed client identity is captured on
    the consumer thread at construction. Installs are epoch-gated, so
    a write racing the stream refuses the in-flight blocks instead of
    stranding stale entries."""

    def __init__(self, cache, base_key, gap_ranges, epoch, place):
        self._cache = cache
        self._base_key = base_key
        self._gaps = list(gap_ranges)  # consumed positionally, in order
        self._epoch = epoch
        self._place = place
        self._i = 0
        self._all_installed = True
        self._client = obs.attrib.current_client()

    def __call__(self, item):
        placed = self._place(item)
        if self._i < len(self._gaps):
            ok = self._cache.install_block(
                self._base_key, self._gaps[self._i], placed,
                epoch=self._epoch, client=self._client)
            self._all_installed = self._all_installed and ok
            self._i += 1
        return placed

    def complete(self) -> None:
        # natural exhaustion with every gap block landed = the
        # partial-mode analogue of one whole-run install (run-level
        # counter semantics preserved for dashboards/SLOs/tests)
        if self._all_installed and self._i == len(self._gaps):
            self._cache.record_run_install(str(self._base_key[0]),
                                           client=self._client)


class _StitchedStream:
    """Row-order interleave of device-cached blocks and a staged gap
    stream — what :func:`stage_stream` returns on a PARTIAL cache hit:
    cached ranges serve from HBM (zero arena reads, zero transfers,
    ticked as ``devcache.partial_hits``) while gap ranges arrive
    through the normal host-prefetch→upload pipeline, so the consumer
    sees one seamless stream in block order. Same ``close()``
    discipline as :class:`StagedStream`."""

    def __init__(self, segments, staged, cache, scope: str, name: str):
        # segments: [("hit", block) | ("gap", None)] in block order
        self._segments = segments
        self._staged = staged  # StagedStream over the gaps (or None)
        self._cache = cache
        self._scope = scope
        self._name = name
        self._i = 0
        self._closed = False
        # count the stitch joints once, up front: a contiguous run of
        # cached blocks is ONE stitched range
        stitched = sum(1 for j, (kind, _b) in enumerate(segments)
                       if kind == "hit"
                       and (j == 0 or segments[j - 1][0] != "hit"))
        self._pending_ranges = stitched

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._i >= len(self._segments):
            self.close()
            raise StopIteration
        kind, block = self._segments[self._i]
        self._i += 1
        if kind == "hit":
            # per-block residency tick (the counters the partial-
            # invalidation proof reads) + the one-time stitch count
            self._cache.tick_partial(self._scope, 1,
                                     self._pending_ranges)
            self._pending_ranges = 0
            return block
        return next(self._staged)

    def close(self) -> None:
        self._closed = True
        if self._staged is not None:
            self._staged.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        with contextlib.suppress(Exception):
            self.close()


def _stage_partial(plan: PartialPlan, place, depth: int, name: str,
                   scope: Optional[str]):
    """The partial-mode leg of :func:`stage_stream`: consult, stitch,
    install-as-you-go."""
    scope = scope if scope is not None else str(plan.base_key[0])
    epoch, covered = plan.cache.plan_ranges(plan.base_key, plan.ranges)
    gaps = [i for i, r in enumerate(plan.ranges) if r not in covered]
    if not gaps:
        _emit("cache_hit", name)
        # a fully resident stream: the query profile's zero-transfer
        # marker keeps its whole-run meaning
        obs.add("stage.cached_runs")
        obs.operators.op_add("stage.cached_runs")
        segments = [("hit", covered[r]) for r in plan.ranges]
        return _StitchedStream(segments, None, plan.cache, scope, name)
    rec = _BlockInstaller(plan.cache, plan.base_key,
                          [plan.ranges[i] for i in gaps], epoch, place)
    staged = StagedStream(plan.source_for(gaps), rec, depth=depth,
                          name=name, on_complete=rec.complete,
                          scope=scope)
    if not covered:
        return staged  # fully cold: plain staged stream, installing
    segments = [("hit", covered[r]) if r in covered else ("gap", None)
                for r in plan.ranges]
    return _StitchedStream(segments, staged, plan.cache, scope, name)


def stage_stream(source: Iterable, place: Callable[[Any], Any],
                 depth: int = 2, name: str = "stage",
                 cache=None, cache_key=None, cache_validator=None,
                 scope: Optional[str] = None, partial=None):
    """Wrap ``source`` so ``place`` (pad + upload via
    ``storage/devcache.to_device``) runs up to ``depth`` items ahead on
    a background thread.  The ONE constructor every out-of-core
    consumer goes through — the static check in
    ``tests/test_static_checks.py`` bans loose ``device_put`` call
    sites in ``storage/``, ``plan/`` and ``relational/outofcore.py``
    so neither the overlap nor the cache can silently regress.

    ``cache``/``cache_key`` (a :class:`~netsdb_tpu.storage.devcache.
    DeviceBlockCache` and its versioned key) make the stream
    cache-aware: a hit replays the device-resident run with ZERO
    host→device transfers (no thread, no arena reads); a miss streams
    normally and installs the completed run on the way through — the
    staged-uploads-install-into-the-cache leg of the tentpole.
    ``cache_validator`` (no-arg callable → bool) re-checks at install
    time that ``cache_key`` is still current — a write racing the
    stream must not leave a dead entry squatting on the budget.

    ``scope`` names the set ("db:set") the per-(client, set) resource
    ledger attributes this stream's staged bytes to; defaults to the
    cache key's scope component for cache-aware streams (store-bound
    handles), None for uncached temporaries (grace-hash spills).

    ``partial`` (a :class:`PartialPlan`) takes the BLOCK-GRANULAR
    cache path instead: cached ranges stitch into the stream from HBM
    (zero arena reads), gap ranges stream + install per block, and
    ``source`` is ignored (the plan's ``source_for`` builds the
    gap-only feed). Mutually exclusive with ``cache``/``cache_key``."""
    if partial is not None and partial.cache.enabled \
            and getattr(partial.cache, "partial", False) \
            and partial.ranges:
        return _stage_partial(partial, place, depth, name, scope)
    if partial is not None and source is None:
        # partial plan declined (cache off / empty layout): fall back
        # to a plain uncached stream over the plan's full block feed
        source = partial.source_for(None)
    if scope is None and cache_key is not None:
        scope = str(cache_key[0])
    if cache is not None and cache_key is not None and cache.enabled:
        hit = cache.get(cache_key)
        if hit is not None:
            _emit("cache_hit", name)
            # a whole run served device-resident: the query profile's
            # zero-transfer marker (per-block hit ticks come from the
            # cache itself), attributed to the consuming plan node too
            obs.add("stage.cached_runs")
            obs.operators.op_add("stage.cached_runs")
            return _CachedRun(hit, name)
        rec = _CacheRecorder(cache, cache_key, place, cache_validator)
        return StagedStream(source, rec, depth=depth, name=name,
                            on_complete=rec.complete, scope=scope)
    return StagedStream(source, place, depth=depth, name=name,
                        scope=scope)
