"""Computation DAG — the user-facing query API (reference layer 9).

The reference's ``Computation`` subclasses (SelectionComp /
MultiSelectionComp / JoinComp / AggregateComp / PartitionComp / ScanSet /
SetWriter — ``src/lambdas/headers/Computation.h:21-97``) carry ``Lambda``
trees of per-tuple C++ logic and compile themselves to TCAP strings.
Here each node carries a traced-Python function over set values
(``BlockedTensor``s or host objects); "compiling" is composing those
functions into jit stages (``netsdb_tpu.plan.planner``), with XLA as the
physical optimizer. The node taxonomy is kept 1:1 so every reference
query has a structural analogue, and ``to_plan_string`` emits a
TCAP-like textual dump (debuggability + test surface, standing in for
``src/logicalPlan``'s IR).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, List, Optional, Sequence

_ids = itertools.count()


class Computation:
    """DAG node. ``inputs`` are upstream Computations; ``op_kind`` mirrors
    the reference class name it replaces."""

    op_kind = "Computation"

    def __init__(self, inputs: Sequence["Computation"]):
        self.inputs: List[Computation] = list(inputs)
        self.node_id = next(_ids)
        self.output_name = f"{self.op_kind}_{self.node_id}"

    # --- evaluation hook (overridden) --------------------------------
    def evaluate(self, *args: Any) -> Any:
        raise NotImplementedError

    # --- TCAP-like dump ----------------------------------------------
    def plan_atom(self) -> str:
        ins = ", ".join(i.output_name for i in self.inputs)
        return f"{self.output_name} <= {self.op_kind.upper()}({ins})"

    def __repr__(self):
        return f"<{self.op_kind} #{self.node_id}>"


class ScanSet(Computation):
    """Read a stored set — reference ``ScanUserSet``/``ScanSet``
    (``src/lambdas/headers/ScanSet.h``). Leaf node."""

    op_kind = "Scan"

    def __init__(self, db: str, set_name: str):
        super().__init__([])
        self.db = db
        self.set_name = set_name
        self.output_name = f"scan_{db}_{set_name}_{self.node_id}"

    def plan_atom(self) -> str:
        return f"{self.output_name} <= SCAN('{self.db}', '{self.set_name}')"


class Apply(Computation):
    """1-in selection/projection — reference ``SelectionComp``
    (``src/lambdas/headers/SelectionComp.h``): projection lambda only."""

    op_kind = "Apply"

    def __init__(self, input_: Computation, fn: Callable[[Any], Any],
                 label: str = "", traceable: bool = True):
        """``traceable=False`` marks a host-side projection (numpy / Python
        object work) that must run eagerly outside jit — the reference
        analogue is a C++ lambda that touches non-tensor state."""
        super().__init__([input_])
        self.fn = fn
        self.traceable = traceable
        self.label = label or getattr(fn, "__name__", "fn")

    def evaluate(self, x):
        return self.fn(x)

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= APPLY({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Filter(Computation):
    """Selection predicate — reference ``SelectionComp::getSelection``
    (FILTER atom in TCAP, ``src/logicalPlan/source/Lexer.l``). For host
    object sets; tensor pipelines express filtering as masks."""

    op_kind = "Filter"

    def __init__(self, input_: Computation, pred: Callable[[Any], bool],
                 label: str = ""):
        super().__init__([input_])
        self.pred = pred
        self.label = label or getattr(pred, "__name__", "pred")

    def evaluate(self, items):
        return [x for x in items if self.pred(x)]

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= FILTER({self.inputs[0].output_name}, "
                f"'{self.label}')")


class MultiApply(Computation):
    """1-in → many-out flatten — reference ``MultiSelectionComp``
    (FLATTEN atom). ``fn`` returns a list per input value."""

    op_kind = "Flatten"

    def __init__(self, input_: Computation, fn: Callable[[Any], List[Any]],
                 label: str = ""):
        super().__init__([input_])
        self.fn = fn
        self.label = label or getattr(fn, "__name__", "fn")

    def evaluate(self, items):
        out: List[Any] = []
        for x in items:
            out.extend(self.fn(x))
        return out

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= FLATTEN({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Join(Computation):
    """2-in combine — reference ``JoinComp`` (``src/lambdas/headers/
    JoinComp.h``). For tensor pipelines the join-on-block-index +
    projection collapses into one traced fn (e.g. ``ops.matmul_t``); for
    host sets an equi-join on key fns (hash join, as the reference's
    broadcast/partitioned hash joins)."""

    op_kind = "Join"

    def __init__(self, left: Computation, right: Computation,
                 fn: Optional[Callable[[Any, Any], Any]] = None,
                 left_key: Optional[Callable] = None,
                 right_key: Optional[Callable] = None,
                 project: Optional[Callable[[Any, Any], Any]] = None,
                 label: str = ""):
        super().__init__([left, right])
        self.fn = fn
        self.left_key = left_key
        self.right_key = right_key
        self.project = project
        self.label = label or (getattr(fn, "__name__", "join") if fn else "equijoin")

    def evaluate(self, left, right):
        if self.fn is not None:
            return self.fn(left, right)
        # host-side hash equi-join (reference broadcast join: build small
        # side hash table, probe the large side)
        table = {}
        for r in right:
            table.setdefault(self.right_key(r), []).append(r)
        out = []
        proj = self.project or (lambda a, b: (a, b))
        for l in left:
            for r in table.get(self.left_key(l), ()):
                out.append(proj(l, r))
        return out

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= JOIN({self.inputs[0].output_name}, "
                f"{self.inputs[1].output_name}, '{self.label}')")


class Aggregate(Computation):
    """Group-by/reduce — reference ``AggregateComp``/``ClusterAggregateComp``
    (``src/lambdas/headers/AggregateComp.h``). Tensor pipelines pass a
    traced reduction fn; host sets pass key/value fns + combiner (the
    CombinerProcessor/AggregationProcessor pair collapses into one dict
    fold — the cross-node shuffle it implemented is XLA's problem now)."""

    op_kind = "Aggregate"

    def __init__(self, input_: Computation,
                 fn: Optional[Callable[[Any], Any]] = None,
                 key: Optional[Callable] = None,
                 value: Optional[Callable] = None,
                 combine: Optional[Callable[[Any, Any], Any]] = None,
                 label: str = ""):
        super().__init__([input_])
        self.fn = fn
        self.key = key
        self.value = value
        self.combine = combine
        self.label = label or (getattr(fn, "__name__", "agg") if fn else "groupby")

    def evaluate(self, x):
        if self.fn is not None:
            return self.fn(x)
        acc = {}
        for item in x:
            k = self.key(item)
            v = self.value(item)
            acc[k] = self.combine(acc[k], v) if k in acc else v
        return acc

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= AGGREGATE({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Partition(Computation):
    """Repartition by key — reference ``PartitionComp``
    (``src/lambdas/headers/PartitionComp.h``, TCAP APPLY-PARTITION atom
    ``AtomicComputationClasses.h:497``): route each item to one of
    ``num_partitions`` by its partition-lambda key. Routing uses the
    dispatcher's stable hash, so a set materialized from this node is
    co-partitioned with any set ingested via
    ``HashPolicy`` with the same key fn (the reference's co-located
    join setup). Output is {partition_id: [items]}."""

    op_kind = "Partition"

    def __init__(self, input_: Computation, key_fn: Callable[[Any], Any],
                 num_partitions: int, label: str = ""):
        super().__init__([input_])
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got "
                             f"{num_partitions}")
        self.key_fn = key_fn
        self.num_partitions = num_partitions
        self.traceable = False  # host-object routing, never under jit
        self.label = label or getattr(key_fn, "__name__", "partition")

    def evaluate(self, items):
        from netsdb_tpu.storage.dispatcher import HashPolicy

        # same routing as the dispatcher by construction (the
        # co-partitioning guarantee in the class docstring)
        parts = HashPolicy(self.key_fn).partition(items,
                                                  self.num_partitions)
        return dict(enumerate(parts))

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= PARTITION("
                f"{self.inputs[0].output_name}, '{self.label}')")


class WriteSet(Computation):
    """Materialize into a set — reference ``SetWriter``/``WriteUserSet``.
    Sink node; stage boundary (the reference's pipeline breaker)."""

    op_kind = "Write"

    def __init__(self, input_: Computation, db: str, set_name: str):
        super().__init__([input_])
        self.db = db
        self.set_name = set_name

    def evaluate(self, x):
        return x

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= OUTPUT({self.inputs[0].output_name}, "
                f"'{self.db}', '{self.set_name}')")
