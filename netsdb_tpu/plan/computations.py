"""Computation DAG — the user-facing query API (reference layer 9).

The reference's ``Computation`` subclasses (SelectionComp /
MultiSelectionComp / JoinComp / AggregateComp / PartitionComp / ScanSet /
SetWriter — ``src/lambdas/headers/Computation.h:21-97``) carry ``Lambda``
trees of per-tuple C++ logic and compile themselves to TCAP strings.
Here each node carries a traced-Python function over set values
(``BlockedTensor``s or host objects); "compiling" is composing those
functions into jit stages (``netsdb_tpu.plan.planner``), with XLA as the
physical optimizer. The node taxonomy is kept 1:1 so every reference
query has a structural analogue, and ``to_plan_string`` emits a
TCAP-like textual dump (debuggability + test surface, standing in for
``src/logicalPlan``'s IR).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, List, Optional, Sequence

_ids = itertools.count()

#: Labels of the suite's AUDITED row-decomposable chunk transforms —
#: the DERIVED ``rowwise`` set (PR 10 follow-on). An :class:`Apply`
#: whose label matches an entry (exact match, or prefix match for
#: entries ending in ``:``) auto-derives ``rowwise=True`` instead of
#: requiring a per-node declaration; call sites must NOT additionally
#: pass ``rowwise=True`` for these labels (the ``rowwise-shadow`` lint
#: rule flags the shadowing declaration — one source of truth).
#:
#: Membership is a CORRECTNESS contract, audited like a FoldSpec
#: decomposition: every listed label names a per-row transform that
#: (a) maps any row-slice to exactly the matching row-slice of the
#: whole-input result and (b) preserves the chunk contract AND the
#: schema surface (see the ``rowwise`` docstring below). The in-repo
#: members are the bench/suite pre-chain transforms under the
#: ``pre:`` namespace: affine per-row column maps (``pre:affine``),
#: column projections/renames-free selections (``pre:project``) and
#: per-row scaling (``pre:scale``).
ROWWISE_SAFE_LABELS = ("pre:affine", "pre:project", "pre:scale")


def rowwise_safe(label: str) -> bool:
    """True when ``label`` is in the derived rowwise set (exact entry,
    or namespace entry ending in ``:`` matched as a prefix)."""
    lab = str(label or "")
    return any(lab.startswith(entry) if entry.endswith(":")
               else lab == entry for entry in ROWWISE_SAFE_LABELS)


class Computation:
    """DAG node. ``inputs`` are upstream Computations; ``op_kind`` mirrors
    the reference class name it replaces."""

    op_kind = "Computation"

    def __init__(self, inputs: Sequence["Computation"]):
        self.inputs: List[Computation] = list(inputs)
        self.node_id = next(_ids)
        self.output_name = f"{self.op_kind}_{self.node_id}"

    # --- evaluation hook (overridden) --------------------------------
    def evaluate(self, *args: Any) -> Any:
        raise NotImplementedError

    # --- TCAP-like dump ----------------------------------------------
    def plan_atom(self) -> str:
        ins = ", ".join(i.output_name for i in self.inputs)
        return f"{self.output_name} <= {self.op_kind.upper()}({ins})"

    def __repr__(self):
        return f"<{self.op_kind} #{self.node_id}>"


class ScanSet(Computation):
    """Read a stored set — reference ``ScanUserSet``/``ScanSet``
    (``src/lambdas/headers/ScanSet.h``). Leaf node."""

    op_kind = "Scan"

    def __init__(self, db: str, set_name: str):
        super().__init__([])
        self.db = db
        self.set_name = set_name
        self.output_name = f"scan_{db}_{set_name}_{self.node_id}"

    def plan_atom(self) -> str:
        return f"{self.output_name} <= SCAN('{self.db}', '{self.set_name}')"


class Apply(Computation):
    """1-in selection/projection — reference ``SelectionComp``
    (``src/lambdas/headers/SelectionComp.h``): projection lambda only."""

    op_kind = "Apply"

    def __init__(self, input_: Computation, fn: Optional[Callable[[Any], Any]] = None,
                 label: str = "", traceable: bool = True, fold=None,
                 tensor_fold=None, rowwise: Optional[bool] = None):
        """``traceable=False`` marks a host-side projection (numpy / Python
        object work) that must run eagerly outside jit — the reference
        analogue is a C++ lambda that touches non-tensor state.

        ``fold`` (:class:`netsdb_tpu.plan.fold.FoldSpec`) gives the node
        a streamable decomposition; when the scanned set is paged, the
        executor folds the node over the page stream instead of calling
        ``fn``. With ``fn=None`` the whole-table path is derived from
        the fold, so the two cannot diverge.

        ``tensor_fold`` (:class:`netsdb_tpu.plan.fold.TensorFold`) is
        the same for a paged TENSOR input: the executor streams the
        matrix's row-block pages through the node (in-DB inference over
        storage-managed weights, ref ``SimpleFF.cc:94-290``).

        **Label contract (jit-cache correctness).** On the streamed
        executor path, a traceable node's ``fn`` is compiled ONCE per
        ``(job_name, canonical plan, topo position, label)`` and
        REUSED across executions. Parameters ``fn`` bakes into its
        closure (thresholds, constants, captured arrays) are traced
        into that first compilation as constants — so two DAGs that
        differ ONLY in closure values but share job name, plan shape
        and label will silently reuse the first DAG's stale constants.
        Either reflect every closure parameter in ``label`` (what the
        in-repo builders do: ``label=f"filter>{cutoff}"``) or vary
        ``job_name`` per parameterization. Non-traceable
        (``traceable=False``) nodes evaluate fresh every time and are
        exempt. See README "Execution pipeline".

        ``rowwise=True`` declares ``fn`` ROW-DECOMPOSABLE: applied to
        any row-slice of its input it produces exactly the matching
        row-slice of the whole-input result, and it preserves the
        chunk contract (a ColumnTable in → a ColumnTable out, row for
        row, validity mask and ``_rowid`` untouched or forwarded) AND
        the table's SCHEMA SURFACE — column names and dictionary
        encodings a downstream fold's ``init``/``finalize`` may read.
        The fusion mapper (``plan/fusion.py``) uses the declaration to
        fuse the node into a downstream fold's per-chunk step when the
        scanned set is paged — the chunk is transformed and reduced in
        one compiled program instead of materializing the whole set
        for the transform. Under that fusion only the STEPS see
        transformed chunks; ``init(state, src, ...)`` and
        ``finalize(state, src, ...)`` still receive the raw scan
        handle, which is why a rename or dictionary re-encoding
        (schema the fold could observe via ``src``) disqualifies the
        declaration. Declaring ``rowwise`` for a fn that mixes rows
        (sorts, global statistics, cross-row joins) or reshapes the
        schema surface silently computes the wrong answer on paged
        inputs — the same class of contract as a FoldSpec's
        decomposition.

        ``rowwise=None`` (the default) DERIVES the declaration from
        the audited label registry (:data:`ROWWISE_SAFE_LABELS`): the
        suite's known-safe pre-chain transforms fuse without per-node
        declarations, and a label outside the registry stays
        non-rowwise. Passing an explicit True/False always wins —
        but an explicit ``rowwise=True`` on a registry label shadows
        the derived set and is flagged by the ``rowwise-shadow`` lint
        rule (drop the argument; the registry is the one source of
        truth for those labels)."""
        super().__init__([input_])
        self.fold = fold
        self.tensor_fold = tensor_fold
        if fn is None:
            if fold is None:
                raise ValueError("Apply needs fn or fold")
            fn = fold.whole
        self.fn = fn
        self.traceable = traceable
        self.label = label or getattr(fn, "__name__", "fn")
        # None = derive from the audited registry; an explicit
        # declaration (True OR False) always wins over derivation
        self.rowwise_declared = rowwise is not None
        self.rowwise = (bool(rowwise) if rowwise is not None
                        else rowwise_safe(self.label))

    def evaluate(self, x):
        return self.fn(x)

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= APPLY({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Filter(Computation):
    """Selection predicate — reference ``SelectionComp::getSelection``
    (FILTER atom in TCAP, ``src/logicalPlan/source/Lexer.l``). For host
    object sets; tensor pipelines express filtering as masks."""

    op_kind = "Filter"

    def __init__(self, input_: Computation, pred: Callable[[Any], bool],
                 label: str = ""):
        super().__init__([input_])
        self.pred = pred
        self.label = label or getattr(pred, "__name__", "pred")

    def evaluate(self, items):
        return [x for x in items if self.pred(x)]

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= FILTER({self.inputs[0].output_name}, "
                f"'{self.label}')")


class MultiApply(Computation):
    """1-in → many-out flatten — reference ``MultiSelectionComp``
    (FLATTEN atom). ``fn`` returns a list per input value."""

    op_kind = "Flatten"

    def __init__(self, input_: Computation, fn: Callable[[Any], List[Any]],
                 label: str = ""):
        super().__init__([input_])
        self.fn = fn
        self.label = label or getattr(fn, "__name__", "fn")

    def evaluate(self, items):
        out: List[Any] = []
        for x in items:
            out.extend(self.fn(x))
        return out

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= FLATTEN({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Join(Computation):
    """2-in combine — reference ``JoinComp`` (``src/lambdas/headers/
    JoinComp.h``). For tensor pipelines the join-on-block-index +
    projection collapses into one traced fn (e.g. ``ops.matmul_t``); for
    host sets an equi-join on key fns (hash join, as the reference's
    broadcast/partitioned hash joins)."""

    op_kind = "Join"

    def __init__(self, left: Computation, right: Computation,
                 fn: Optional[Callable[[Any, Any], Any]] = None,
                 left_key: Optional[Callable] = None,
                 right_key: Optional[Callable] = None,
                 project: Optional[Callable[[Any, Any], Any]] = None,
                 label: str = "", fold=None, fold_src: int = 0,
                 on: Optional[tuple] = None,
                 take: Optional[Sequence[str]] = None,
                 tensor_fold=None, passthrough: bool = False):
        """``fold`` + ``fold_src``: streamable decomposition (see
        :class:`netsdb_tpu.plan.fold.FoldSpec`); ``fold_src`` says which
        input (0=left, 1=right) is the probe/fact side the page stream
        replaces — the other input's value is passed to the fold as
        resident state (gather-chain tuples flattened).

        ``on=(left_col, right_col)`` declares the equi-join key by
        COLUMN NAME (the reference's attribute-naming join lambdas,
        ``JoinComp::getKeySelection``) and lowers evaluation to the
        device LUT/sort join (``relational.autojoin.equijoin``):
        object-record inputs columnarize automatically, string keys
        ride dictionary unification, and the probe is one device
        gather — the automatic form of what round 3 exposed only as
        hand calls. ``take`` limits which right columns are gathered.
        Callable ``left_key``/``right_key`` stay the interpreter
        fallback for keys no column expresses.

        **Label contract**: a traceable ``fn``-bearing Join on the
        streamed executor path shares one compiled program per
        ``(job_name, plan shape, topo position, label)`` — closure
        constants inside ``fn`` must be reflected in ``label`` (or a
        distinct ``job_name``) or a structurally identical DAG reuses
        this one's baked-in values. See :class:`Apply` for the full
        contract."""
        super().__init__([left, right])
        self.fold = fold
        self.fold_src = fold_src
        # streamable decomposition over a paged TENSOR input (weight
        # scans — see Apply docstring / plan.fold.TensorFold)
        self.tensor_fold = tensor_fold
        # passthrough=True: fn only re-shapes its inputs (the gather-
        # chain tuple append) — the streamed executor forwards paged
        # handles through it UNMATERIALIZED so a downstream fold can
        # stream them (grace-hash build sides behind a gather chain)
        self.passthrough = passthrough
        self.on = tuple(on) if on else None
        self.take = take
        if fn is None and fold is not None and left_key is None:
            from netsdb_tpu.plan.fold import flatten_resident

            if fold_src == 0:
                fn = lambda a, b: fold.whole(a, *flatten_resident((b,)))
            else:
                fn = lambda a, b: fold.whole(b, *flatten_resident((a,)))
        self.fn = fn
        self.left_key = left_key
        self.right_key = right_key
        self.project = project
        self.label = label or (getattr(fn, "__name__", "join") if fn else "equijoin")

    def evaluate(self, left, right):
        if self.fn is not None:
            return self.fn(left, right)
        if self.on is not None:
            # device path: columnarize records if needed, then one
            # LUT/sort equi-join gather (string keys unify host-side)
            from netsdb_tpu.relational.autojoin import (equijoin,
                                                        table_from_objects)
            from netsdb_tpu.relational.table import ColumnTable

            lt = (left if isinstance(left, ColumnTable)
                  else table_from_objects(left))
            rt = (right if isinstance(right, ColumnTable)
                  else table_from_objects(right))
            return equijoin(lt, self.on[0], rt, self.on[1], take=self.take)
        # host-side hash equi-join (reference broadcast join: build small
        # side hash table, probe the large side)
        table = {}
        for r in right:
            table.setdefault(self.right_key(r), []).append(r)
        out = []
        proj = self.project or (lambda a, b: (a, b))
        for l in left:
            for r in table.get(self.left_key(l), ()):
                out.append(proj(l, r))
        return out

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= JOIN({self.inputs[0].output_name}, "
                f"{self.inputs[1].output_name}, '{self.label}')")


class Aggregate(Computation):
    """Group-by/reduce — reference ``AggregateComp``/``ClusterAggregateComp``
    (``src/lambdas/headers/AggregateComp.h``). Tensor pipelines pass a
    traced reduction fn; host sets pass key/value fns + combiner (the
    CombinerProcessor/AggregationProcessor pair collapses into one dict
    fold — the cross-node shuffle it implemented is XLA's problem now)."""

    op_kind = "Aggregate"

    def __init__(self, input_: Computation,
                 fn: Optional[Callable[[Any], Any]] = None,
                 key: Optional[Callable] = None,
                 value: Optional[Callable] = None,
                 combine: Optional[Callable[[Any, Any], Any]] = None,
                 label: str = ""):
        super().__init__([input_])
        self.fn = fn
        self.key = key
        self.value = value
        self.combine = combine
        self.label = label or (getattr(fn, "__name__", "agg") if fn else "groupby")

    def evaluate(self, x):
        if self.fn is not None:
            return self.fn(x)
        acc = {}
        for item in x:
            k = self.key(item)
            v = self.value(item)
            acc[k] = self.combine(acc[k], v) if k in acc else v
        return acc

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= AGGREGATE({self.inputs[0].output_name}, "
                f"'{self.label}')")


class Partition(Computation):
    """Repartition by key — reference ``PartitionComp``
    (``src/lambdas/headers/PartitionComp.h``, TCAP APPLY-PARTITION atom
    ``AtomicComputationClasses.h:497``): route each item to one of
    ``num_partitions`` by its partition-lambda key. Routing uses the
    dispatcher's stable hash, so a set materialized from this node is
    co-partitioned with any set ingested via
    ``HashPolicy`` with the same key fn (the reference's co-located
    join setup). Output is {partition_id: [items]}."""

    op_kind = "Partition"

    def __init__(self, input_: Computation, key_fn,
                 num_partitions: int, label: str = "",
                 slack: float = 2.0):
        """``key_fn`` may be a callable (host-object routing) or a
        COLUMN NAME string: over a placed ColumnTable input, the node
        then lowers to the device all_to_all row shuffle
        (``relational.shuffle.hash_repartition``) on the mesh the
        set's placement put the columns on — the reference's
        partition stage shipping rows to their owning workers
        (``PipelineStage.cc:1652-1728``), output a ShardedRows a
        downstream ``local_join``/aggregate stage consumes."""
        super().__init__([input_])
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got "
                             f"{num_partitions}")
        self.key_fn = key_fn
        self.num_partitions = num_partitions
        self.slack = slack
        self.traceable = False  # host routing / shard_map progs run eager
        self.label = label or (key_fn if isinstance(key_fn, str)
                               else getattr(key_fn, "__name__", "partition"))

    def evaluate(self, items):
        if isinstance(self.key_fn, str):
            from netsdb_tpu.relational.shuffle import hash_repartition
            from netsdb_tpu.relational.table import ColumnTable

            if not isinstance(items, ColumnTable):
                raise TypeError(
                    f"Partition on column {self.key_fn!r} needs a "
                    f"ColumnTable input; got {type(items).__name__}")
            mesh, axis = _mesh_of_table(items)
            if mesh.shape[axis] != self.num_partitions:
                raise ValueError(
                    f"Partition declared {self.num_partitions} "
                    f"partitions but the set's placement meshes "
                    f"{mesh.shape[axis]} shards on {axis!r}")
            return hash_repartition(mesh, axis, dict(items.cols),
                                    self.key_fn, self.slack,
                                    valid=items.valid)
        from netsdb_tpu.storage.dispatcher import HashPolicy

        # same routing as the dispatcher by construction (the
        # co-partitioning guarantee in the class docstring)
        parts = HashPolicy(self.key_fn).partition(items,
                                                  self.num_partitions)
        return dict(enumerate(parts))

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= PARTITION("
                f"{self.inputs[0].output_name}, '{self.label}')")


def _mesh_of_table(table):
    """(mesh, axis) a placed ColumnTable's columns live on — read off
    the arrays' NamedSharding, so DAG nodes never take a hand mesh."""
    import jax

    for col in table.cols.values():
        sh = getattr(col, "sharding", None)
        if sh is not None and hasattr(sh, "mesh") and sh.spec:
            for entry in sh.spec:
                if entry is not None:
                    ax = entry if isinstance(entry, str) else entry[0]
                    return sh.mesh, ax
    raise ValueError(
        "device Partition needs a placed (mesh-sharded) input set — "
        "create the set with a row-sharding Placement")


class WriteSet(Computation):
    """Materialize into a set — reference ``SetWriter``/``WriteUserSet``.
    Sink node; stage boundary (the reference's pipeline breaker)."""

    op_kind = "Write"

    def __init__(self, input_: Computation, db: str, set_name: str):
        super().__init__([input_])
        self.db = db
        self.set_name = set_name

    def evaluate(self, x):
        return x

    def plan_atom(self) -> str:
        return (f"{self.output_name} <= OUTPUT({self.inputs[0].output_name}, "
                f"'{self.db}', '{self.set_name}')")
