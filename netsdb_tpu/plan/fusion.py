"""Fusion-aware plan compilation — the cost-driven region mapper.

The executor used to make a binary per-node choice: eager interpret or
one ``_cached_jit`` entry per operator, with the whole-plan fused path
reserved for fully-resident sinks.  Mixed paged/resident plans — the
production shape, a streamed fact fold joined against resident
dimension math — therefore paid per-node dispatch, intermediate
materialization and one XLA program per operator on exactly the spine
the whole-plan path would have fused.

Per *Operator Fusion in XLA* (arxiv 2301.13062) fusion is the dominant
XLA optimization but greedy always-fuse heuristics misfire, and *Fast
and Fusiest* (arxiv 2602.15166) shows a fusion **mapper** driven by an
explicit cost feed beats both never-fuse and always-fuse.  This module
is that mapper: it partitions a :class:`~netsdb_tpu.plan.planner.
LogicalPlan` into **fusion regions** and hands the executor a
:class:`RegionMap` to execute region-at-a-time:

* **spine regions** — maximal topo-contiguous runs of traceable,
  resident-valued nodes.  The executor compiles each region as ONE
  jitted program (one ``_cached_jit`` entry keyed by the region's
  structural fingerprint, replacing N per-node entries and N
  dispatches).  Topo-contiguity makes regions convex by construction:
  every external input precedes the region, every external consumer
  follows it, so region-at-a-time replay is a pure reordering of the
  per-node schedule.
* **graft regions** — a streamed fold (or paged-tensor stream) node
  plus the traceable work fused into its streaming loop: upstream
  ``rowwise`` Apply nodes fold into the per-chunk step (the chunk is
  transformed AND reduced in one compiled step instead of
  materialize→per-node dispatch), and the downstream single-consumer
  traceable chain compiles into one epilogue program applied to the
  fold's merged output (fold→materialize→per-node dispatch becomes
  fold→one program).

The cost feed is PR 7's :class:`~netsdb_tpu.obs.operators.
OperatorLedger`: per-(job, node-label) mean wall vs device-estimate
seconds (their gap is the dispatch overhead fusion deletes), staged
bytes, and retrace counts (a label that retraces chronically would
amplify inside a region — the mapper leaves it unfused).  Labels the
ledger has never seen fall back to a conservative static estimate
(``fusion_cost_source="static"`` forces that mode).  Decisions are
observable: ``fusion.regions_formed`` / ``fusion.nodes_fused`` /
``fusion.fallbacks`` / ``fusion.cost_estimates`` counters (catalogued
in docs/METRICS.md), per-node ``region`` ids in the EXPLAIN ANALYZE
tree, and per-region trace counters in ``executor.compile_stats()``.

Two mappers share the region machinery (``config.fusion_mapper``):

* ``"optimal"`` (default) — each maximal fusable run is partitioned
  EXACTLY by DP over its region lattice (runs are topo-contiguous and
  convex, so the lattice is the contiguous segmentations and the DP
  is exact, not a heuristic).  The cost model additionally learns
  per-label **staged bytes** from the ledger, so a run whose
  single-region staging estimate exceeds
  ``fusion_stage_budget_bytes`` SPLITS at its cheapest admissible
  edges (``fusion.splits``) instead of abandoning the run per-node.
* ``"greedy"`` — the PR 10 flush-the-whole-run mapper, byte-for-byte
  (same region ids, fingerprints, jit keys and counters): the
  rollback arm the A/B advisor compares against
  (:func:`~netsdb_tpu.learning.advisor.mapper_candidates`).

The mapper also owns the **scatter boundary**: a shard-side partial
fold (``scatter_partial`` sinks shipped by plan/scatter.py) forms a
region even when it has nothing local to graft — the shard's one
compiled program — and :func:`compile_scatter_merge` compiles the
coordinator's merge+finalize seam as ONE program through the same
``_cached_jit`` discipline (the only sanctioned route: the
``scatter-jit-route`` lint rule bans direct program construction for
scatter subplans anywhere else).  Both tick
``fusion.distributed_regions``.

``config.plan_fusion=False`` disables the mapper entirely — the
executor then takes byte-for-byte the per-node paths (same jit-cache
keys, same trace counts, same EXPLAIN shape), so the knob is a safe
rollback.  Fusion on/off is also exposed as advisor **arms**
(:func:`~netsdb_tpu.learning.advisor.fusion_candidates`) so the
``learning/`` bandit can A/B the decision per job the way it already
learns placements.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from netsdb_tpu import obs
from netsdb_tpu.plan.computations import (
    Aggregate,
    Apply,
    Computation,
    Join,
    ScanSet,
    WriteSet,
)
from netsdb_tpu.plan.planner import LogicalPlan

# ------------------------------------------------------------------
# value-kind classification (the static mirror of dispatch)
# ------------------------------------------------------------------

#: value kinds a node's output can statically be — the mapper's
#: abstraction of what the executor's dispatch decides from runtime
#: types. "tensor" = jit-safe resident value (ColumnTable /
#: BlockedTensor / array); everything else is a fusion barrier.
K_TENSOR = "tensor"
K_PAGED_REL = "paged_rel"
K_PAGED_TENSOR = "paged_tensor"
K_PAGED_OBJ = "paged_obj"
K_HOST = "host"
K_GATHER = "gather"  # passthrough tuple possibly carrying paged handles
#: a paged relation seen THROUGH a single-consumer chain of declared
#: ``rowwise`` Apply nodes — still streamable: a downstream fold can
#: graft the chain into its per-chunk step instead of forcing the
#: demote-to-host-table path
K_ROWWISE_PAGED = "rowwise_paged"


def classify_values(plan: LogicalPlan, scan_values: Dict[int, Any],
                    consumers: Optional[Dict[int, List[Computation]]]
                    = None) -> Dict[int, str]:
    """node_id → value kind, propagated topo-forward from the scan
    values the executor already fetched. Deliberately conservative:
    a kind the rules cannot prove lands on ``K_HOST`` (the node simply
    stays on today's per-node path — misclassification can only LOSE a
    fusion opportunity, never fuse an unsafe node; the executor's
    runtime jit-safety check is the second net)."""
    import jax
    import numpy as _np

    from netsdb_tpu.core.blocked import BlockedTensor
    from netsdb_tpu.relational.table import ColumnTable

    if consumers is None:
        consumers = plan.consumers()
    kinds: Dict[int, str] = {}
    for node in plan.topo:
        if isinstance(node, ScanSet):
            v = scan_values.get(node.node_id)
            # late imports only where needed: PagedColumns/PagedTensor
            # live in heavier modules
            tname = type(v).__name__
            if tname == "PagedColumns":
                kinds[node.node_id] = K_PAGED_REL
            elif tname == "PagedTensor":
                kinds[node.node_id] = K_PAGED_TENSOR
            elif tname == "PagedObjects":
                kinds[node.node_id] = K_PAGED_OBJ
            elif isinstance(v, (ColumnTable, BlockedTensor, jax.Array)):
                kinds[node.node_id] = K_TENSOR
            elif isinstance(v, _np.ndarray):
                kinds[node.node_id] = K_TENSOR
            else:
                kinds[node.node_id] = K_HOST
            continue
        in_kinds = [kinds.get(i.node_id, K_HOST) for i in node.inputs]
        if isinstance(node, WriteSet):
            kinds[node.node_id] = in_kinds[0] if in_kinds else K_HOST
            continue
        if getattr(node, "passthrough", False):
            kinds[node.node_id] = (
                K_GATHER if any(k != K_TENSOR for k in in_kinds)
                else K_TENSOR)
            continue
        fold = getattr(node, "fold", None)
        src = getattr(node, "fold_src", 0)
        if (fold is not None and len(in_kinds) > src
                and in_kinds[src] in (K_PAGED_REL, K_ROWWISE_PAGED)):
            kinds[node.node_id] = K_TENSOR  # fold output: table/array
            continue
        if (isinstance(node, Apply)
                and getattr(node, "rowwise", False)
                and node.fn is not None
                and getattr(node, "traceable", True)
                and in_kinds
                and in_kinds[0] in (K_PAGED_REL, K_ROWWISE_PAGED)
                and len(consumers.get(node.node_id, ())) == 1):
            # a declared row-decomposable transform over a (possibly
            # already-chained) paged stream stays STREAMABLE — a
            # downstream fold grafts the chain into its chunk step
            kinds[node.node_id] = K_ROWWISE_PAGED
            continue
        if any(k == K_PAGED_TENSOR for k in in_kinds):
            # tensor stream (or an error at dispatch) — output is the
            # assembled tensor either way
            kinds[node.node_id] = K_TENSOR
            continue
        if any(k in (K_PAGED_REL, K_PAGED_OBJ, K_GATHER,
                     K_ROWWISE_PAGED) for k in in_kinds):
            # dispatch demotes paged relations to host tables before
            # evaluating — output may be a table, but the node itself
            # cannot join a region (it needs the demote)
            kinds[node.node_id] = K_TENSOR if getattr(node, "fn", None) \
                is not None else K_HOST
            continue
        fn = getattr(node, "fn", None)
        if fn is not None and getattr(node, "traceable", True) \
                and all(k == K_TENSOR for k in in_kinds):
            kinds[node.node_id] = K_TENSOR
            continue
        if isinstance(node, Join) and node.fn is None \
                and node.on is not None:
            kinds[node.node_id] = K_TENSOR  # device equijoin → table
            continue
        kinds[node.node_id] = K_HOST
    return kinds


# ------------------------------------------------------------------
# cost model
# ------------------------------------------------------------------

#: static per-node dispatch overhead assumed for labels the ledger has
#: never seen (one python dispatch + jit-call round trip, conservative
#: for CPU and TPU alike)
STATIC_DISPATCH_S = 50e-6
#: a ledger label whose mean traces-per-execution exceeds this keeps
#: its nodes OUT of regions: chronic retracing would recompile the
#: whole fused program instead of one operator
RETRACE_RATE_CAP = 1.5
#: static per-node staged-bytes estimate for labels the ledger has
#: never seen — conservative enough that budget pressure with a cold
#: ledger still splits a long run rather than over-packing HBM/pin
STATIC_STAGED_BYTES = 4 * 1024 * 1024


class CostModel:
    """Per-node cost estimates over the :class:`OperatorLedger` feed.

    ``source="ledger"`` (default) reads the bounded per-(job,
    kind:label) ledger rows — mean wall vs device-estimate seconds
    (their gap ≈ dispatch/interpreter overhead, the quantity fusion
    recovers) and mean retraces per execution.  Unseen labels fall
    back to the static estimate; ``source="static"`` forces the
    fallback for every node (cold daemons, tests)."""

    def __init__(self, job_name: str, source: str = "ledger"):
        self.source = source
        self._rows: Dict[str, Dict[str, float]] = {}
        if source == "ledger":
            # per-job read, NOT a whole-ledger snapshot: this runs on
            # every streamed execution
            self._rows = obs.operators.LEDGER.job_rows(job_name)

    def _row(self, node: Computation) -> Optional[Dict[str, float]]:
        label = getattr(node, "label", "") or ""
        kind = getattr(node, "op_kind", "?")
        return self._rows.get(f"{kind}:{label}")

    def dispatch_overhead_s(self, node: Computation) -> float:
        """Estimated per-execution overhead fusing this node deletes."""
        obs.REGISTRY.counter("fusion.cost_estimates").inc()
        row = self._row(node)
        if row and row.get("count"):
            n = row["count"]
            gap = (row.get("wall_s", 0.0)
                   - row.get("device_est_s", 0.0)) / n
            # the measured gap, floored by the static dispatch cost —
            # a ledger mean can be noisy-low, never truly zero
            return max(gap, STATIC_DISPATCH_S)
        return STATIC_DISPATCH_S

    def retrace_rate(self, node: Computation) -> float:
        """Mean XLA traces per execution (0.0 when unseen — a cold
        label is not evidence of churn)."""
        row = self._row(node)
        if row and row.get("count"):
            return row.get("traces", 0.0) / row["count"]
        return 0.0

    def staged_bytes(self, node: Computation) -> float:
        """Mean bytes one execution of this node's label holds on
        device: the ledger's per-label ``stage.bytes`` (streamed
        chunk uploads) plus ``bytes_in`` (resident input surface),
        per execution.  Cold labels fall back to the static per-node
        estimate, mirroring :meth:`dispatch_overhead_s`."""
        row = self._row(node)
        if row and row.get("count"):
            b = (row.get("stage.bytes", 0.0)
                 + row.get("bytes_in", 0.0)) / row["count"]
            if b > 0:
                return b
        return float(STATIC_STAGED_BYTES)

    def region_profitable(self, nodes: Sequence[Computation]) -> bool:
        """Fuse when the summed dispatch saving is positive and no
        member label retraces chronically."""
        if any(self.retrace_rate(n) > RETRACE_RATE_CAP for n in nodes):
            return False
        saving = sum(self.dispatch_overhead_s(n) for n in nodes)
        # fusing N nodes keeps 1 dispatch of the N
        return saving > STATIC_DISPATCH_S


# ------------------------------------------------------------------
# regions
# ------------------------------------------------------------------

@dataclasses.dataclass
class Region:
    """One fusion unit. ``kind="spine"``: ``node_ids`` compile as one
    program. ``kind="graft"``: ``anchor`` is the streaming fold node,
    ``pre_ids`` the rowwise chunk transforms fused into its step,
    ``post_ids`` the downstream chain fused into its epilogue."""

    rid: int
    kind: str  # "spine" | "graft"
    node_ids: Tuple[int, ...]  # topo order, anchor included for grafts
    fingerprint: str
    anchor: Optional[int] = None
    pre_ids: Tuple[int, ...] = ()
    post_ids: Tuple[int, ...] = ()
    #: the paged ScanSet feeding a fused pre-chain (the executor
    #: substitutes its stream handle for the chain's skipped output)
    stream_src: Optional[int] = None


class RegionMap:
    """The mapper's verdict for one plan execution."""

    def __init__(self, regions: List[Region]):
        self.regions = regions
        #: node_id → region, for every node any region covers
        self.by_node: Dict[int, Region] = {}
        #: node ids whose evaluation is subsumed by their region (the
        #: executor's topo loop skips them): spine non-trigger nodes,
        #: graft pre/post chains — NOT the graft anchor (it still
        #: dispatches, with the region woven into its fold)
        self.fused_away: set = set()
        #: spine regions keyed by their FIRST node (the trigger)
        self.spine_at: Dict[int, Region] = {}
        for r in regions:
            for nid in r.node_ids:
                self.by_node[nid] = r
            if r.kind == "spine":
                self.spine_at[r.node_ids[0]] = r
                self.fused_away.update(r.node_ids[1:])
            else:
                self.fused_away.update(r.pre_ids)
                self.fused_away.update(r.post_ids)

    def region_of(self, node_id: int) -> Optional[int]:
        r = self.by_node.get(node_id)
        return r.rid if r is not None else None


def _fingerprint(plan: LogicalPlan, node_ids: Sequence[int]) -> str:
    """Structural digest of a region: canonical (topo-renumbered)
    atoms of its nodes — two builds of the same DAG fingerprint
    identically, two regions differing in any label do not."""
    names = {n.node_id: f"n{i}" for i, n in enumerate(plan.topo)}
    sel = set(node_ids)
    atoms = []
    for n in plan.topo:
        if n.node_id not in sel:
            continue
        ins = ",".join(names[i.node_id] for i in n.inputs)
        label = getattr(n, "label", "") or getattr(n, "op_kind", "?")
        atoms.append(f"{names[n.node_id]}={n.op_kind}({ins};{label})")
    return hashlib.blake2s("|".join(atoms).encode()).hexdigest()[:12]


def map_regions(plan: LogicalPlan, scan_values: Dict[int, Any],
                config=None, job_name: str = "job",
                traceable: Optional[Callable[[Computation], bool]] = None,
                consumers: Optional[Dict[int, List[Computation]]] = None
                ) -> RegionMap:
    """Partition ``plan`` into fusion regions (see module docstring).

    ``traceable`` is the executor's ``_is_traceable`` predicate
    (injected to keep this module import-light).  Counters:
    ``fusion.regions_formed`` and ``fusion.nodes_fused`` tick per call
    — an always-on mapper over a busy daemon shows its activity on the
    scrape."""
    if traceable is None:
        traceable = lambda n: getattr(n, "traceable", True)  # noqa: E731
    min_region = max(2, int(getattr(config, "fusion_min_region", 2)))
    source = getattr(config, "fusion_cost_source", "ledger")
    mapper = getattr(config, "fusion_mapper", "optimal")
    budget = int(getattr(config, "fusion_stage_budget_bytes", 0) or 0)
    cost = CostModel(job_name, source=source)
    if consumers is None:
        consumers = plan.consumers()
    kinds = classify_values(plan, scan_values, consumers)

    regions: List[Region] = []
    rid = 0
    graft_covered: set = set()

    # --- graft regions FIRST: streamed folds + their fusable
    # neighbors (the fold-centric fusion gets priority over spines —
    # a chain absorbed into the fold's compiled loop must not be
    # claimed by a spine region instead) ---------------------------
    for node in plan.topo:
        fold = getattr(node, "fold", None)
        src = getattr(node, "fold_src", 0)
        in_kinds = [kinds.get(i.node_id, K_HOST) for i in node.inputs]
        anchored = (fold is not None and len(in_kinds) > src
                    and in_kinds[src] in (K_PAGED_REL,
                                          K_ROWWISE_PAGED))
        tensor_anchored = (getattr(node, "tensor_fold", None) is not None
                           and any(k == K_PAGED_TENSOR
                                   for k in in_kinds))
        if not (anchored or tensor_anchored):
            continue

        # upstream: rowwise Apply chain between the paged scan and the
        # fold's stream input — fused into the per-chunk step. Only
        # when the fold cannot take the grace-hash path (the grace
        # partitioner reads RAW key columns off the stream, a chunk
        # transform upstream of it would be unsound).
        pre: List[Computation] = []
        stream_src: Optional[int] = None
        if anchored and fold.probe_key is None \
                and fold.build_key is None:
            cur = node.inputs[src]
            while (isinstance(cur, Apply)
                   and getattr(cur, "rowwise", False)
                   and cur.fn is not None and traceable(cur)
                   and getattr(cur, "fold", None) is None
                   and len(consumers.get(cur.node_id, ())) == 1
                   and cur.node_id not in graft_covered):
                pre.append(cur)
                cur = cur.inputs[0]
            if pre and kinds.get(cur.node_id) == K_PAGED_REL \
                    and isinstance(cur, ScanSet):
                stream_src = cur.node_id
            else:
                pre = []  # chain must bottom out at the paged scan
        pre.reverse()  # scan → fold order

        # downstream: single-consumer traceable 1-input chain — fused
        # into one compiled epilogue over the fold's merged output
        post: List[Computation] = []
        cur_id = node.node_id
        while True:
            outs = consumers.get(cur_id, ())
            if len(outs) != 1:
                break
            nxt = outs[0]
            if not isinstance(nxt, (Apply, Aggregate)) \
                    or getattr(nxt, "fn", None) is None \
                    or not traceable(nxt) \
                    or getattr(nxt, "fold", None) is not None \
                    or getattr(nxt, "tensor_fold", None) is not None \
                    or nxt.node_id in graft_covered:
                break
            post.append(nxt)
            cur_id = nxt.node_id
        if not pre and not post:
            if mapper == "optimal" and getattr(node, "scatter_partial",
                                               False):
                # a shard-side scatter partial fold with nothing local
                # to graft still IS the shard's one compiled program —
                # form the anchor-only region so the distributed
                # EXPLAIN forest carries the same region ids/boundary
                # markers the coordinator tree gets. Greedy skips it:
                # the PR 10 map stays byte-for-byte.
                ids = (node.node_id,)
                regions.append(Region(
                    rid, "graft", ids, _fingerprint(plan, ids),
                    anchor=node.node_id))
                graft_covered.update(ids)
                rid += 1
                obs.REGISTRY.counter(
                    "fusion.distributed_regions").inc()
            continue
        members = pre + [node] + post
        if not cost.region_profitable(members):
            continue
        ids = tuple(n.node_id for n in members)
        regions.append(Region(
            rid, "graft", ids, _fingerprint(plan, ids),
            anchor=node.node_id,
            pre_ids=tuple(n.node_id for n in pre),
            post_ids=tuple(n.node_id for n in post),
            stream_src=stream_src))
        graft_covered.update(ids)
        rid += 1
        if getattr(node, "scatter_partial", False):
            # the shard's partial fold + its grafted pre/post chain:
            # one per-shard program spanning the scatter boundary
            obs.REGISTRY.counter("fusion.distributed_regions").inc()

    # --- spine regions over the remainder: maximal topo-contiguous
    # traceable resident runs ---------------------------------------
    def spine_eligible(node: Computation) -> bool:
        if node.node_id in graft_covered:
            return False
        if not isinstance(node, (Apply, Join, Aggregate)):
            return False
        if getattr(node, "fn", None) is None or not traceable(node):
            return False
        if getattr(node, "passthrough", False):
            return False
        # a node the dispatch would stream or demote stays out (fold
        # anchors fail the all-tensor input check by construction)
        in_kinds = [kinds.get(i.node_id, K_HOST) for i in node.inputs]
        if any(k != K_TENSOR for k in in_kinds):
            return False
        return kinds.get(node.node_id) == K_TENSOR

    run: List[Computation] = []

    def flush_run():
        nonlocal rid
        if mapper == "greedy":
            # PR 10 mapper, byte-for-byte: fuse the whole run or
            # nothing (the rollback/A-B arm)
            if len(run) >= min_region and cost.region_profitable(run):
                ids = tuple(n.node_id for n in run)
                regions.append(Region(rid, "spine", ids,
                                      _fingerprint(plan, ids)))
                rid += 1
            run.clear()
            return
        for seg in _optimal_segments(run, cost, min_region, budget):
            ids = tuple(n.node_id for n in seg)
            regions.append(Region(rid, "spine", ids,
                                  _fingerprint(plan, ids)))
            rid += 1
        run.clear()

    for node in plan.topo:
        if spine_eligible(node):
            run.append(node)
        else:
            flush_run()
    flush_run()

    if regions:
        obs.REGISTRY.counter("fusion.regions_formed").inc(len(regions))
        obs.REGISTRY.counter("fusion.nodes_fused").inc(
            sum(len(r.node_ids) for r in regions))
    return RegionMap(regions)


def _optimal_segments(run: List[Computation], cost: CostModel,
                      min_region: int,
                      budget: int) -> List[List[Computation]]:
    """Exact minimum-cost partition of ONE maximal fusable run into
    fused segments (the ``fusion_mapper="optimal"`` spine planner).

    The runs the mapper accumulates are topo-contiguous and convex, so
    the region lattice over a run is exactly its set of contiguous
    segmentations — and minimum-cost segmentation is solved EXACTLY by
    an O(n²) DP, not a heuristic: state ``i`` is the best plan for the
    run's first ``i`` nodes; a node either stays per-node (cost: its
    measured dispatch overhead) or closes a fused segment (cost: ONE
    dispatch).  A segment is admissible when it meets the min-region
    floor, is profitable, contains no chronic retracer, and its
    staged-bytes estimate fits ``fusion_stage_budget_bytes``.  Ties on
    modeled cost break toward more fused nodes, then fewer segments —
    with no budget pressure an admissible run therefore fuses WHOLE,
    reproducing the greedy mapper's regions (and jit keys) exactly.
    Under pressure the DP splits at the cheapest admissible edges
    instead of abandoning the run per-node; ``fusion.splits`` counts
    the extra seams of runs that were fusable whole but for the
    budget."""
    n = len(run)
    if n == 0:
        return []
    over = [cost.dispatch_overhead_s(x) for x in run]
    veto = [cost.retrace_rate(x) > RETRACE_RATE_CAP for x in run]
    staged = [cost.staged_bytes(x) for x in run]
    p_over, p_staged, p_veto = [0.0], [0.0], [0]
    for i in range(n):
        p_over.append(p_over[-1] + over[i])
        p_staged.append(p_staged[-1] + staged[i])
        p_veto.append(p_veto[-1] + (1 if veto[i] else 0))

    def admissible(j: int, i: int) -> bool:
        """run[j:i] as one fused segment?"""
        if i - j < min_region or p_veto[i] - p_veto[j]:
            return False
        if p_over[i] - p_over[j] <= STATIC_DISPATCH_S:
            return False  # fusing must beat the one kept dispatch
        return not budget or p_staged[i] - p_staged[j] <= budget

    # best[i] = (cost, -nodes_fused, segments) for run[:i]
    best: List[Tuple[float, int, List[Tuple[int, int]]]] = [(0.0, 0, [])]
    for i in range(1, n + 1):
        c, f, segs = best[i - 1]
        cand = (c + over[i - 1], f, segs)  # run[i-1] stays per-node
        for j in range(i - min_region, -1, -1):
            if not admissible(j, i):
                continue
            cj, fj, sj = best[j]
            t = (cj + STATIC_DISPATCH_S, fj - (i - j), sj + [(j, i)])
            if (t[0], t[1], len(t[2])) < (cand[0], cand[1],
                                          len(cand[2])):
                cand = t
        best.append(cand)
    chosen = best[n][2]
    if budget and len(chosen) > 1 and not p_veto[n] \
            and n >= min_region and p_over[n] > STATIC_DISPATCH_S \
            and p_staged[n] > budget:
        # the run was fusable whole but for the byte budget: it SPLIT
        # at the cheapest edges instead of falling back per-node
        obs.REGISTRY.counter("fusion.splits").inc(len(chosen) - 1)
    return [run[j:i] for j, i in chosen]


# ------------------------------------------------------------------
# the scatter boundary (used by plan/scatter.py + serve/shard.py)
# ------------------------------------------------------------------

def compile_scatter_merge(fold, nslots: int, src, job_name: str,
                          label: str) -> Callable:
    """ONE compiled program for a scatter-gather fold_state
    coordinator: the left-fold of the N shards' partial states through
    ``fold.state_merge`` AND ``fold.finalize`` over the merged state —
    the merge+finalize seam that used to dispatch eagerly per shard
    compiles across the scatter boundary.

    ``src`` (the coordinator's SchemaProxy) is closed over as trace
    constants — ``finalize`` may only read ``src.dicts``/
    ``src.num_rows`` (the FoldSpec contract), so the jit key carries a
    structural digest of exactly that surface: a changed dict table or
    row count re-traces rather than serving stale constants.  This is
    the ONLY sanctioned ``_cached_jit`` route for scatter programs
    outside the region executor (the ``scatter-jit-route`` lint rule
    enforces it); callers fall back to the eager merge via
    :func:`fallback` when states or the fold are not jit-safe."""
    from netsdb_tpu.plan import executor as _executor

    dicts = getattr(src, "dicts", None) or {}
    src_fp = hashlib.blake2s(repr(
        (sorted((k, tuple(v)) for k, v in dicts.items()),
         int(getattr(src, "num_rows", 0) or 0))).encode()
    ).hexdigest()[:12]
    key = (f"region::{job_name}::scatter::{label}::merge"
           f"::k{int(nslots)}::{src_fp}")

    def merge_finalize(states):
        merged = states[0]
        for s in states[1:]:
            merged = fold.state_merge(merged, s)
        return fold.finalize(merged, src)

    obs.REGISTRY.counter("fusion.distributed_regions").inc()
    return _executor._cached_jit(key, merge_finalize)


# ------------------------------------------------------------------
# graft helpers (used by the executor)
# ------------------------------------------------------------------

def wrap_fold_prechain(fold, pre_fns: Sequence[Callable]):
    """A :class:`~netsdb_tpu.plan.fold.FoldSpec` whose every pass step
    applies ``pre_fns`` (scan→fold order) to the chunk BEFORE the
    original step — the chunk is transformed and reduced in one
    compiled program.  Only the STEPS are wrapped: ``init`` and
    ``finalize`` still receive the raw scan handle as ``src``, which
    is why the ``rowwise`` declaration requires schema/dict
    preservation (see ``Apply`` in plan/computations.py) — a fold
    reading ``src.dicts`` must observe the same surface either way.
    The caller must key the wrapped step's jit entry differently from
    the bare fold's (the executor appends the region fingerprint)."""
    fns = tuple(pre_fns)

    def wrap(step):
        def fused_step(state, chunk, *resident):
            c = chunk
            for f in fns:
                c = f(c)
            return step(state, c, *resident)
        return fused_step

    passes = tuple((init, wrap(step)) for init, step in fold.passes)
    return dataclasses.replace(fold, passes=passes)


def compose_chain(fns: Sequence[Callable]) -> Callable:
    """``fns`` applied left-to-right as one callable (the epilogue
    body handed to ``_cached_jit``)."""
    fseq = tuple(fns)

    def chain(x):
        for f in fseq:
            x = f(x)
        return x

    return chain


def fallback(reason: str) -> None:
    """Tick the runtime-fallback counter (a region abandoned at
    execution time — non-jit-safe values, a trace failure) and
    annotate the active trace."""
    obs.REGISTRY.counter("fusion.fallbacks").inc()
    tr = obs.current_trace()
    if tr is not None:
        tr.add("fusion.fallbacks")
        tr.annotate("fusion.fallback", reason)
