"""Logical planner — TCAP + TCAPAnalyzer, collapsed to what TPU needs.

The reference compiles the Computation DAG to a textual TCAP program
(``src/queryPlanning/headers/QueryGraphAnalyzer.h``), then a cost-based
``TCAPAnalyzer`` greedily cuts it into JobStages at pipeline breakers,
re-planning after each stage using storage stats
(``src/queryPlanning/headers/TCAPAnalyzer.h:20-40``,
``QuerySchedulerServer.cc:1332-1420``). Under XLA the physical operator
ordering/fusion inside a stage is the compiler's job, so planning
reduces to: topo-sort the DAG, memoize shared subgraphs (the reference
materializes these as intermediate sets), and cut stages at
materialization points (WriteSet sinks) — exactly the "stages = jit
boundaries" translation of SURVEY §7. The TCAP-like dump is kept as the
debuggable plan artifact.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from netsdb_tpu.plan.computations import Computation, ScanSet, WriteSet


@dataclasses.dataclass
class JobStage:
    """One materialization unit — analogue of ``TupleSetJobStage``
    (``src/builtInPDBObjects/headers/TupleSetJobStage.h:20-50``): the topo
    slice of nodes from scans to one sink."""

    stage_id: int
    sink: WriteSet
    nodes: List[Computation]  # topo order, sink last

    @property
    def scans(self) -> List[ScanSet]:
        return [n for n in self.nodes if isinstance(n, ScanSet)]


@dataclasses.dataclass
class LogicalPlan:
    sinks: List[WriteSet]
    topo: List[Computation]  # whole-DAG topo order
    stages: List[JobStage]

    def to_plan_string(self) -> str:
        """TCAP-like textual dump (test/debug surface)."""
        lines = [n.plan_atom() for n in self.topo]
        return "\n".join(lines)

    def consumers(self) -> Dict[int, List[Computation]]:
        """node_id → consumer nodes in topo order — the reverse edges
        the fusion mapper walks (a node with exactly one consumer can
        fuse into it without materializing its output)."""
        out: Dict[int, List[Computation]] = {}
        for n in self.topo:
            for i in n.inputs:
                out.setdefault(i.node_id, []).append(n)
        return out

    def cache_key(self) -> str:
        """Canonical structural key: node names renumbered by topo
        position so two independently-built DAGs of the same shape share
        compiled code (process-global node_ids would never collide).
        Like the reference's per-job-name ``PreCompiledWorkload`` cache,
        this keys on structure + labels, not lambda identity: reusing a
        label for behaviorally different lambdas under one job name
        serves the first compilation."""
        from netsdb_tpu.plan.computations import ScanSet, WriteSet

        names = {n.node_id: f"n{i}" for i, n in enumerate(self.topo)}
        atoms = []
        for n in self.topo:
            ins = ",".join(names[i.node_id] for i in n.inputs)
            extra = ""
            if isinstance(n, ScanSet):
                extra = f"{n.db}:{n.set_name}"
            elif isinstance(n, WriteSet):
                extra = f"{n.db}:{n.set_name}"
            else:
                extra = getattr(n, "label", "")
            atoms.append(f"{names[n.node_id]}={n.op_kind}({ins};{extra})")
        return "|".join(atoms)


def _topo_sort(sinks: Sequence[Computation]) -> List[Computation]:
    order: List[Computation] = []
    seen: Dict[int, bool] = {}

    def visit(node: Computation, path: set):
        if node.node_id in seen:
            return
        if node.node_id in path:
            raise ValueError("computation graph has a cycle")
        path = path | {node.node_id}
        for dep in node.inputs:
            visit(dep, path)
        seen[node.node_id] = True
        order.append(node)

    for s in sinks:
        visit(s, set())
    return order


def plan_from_sinks(sinks: Sequence[WriteSet]) -> LogicalPlan:
    """Build the plan from sink computations — the DFS-from-sinks walk of
    ``QueryGraphAnalyzer::parseTCAPString``."""
    for s in sinks:
        if not isinstance(s, WriteSet):
            raise TypeError(f"sink {s!r} is not a WriteSet")
    topo = _topo_sort(sinks)
    stages = []
    for i, sink in enumerate(sinks):
        sub = _topo_sort([sink])
        stages.append(JobStage(stage_id=i, sink=sink, nodes=sub))
    return LogicalPlan(sinks=list(sinks), topo=topo, stages=stages)
