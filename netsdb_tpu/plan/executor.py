"""Query executor — QueryScheduler + PipelineStage, single-controller.

The reference ships JobStages to every worker, whose backend builds
pipelines from TCAP and runs them threaded over pages
(``QuerySchedulerServer.cc:216-330``, ``PipelineStage.cc:933-1213``);
shuffles/broadcasts move bytes over TCP. Here one controller process
evaluates the DAG: tensor subgraphs are composed into a single traced
function and jit-compiled (XLA fuses the whole stage and, when inputs
are sharded over a mesh, inserts the collectives the reference's
shuffle threads implemented by hand); host-object nodes (relational
workloads) run eagerly.

The per-job compiled-function cache replaces the master's
``materializedWorkloads`` precompiled-plan cache
(``QuerySchedulerServer.cc:1242-1264``,
``src/queryPlanning/headers/PreCompiledWorkload.h``).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import jax

from netsdb_tpu import obs
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.plan.computations import (
    Aggregate,
    Apply,
    Computation,
    Filter,
    Join,
    MultiApply,
    ScanSet,
    WriteSet,
)
from netsdb_tpu.plan.planner import LogicalPlan, plan_from_sinks
from netsdb_tpu.storage.paged import PagedObjects
from netsdb_tpu.storage.store import SetIdentifier, _PagedMatrix

# job_name+canonical-plan → compiled callable (the PreCompiledWorkload
# analogue, QuerySchedulerServer.cc:1242-1264). LRU-bounded: a serving
# loop rebuilding DAGs must not grow this without bound.
from collections import OrderedDict
import threading

_COMPILED_CACHE_CAP = 64
_compiled_cache: "OrderedDict[str, Any]" = OrderedDict()
# serve-layer jobs run on concurrent handler threads; the LRU
# reorder/insert/evict sequence must not interleave
_cache_lock = threading.Lock()

# observability for the shape-bucketing contract: ``traces`` counts XLA
# (re)traces across every cached wrapper — with bucketed chunk shapes
# it must stay CONSTANT across repeated executions over differing
# ragged tails (the recompile-churn regression the buckets absorb)
_compile_stats = {"hits": 0, "misses": 0, "traces": 0}
# per-FUSION-REGION trace counters ("job:fingerprint" → XLA traces of
# that region's one compiled program) — the fused-path analogue of
# ``traces``: flat across ragged-tail re-executions, one tick per
# region program per bucketed shape (plan/fusion.py). Bounded: a
# serving loop rebuilding distinct plans must not grow this without
# limit (oldest-inserted entries drop past the cap — dict preserves
# insertion order), and ``clear_compiled_cache`` resets it with the
# LRU it shadows.
_REGION_TRACES_CAP = 1024
_region_traces: Dict[str, int] = {}


def compile_stats() -> Dict[str, Any]:
    """Snapshot of the compiled-cache counters (hits/misses at the LRU,
    traces at XLA, plus the per-fusion-region trace map under
    ``region_traces``). The staging tests assert ``traces`` is flat
    across re-executions with different ragged tail sizes; the fusion
    tests assert the same of every ``region_traces`` entry."""
    with _cache_lock:
        out: Dict[str, Any] = dict(_compile_stats)
        out["region_traces"] = dict(_region_traces)
        return out


# the central registry reports these SAME counters under "compile"
# (obs/metrics.py absorption hook) — the accessor above keeps its
# shape and callers; the registry snapshot never double-books
obs.REGISTRY.register_collector("compile", compile_stats)


def compiled_cache_keys() -> List[str]:
    """Snapshot of the compiled-program cache's keys (LRU order). The
    rollback-parity tests pin that ``plan_fusion=off`` and
    ``fusion_mapper="greedy"`` produce byte-for-byte the same key sets
    (``fold::``/``eager::``/``region::``) as the paths they roll back
    to — a key drift here is a silent recompile in production."""
    with _cache_lock:
        return list(_compiled_cache)


def _cached_jit(key: str, fn, donate_argnums: tuple = (),
                region: Optional[str] = None) -> Any:
    """compiled-cache get-or-insert with the ONE LRU discipline (all
    call sites: fold steps, eager traceable nodes, fusion-region
    programs, whole-plan programs). The wrapper is published BEFORE
    its first call, so concurrent serve-layer threads racing the same
    cold key all call ONE jitted wrapper (jax dedups the trace/compile
    internally) instead of compiling N identical programs.

    ``donate_argnums`` marks arguments XLA may consume in place — the
    fold loops donate argument 0 (the carried accumulator) so each
    step updates its state buffer instead of allocating a fresh one
    per block (gated by ``staging.fold_donate_argnums``).

    ``region`` names the fusion region this program compiles
    (``"job:fingerprint"``) — its retraces tick the per-region map
    ``compile_stats()["region_traces"]`` alongside the global
    ``traces`` counter."""
    with _cache_lock:
        cached = _compiled_cache.get(key)
        if cached is not None:
            _compiled_cache.move_to_end(key)
            _compile_stats["hits"] += 1
            return cached

    def counted(*args, **kwargs):
        # body runs only when jax (re)traces — the recompile counter;
        # the active query trace (if any) gets the same tick so a
        # profile shows WHICH query paid a compile, and the current
        # operator (if any) so the explain tree shows WHICH NODE did
        with _cache_lock:
            _compile_stats["traces"] += 1
            if region is not None:
                _region_traces[region] = \
                    _region_traces.get(region, 0) + 1
                while len(_region_traces) > _REGION_TRACES_CAP:
                    _region_traces.pop(next(iter(_region_traces)))
        obs.add("executor.traces")
        obs.operators.op_add("traces")
        return fn(*args, **kwargs)

    jfn = jax.jit(counted, donate_argnums=tuple(donate_argnums))
    with _cache_lock:
        _compile_stats["misses"] += 1
        jfn = _compiled_cache.setdefault(key, jfn)
        _compiled_cache.move_to_end(key)
        while len(_compiled_cache) > _COMPILED_CACHE_CAP:
            _compiled_cache.popitem(last=False)
    return jfn


def _is_traceable(node: Computation) -> bool:
    """Host-object nodes can't go under jit: equi-joins/group-bys over
    Python records and predicate filters stay eager."""
    if isinstance(node, Filter):
        return False
    if isinstance(node, Join) and node.fn is None:
        return False
    if isinstance(node, Aggregate) and node.fn is None:
        return False
    return getattr(node, "traceable", True)


def _eval_node(node: Computation, in_vals: List[Any]) -> Any:
    """``node.evaluate`` with :class:`PagedObjects` inputs iterated
    under ``contextlib.closing`` for the node kinds that CONSUME
    record iterables (eager Filter / Flatten / key-based Join /
    key-based Aggregate): ``PagedObjects.__iter__`` holds the
    relation's read lock for the generator's lifetime, and a predicate
    raising mid-iteration — with the traceback frames retaining the
    generator — would otherwise hold that lock until GC, blocking
    appends and drops indefinitely (ADVICE round 5). Forwarding nodes
    (WriteSet, passthrough gathers, fn-bearing Apply/Aggregate) keep
    the raw handle — they may legitimately pass it downstream."""
    consumes = (isinstance(node, (Filter, MultiApply))
                or (isinstance(node, Join) and node.fn is None)
                or (isinstance(node, Aggregate) and node.fn is None))
    if not consumes or not any(isinstance(v, PagedObjects)
                               for v in in_vals):
        return node.evaluate(*in_vals)
    with contextlib.ExitStack() as stack:
        safe = [stack.enter_context(contextlib.closing(iter(v)))
                if isinstance(v, PagedObjects) else v
                for v in in_vals]
        return node.evaluate(*safe)


def _evaluate(plan: LogicalPlan, scan_values: Dict[int, Any],
              recorder=None) -> Dict[int, Any]:
    """Replay the DAG in topo order, memoizing shared subgraphs (the
    reference would materialize these as intermediate per-job sets).

    ``recorder`` (an :class:`obs.operators.OperatorRecorder`) times
    each node into the per-operator explain tree — passed ONLY by the
    eager execution branch: inside the whole-plan jit this function
    runs under trace (node values are tracers, wall times would be
    trace-time lies), so that caller leaves it None and the fused
    program records via ``mark_fused`` instead."""
    values: Dict[int, Any] = dict(scan_values)
    if recorder is None:
        for node in plan.topo:
            if node.node_id in values:
                continue
            args = [values[i.node_id] for i in node.inputs]
            values[node.node_id] = _eval_node(node, args)
        return values
    base = recorder.reserve(len(plan.topo))
    recorder.mode = "eager" if base == 0 else "mixed"
    pos = {n.node_id: base + i for i, n in enumerate(plan.topo)}
    for node in plan.topo:
        if node.node_id in values:
            # scans (and memoized shared subgraphs): register the node
            # so the tree keeps the plan's shape, no time attributed
            opr = recorder.node(pos[node.node_id], node,
                                [pos[i.node_id] for i in node.inputs])
            opr.rows_out = obs.operators.rows_of(values[node.node_id])
            continue
        args = [values[i.node_id] for i in node.inputs]
        with recorder.op(pos[node.node_id], node,
                         [pos[i.node_id] for i in node.inputs],
                         args) as opr:
            out = _eval_node(node, args)
            opr.rows_out = obs.operators.rows_of(out)
        values[node.node_id] = out
    return values


def _run_fold_once(fold, pc, resident, placement, step_jit):
    """One (possibly multi-pass) fold of a node over a page stream —
    the PageScanner loop: every pass re-streams the source, each chunk
    runs through ONE compiled step (static shapes; the chunk validity
    mask carries the ragged tail), and ``placement`` mesh-shards every
    chunk before the step so the fold executes distributed per chunk
    (ref ``PipelineStage.cc:228-265`` — workers stream local
    partitions through the same pipeline)."""
    state = None
    for pidx, (init, step) in enumerate(fold.passes):
        jstep = step_jit(pidx, step)
        state = init(state, pc, *resident)
        # closing(): a step raising mid-stream must release the page
        # stream's read lock NOW, not at GC (a retained traceback would
        # otherwise hold the lock and block appends/drops indefinitely)
        with obs.span("executor.fold_stream", "executor") as sp, \
                contextlib.closing(
                    pc.stream_tables(placement=placement)) as chunks:
            n = 0
            dev_s = 0.0
            for chunk in chunks:
                t0 = time.perf_counter()
                state = jstep(state, chunk, *resident)
                dev_s += time.perf_counter() - t0
                n += 1
            if sp is not None:
                # per-span device-time estimate (dispatch-inclusive
                # wall around the jitted step) — the host-vs-device
                # split the profile derives (obs/trace.profile)
                sp.counters["chunks"] = n
                sp.counters["device_est_s"] = dev_s
            obs.add("device.est_s", dev_s)
            obs.operators.op_add("device_est_s", dev_s)
            obs.operators.op_add("chunks", n)
            obs.attrib.account("executor.chunks", n,
                               scope=getattr(pc, "cache_scope", None))
    return fold.finalize(state, pc, *resident)


def _pad_table_rows(t, rows: int):
    """Pad a ColumnTable with invalid rows up to ``rows`` — build
    partitions pad to ONE uniform size so every partition reuses a
    single compiled step (the same static-shape discipline as the
    chunk stream)."""
    import jax.numpy as jnp

    from netsdb_tpu.relational.table import ColumnTable

    pad = rows - t.num_rows
    if pad <= 0:
        return t
    cols = {k: jnp.concatenate(
        [jnp.asarray(v), jnp.zeros((pad,) + v.shape[1:], v.dtype)])
        for k, v in t.cols.items()}
    valid = jnp.concatenate([t.mask(), jnp.zeros((pad,), jnp.bool_)])
    return ColumnTable(cols, t.dicts, valid)


def _part_chunks(ppc, placement):
    """Stream one probe partition, restoring the ORIGINAL global
    ``_rowid`` saved by the partitioner (folds arbitrate ties on it).
    Prefetch/staging depth come from the store's config knobs (the old
    hardwired ``prefetch=0`` defeated the overlap end-to-end)."""
    from netsdb_tpu.relational.table import ColumnTable

    if ppc is None:
        return
    with contextlib.closing(
            ppc.stream_tables(placement=placement)) as cs:
        for t in cs:
            if "_rowid0" in t.cols:
                cols = dict(t.cols)
                cols["_rowid"] = cols.pop("_rowid0")
                t = ColumnTable(cols, t.dicts, t.valid)
            yield t


def _run_fold_grace(fold, pc, rest, bi, build_pc, placement, step_jit):
    """ONE-PASS grace hash for a paged build side: hash-partition BOTH
    streams by the declared join keys into arena spill partitions (one
    pass each), then loop partition PAIRS — build partition + its probe
    partition resident together, outputs merged. Every probe page is
    read once for partitioning and each repartitioned row once for
    probing, instead of the whole probe stream once per build block
    (the reference partitions both sides the same way,
    ``PipelineStage.cc:1652-1728`` + ``HashSetManager.h``).

    Partition pairs OVERLAP: while pair *i* probes, pair *i+1*'s build
    block assembles and uploads on a bounded
    :class:`~netsdb_tpu.plan.staging.StagedStream` (depth =
    ``config.stage_depth``, same shutdown/leak discipline as every
    other staged stream), so the device no longer idles between pairs
    waiting for the next build side's host→device copy — the ROADMAP
    "staged multi-stream joins" item."""
    from netsdb_tpu.plan import staging
    from netsdb_tpu.relational.outofcore import partition_by_key

    nparts = build_pc.num_pages()
    build_parts: list = []
    probe_parts: list = []
    out = None
    try:
        # inside the try: a failure partitioning the SECOND side must
        # still reclaim the first side's spill partitions
        build_parts = partition_by_key(build_pc, fold.build_key, nparts)
        # partition pages carry only the columns the fold's step reads
        # (the reference's pipelines carry only listed tuple attrs)
        probe_parts = partition_by_key(pc, fold.probe_key, nparts,
                                       keep_rowid=True,
                                       columns=fold.probe_columns)
        maxr = max((bp.num_rows for bp in build_parts
                    if bp is not None), default=0)

        def pairs():
            for p in range(nparts):
                if build_parts[p] is not None:
                    yield p
                # no build rows: probes there can only miss

        def stage_build(p):
            # runs on the staging thread: pair p's build block pads to
            # ONE uniform size (one compiled step for all pairs) and
            # uploads while the previous pair still probes
            return p, _pad_table_rows(build_parts[p].to_table(), maxr)

        depth = getattr(build_pc.store.config, "stage_depth", 2)
        with obs.span("executor.grace_pairs", "executor") as gsp, \
                contextlib.closing(staging.stage_stream(
                    pairs(), stage_build, depth=depth,
                    name=f"grace-build:{build_pc.name}")) as staged_builds:
            npairs = 0
            nchunks = 0
            dev_s = 0.0
            for p, btab in staged_builds:
                part_res = list(rest)
                part_res[bi] = btab
                state = None
                for pidx, (init, step) in enumerate(fold.passes):
                    jstep = step_jit(pidx, step)
                    state = init(state, pc, *part_res)
                    for chunk in _part_chunks(probe_parts[p], placement):
                        t0 = time.perf_counter()
                        state = jstep(state, chunk, *part_res)
                        dev_s += time.perf_counter() - t0
                        nchunks += 1
                part = fold.finalize(state, pc, *part_res)
                out = part if out is None else fold.merge(out, part)
                npairs += 1
            if gsp is not None:
                gsp.counters["pairs"] = npairs
                gsp.counters["chunks"] = nchunks
                gsp.counters["device_est_s"] = dev_s
            # same device-estimate + attribution feed as every other
            # executor loop — grace joins must not read as 100% host
            # time, and a join-heavy tenant's executor.chunks must book
            obs.add("device.est_s", dev_s)
            obs.operators.op_add("device_est_s", dev_s)
            obs.operators.op_add("chunks", nchunks)
            obs.operators.op_add("pairs", npairs)
            obs.attrib.account("executor.chunks", nchunks,
                               scope=getattr(pc, "cache_scope", None))
    finally:
        # after the closing() above joined the build stager — spill
        # partitions must not be reclaimed under a live upload
        for lst in (build_parts, probe_parts):
            for prt in lst:
                if prt is not None:
                    prt.drop()
    return out


def _run_fold(node, fold, pc, resident, placement, step_jit):
    """Dispatch a fold, handling a paged BUILD side: when a resident
    input is itself paged and the fold declares ``merge``, the join
    runs grace-hash style (ref partitioned hash sets,
    ``src/queryExecution/headers/HashSetManager.h``) — ONE-PASS
    (both sides hash-partitioned, partition pairs joined) when the
    fold declares its join keys, else the legacy per-build-block
    re-stream. Other paged residents assemble HOST-side (never a
    silent device materialization of a set that was paged because it
    does not fit)."""
    from netsdb_tpu.relational.outofcore import PagedColumns

    builds = [i for i, v in enumerate(resident)
              if isinstance(v, PagedColumns)]
    bi = None
    keyed = False  # bi really holds the declared build_key column
    if builds and fold.merge is not None:
        if fold.build_key is not None:
            # a declared build_key scopes the merge rule: it is only
            # correct for partitions of THAT side (key-disjoint blocks;
            # e.g. q02's per-part winner merge is wrong for partitions
            # of supplier) — other paged residents assemble host-side
            for i in builds:
                v = resident[i]
                if (fold.build_key in v.int_names
                        or fold.build_key in v.float_names):
                    bi = i
                    keyed = True
                    break
        else:
            # no declared key (q03-style folds whose merge is written
            # for arbitrary row partitions of their one build side)
            bi = builds[0]
    if bi is not None:
        build_pc = resident[bi]
        rest = [v.to_host_table() if isinstance(v, PagedColumns)
                and i != bi else v for i, v in enumerate(resident)]
        if (keyed and fold.probe_key is not None
                and build_pc.num_pages() > 1):
            return _run_fold_grace(fold, pc, rest, bi, build_pc,
                                   placement, step_jit)
        # legacy discipline (no declared keys): outer loop over build
        # blocks, full probe re-stream per block (prefetch depth from
        # the config knob, not hardwired off)
        out = None
        with contextlib.closing(
                build_pc.stream_tables()) as btabs:
            for btab in btabs:
                part_res = list(rest)
                part_res[bi] = btab
                part = _run_fold_once(fold, pc, tuple(part_res),
                                      placement, step_jit)
                out = part if out is None else fold.merge(out, part)
        return out
    if builds:  # no merge rule: assemble the build side HOST-side
        resident = tuple(v.to_host_table() if isinstance(v, PagedColumns)
                         else v for v in resident)
    return _run_fold_once(fold, pc, resident, placement, step_jit)


def _summa_tensor_route(tfold, pt, others):
    """Route a MATMUL-SHAPED tensor-fold stream through the SUMMA
    engine — the ``config.distributed_matmul`` plan leg: a node whose
    :class:`~netsdb_tpu.plan.fold.TensorFold` declares ``summa_rhs``
    (``fn(block, *others) == block @ summa_rhs(*others)``) skips the
    per-block loop entirely; each mesh participant stages only its
    panel of the paged operand (1/N staged bytes per host, 1/(pr·pc)
    under a ``config.summa_grid`` 2-d mesh) and one compiled round
    program does the contraction (``parallel/summa.py``). Returns the
    assembled BlockedTensor, or None when the route does not apply
    (knob off, no declaration, or the declared RHS does not match
    these inputs) — the caller then takes the per-block path,
    byte-for-byte as before. Device/grid selection lives in ONE place
    — ``PagedTensorStore.matmul_streamed`` — so the plan leg and the
    set-property leg (``store.paged_matmul``) can never route
    differently; with fewer than 2 devices that router falls back to
    the single-device blocked stream, which is byte-equal anyway."""
    import jax.numpy as jnp
    import numpy as np

    rhs_fn = getattr(tfold, "summa_rhs", None)
    cfg = pt.store.config
    if rhs_fn is None or not getattr(cfg, "distributed_matmul", False):
        return None
    rhs = rhs_fn(*others)
    if rhs is None:
        return None
    rhs = np.asarray(rhs)
    (rows, k), _blk, _dtype = pt.store.meta(pt.name)
    if rhs.ndim != 2 or rhs.shape[0] != k:
        return None  # declaration does not fit these inputs
    cache = getattr(pt, "devcache", None)
    scope = getattr(pt, "cache_scope", None)
    cache_scope = None if scope is None else str(scope[0])
    stats = {}
    with obs.span("executor.tensor_summa", "executor") as sp, \
            pt.rw.read():
        out = pt.store.matmul_streamed(pt.name, rhs, devcache=cache,
                                       cache_scope=cache_scope,
                                       stats_out=stats)
        if sp is not None:
            sp.counters["summa.participants"] = stats.get(
                "participants", 0)
            sp.counters["summa.rounds"] = stats.get("rounds", 0)
    obs.operators.op_add("summa.participants",
                         stats.get("participants", 0))
    obs.attrib.account("executor.chunks", stats.get("rounds", 0),
                       scope=cache_scope)
    dense = jnp.asarray(out)
    if tfold.out_block is not None:
        return BlockedTensor.from_dense(dense, tfold.out_block)
    return BlockedTensor.from_dense(dense, tuple(dense.shape))


def _run_tensor_stream(node, tfold, in_vals, src, step_jit):
    """Stream a paged TENSOR input through a node — in-DB inference
    over storage-managed weights (ref ``SimpleFF.cc:94-290``: FF
    scans its weight sets page-fed via ``FFMatrixBlockScanner`` +
    ``PageScanner.h:25-34``). Only one weight page (plus the node's
    resident inputs, the staged next block, and the assembled output)
    is device-resident at a time; the upload of the NEXT block runs on
    the staging thread while the current step computes
    (``plan/staging.stage_stream`` — the host page readers feed the
    device stage).

    mode "rows": evaluate the node's fn once per row block (the block
    substituted for the paged input) and concatenate output rows.
    Blocks pad up to the row-block's shape bucket (zero rows — fn is
    row-decomposable by the mode's contract, so padded output rows are
    sliced back off before assembly) so a ragged tail reuses the
    full-block compiled step's bucket instead of compiling per tail
    size; ``out_block`` re-blocks the assembly so its meta — and
    downstream padded shapes — match the resident path exactly.
    mode "reduce": blocks are contraction slices; ``partial``
    accumulates (donated carry — in-place accumulator updates),
    ``finalize`` applies the epilogue. Reduce blocks are staged but
    NEVER bucket-padded: partials slice their co-factor by
    ``start``+``block.shape[0]``, so padded rows would misalign the
    contraction, not just waste it."""
    import jax.numpy as jnp
    import numpy as np

    from netsdb_tpu.plan import staging

    pt = in_vals[src]
    others = [v for i, v in enumerate(in_vals) if i != src]
    placement = pt.placement
    cfg = pt.store.config
    depth = getattr(cfg, "stage_depth", 2)
    rb = pt.store.meta(pt.name)[1][0]  # nominal rows per block
    bucketing = getattr(cfg, "shape_bucketing", True)
    density = getattr(cfg, "bucket_density", 2)

    # cross-query device cache for the weight stream: store-owned
    # handles carry (ident, write version) — a warm scan replays the
    # staged blocks already in HBM (storage/devcache.py); cached
    # blocks are never donated (the reduce carry is the only donated
    # argument)
    cache = getattr(pt, "devcache", None)
    scope = getattr(pt, "cache_scope", None)
    version_fn = getattr(pt, "cache_version_fn", None)

    def cache_key(kind):
        if cache is None or scope is None:
            return None
        pl = placement.label() if placement is not None else None
        return (scope[0], scope[1], kind, rb, bucketing, density, pl)

    def still_current():
        # install-time currentness: a write racing the scan must not
        # leave a dead (old-version) entry squatting on the budget
        return version_fn is None or version_fn() == scope[1]

    def partial_plan(kind):
        # block-granular caching for the weight stream (partial mode):
        # base key drops the write VERSION — put_tensor/restore are
        # whole-set writes, so dirty-range invalidation drops every
        # block anyway — and keeps the layout/sharding components
        if (cache is None or scope is None
                or not getattr(cache, "partial", False)
                or not cache.enabled):
            return None
        pl = placement.label() if placement is not None else None
        ranges = pt.store.block_ranges(pt.name)
        if not ranges:
            return None
        return staging.PartialPlan(
            cache, (scope[0], kind, rb, bucketing, density, pl), ranges,
            lambda idxs: pt.stream_blocks(blocks=idxs))

    def to_device(block):
        b = jnp.asarray(block)
        if placement is not None:
            b = placement.apply(b)
        return b

    if tfold.mode == "rows":
        routed = _summa_tensor_route(tfold, pt, others)
        if routed is not None:
            return routed

        def place(item):
            _start, block = item
            n = block.shape[0]
            target = staging.pad_rows_target(max(n, rb), bucketing,
                                             density=density)
            if target > n:
                block = np.pad(block, ((0, target - n), (0, 0)))
            return n, to_device(block)

        def step(block, *os):
            bt = BlockedTensor.from_dense(block, tuple(block.shape))
            args = list(os)
            args.insert(src, bt)
            return node.fn(*args)

        jstep = step_jit(0, step, donate=())
        outs = []
        was_blocked = False
        dev_s = 0.0
        with obs.span("executor.tensor_rows", "executor") as sp, \
                contextlib.closing(staging.stage_stream(
                    pt.stream_blocks(), place, depth,
                    name=f"trows:{pt.name}",
                    cache=cache, cache_key=cache_key("trows"),
                    cache_validator=still_current,
                    partial=partial_plan("trows"),
                    scope=None if scope is None else str(scope[0])
                    )) as blocks:
            for n, block in blocks:
                t0 = time.perf_counter()
                out = jstep(block, *others)
                dev_s += time.perf_counter() - t0
                if isinstance(out, BlockedTensor):
                    was_blocked = True
                    out = out.to_dense()
                if out.shape[0] != n:  # drop the bucket's padded rows
                    out = out[:n]
                outs.append(out)
            if sp is not None:
                sp.counters["blocks"] = len(outs)
                sp.counters["device_est_s"] = dev_s
            obs.add("device.est_s", dev_s)
            obs.operators.op_add("device_est_s", dev_s)
            obs.operators.op_add("blocks", len(outs))
            obs.attrib.account("executor.chunks", len(outs),
                               scope=scope if scope is None
                               else str(scope[0]))
        dense = jnp.concatenate(outs, axis=0)
        if tfold.out_block is not None:
            return BlockedTensor.from_dense(dense, tfold.out_block)
        if was_blocked:
            return BlockedTensor.from_dense(dense, tuple(dense.shape))
        return dense

    # mode "reduce": carry accumulation over contraction slices
    def place(item):
        start, block = item
        return jnp.asarray(start, jnp.int32), to_device(block)

    def step(carry, start, block, *os):
        return tfold.partial(carry, start, block, *os)

    jstep = step_jit(1, step)
    carry = None
    dev_s = 0.0
    with obs.span("executor.tensor_reduce", "executor") as sp, \
            contextlib.closing(staging.stage_stream(
                pt.stream_blocks(), place, depth,
                name=f"treduce:{pt.name}",
                cache=cache, cache_key=cache_key("treduce"),
                cache_validator=still_current,
                partial=partial_plan("treduce"),
                scope=None if scope is None else str(scope[0])
                )) as blocks:
        nblk = 0
        for start, block in blocks:
            t0 = time.perf_counter()
            carry = jstep(carry, start, block, *others)
            dev_s += time.perf_counter() - t0
            nblk += 1
        if sp is not None:
            sp.counters["blocks"] = nblk
            sp.counters["device_est_s"] = dev_s
        obs.add("device.est_s", dev_s)
        obs.operators.op_add("device_est_s", dev_s)
        obs.operators.op_add("blocks", nblk)
        obs.attrib.account("executor.chunks", nblk,
                           scope=scope if scope is None
                           else str(scope[0]))
    if tfold.finalize is not None:
        return tfold.finalize(carry, *others)
    return carry


def _execute_streamed(client, plan: LogicalPlan, scan_values: Dict[int, Any],
                      job_name: str) -> Dict[int, Any]:
    """Topo-evaluate a plan with paged scans: fold-bearing consumers of
    a paged set stream it page-by-page (``_run_fold``); everything else
    evaluates eagerly on resident values. Fold-less consumers of a
    paged set materialize it (correct, not streamed — the documented
    fallback, like the reference pinning a set that fits RAM).

    A job mixing paged-reachable and resident-only SINKS never reaches
    here whole: ``execute_computations`` auto-splits it and routes the
    resident-only component through the fused whole-plan jit (round
    5). This path sees only components that genuinely touch paged
    sets; their non-fold resident consumers stay correct but unfused."""
    from netsdb_tpu.plan.fold import flatten_resident
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.storage.paged import PagedTensor

    placements = {
        n.node_id: client.store.placement_of(
            SetIdentifier(n.db, n.set_name))
        for n in plan.topo if isinstance(n, ScanSet)
        and isinstance(scan_values.get(n.node_id), PagedColumns)
    }
    plan_key = plan.cache_key()
    # nodes are keyed by topo POSITION, not label alone: two fold-bearing
    # nodes sharing a label in one plan must not reuse each other's
    # jitted steps (plan_key renumbers nodes n0..nN, so structurally
    # identical plans still share cache entries)
    topo_pos = {n.node_id: i for i, n in enumerate(plan.topo)}

    # fusion-aware region mapping (plan/fusion.py): spine regions
    # compile as ONE program each, graft regions weave rowwise
    # pre-chains into fold steps and traceable epilogues onto fold
    # outputs. plan_fusion=False takes the per-node paths byte-for-byte
    # (same keys, same trace counts — the rollback contract).
    from netsdb_tpu.plan import fusion

    cfg = client.store.config
    regions = None
    graft_at: Dict[int, Any] = {}
    consumers: Dict[int, Any] = {}
    if getattr(cfg, "plan_fusion", True):
        consumers = plan.consumers()  # ONE reverse-edge build, shared
        rmap = fusion.map_regions(plan, scan_values, cfg, job_name,
                                  traceable=_is_traceable,
                                  consumers=consumers)
        if rmap.regions:
            regions = rmap
            graft_at = {r.anchor: r for r in rmap.regions
                        if r.kind == "graft"}
    node_by_id = {n.node_id: n for n in plan.topo}
    skip = set(regions.fused_away) if regions is not None else set()

    # fold-step accumulators (argument 0 of every step) are donated so
    # XLA updates the per-stream state in place instead of allocating a
    # fresh HBM buffer every block; auto-gated to backends that
    # implement donation (staging.fold_donate_argnums). ONLY the
    # carried state is ever donated: chunk and resident arguments may
    # be device-cache-owned blocks reused by the next query, and a
    # donated cache block would be freed out from under it — donation
    # applies exclusively to buffers the cache does not own.
    from netsdb_tpu.plan.staging import fold_donate_argnums

    donate_default = fold_donate_argnums(client.store.config)

    def step_jit_for(node, fz: str = ""):
        # ``fz`` carries the graft region's fingerprint when the fold's
        # steps were rewritten with a fused pre-chain: the wrapped step
        # is a DIFFERENT program and must never share a cache entry
        # with the bare fold's (plan_fusion=off keys stay unchanged)
        def step_jit(pidx, step, donate=None):
            key = (f"fold::{job_name}::{plan_key}::"
                   f"n{topo_pos[node.node_id]}::{node.label}::{pidx}{fz}")
            return _cached_jit(
                key, step,
                donate_argnums=donate_default if donate is None else donate)
        return step_jit

    values: Dict[int, Any] = dict(scan_values)
    materialized: Dict[int, Any] = {}  # per-relation memo: N fold-less
    # consumers of one paged set must not stream it N times

    def table_of(pc: PagedColumns):
        # HOST-side assembly (numpy columns): the fold-less fallback
        # must not materialize a paged set in device memory — consumers
        # that compute on it upload transiently as jit arguments.
        # The per-EXECUTION memo consults the CROSS-QUERY cache first
        # (same budget as the device blocks): a warm serve EXECUTE
        # skips the re-assembly stream entirely, and any write bumps
        # the version out from under the entry.
        if id(pc) not in materialized:
            cache, key = pc._cache_ref("host-table", None)
            if cache is not None:
                hit = cache.get(key)
                if hit is not None:
                    materialized[id(pc)] = hit[0]
                    return hit[0]
            t = pc.to_host_table()
            if cache is not None:
                # currentness re-checked INSIDE install's lock — a
                # racing write must not leave a dead entry on the budget
                cache.install(
                    key, [t],
                    validator=lambda: pc._cache_ref(
                        "host-table", None)[1] == key)
            materialized[id(pc)] = t
        return materialized[id(pc)]

    def demote(v):
        """Replace paged handles (possibly inside gather tuples) with
        host-assembled tables for non-streaming consumers."""
        if isinstance(v, PagedColumns):
            return table_of(v)
        if isinstance(v, tuple):
            return tuple(demote(x) for x in v)
        return v

    def graft_epilogue(greg, out):
        """Apply a graft region's fused downstream chain to the fold's
        merged output as ONE compiled program (fold→materialize→
        per-node dispatch becomes fold→one program). Non-jit-safe fold
        outputs run the chain eagerly — a counted fallback, never a
        failure."""
        if greg is None or not greg.post_ids:
            return out
        chain = fusion.compose_chain(
            [node_by_id[i].fn for i in greg.post_ids])
        if not _jit_safe_values([out]):
            fusion.fallback("graft epilogue input not jit-safe")
            return chain(out)
        key = (f"region::{job_name}::{plan_key}::r{greg.rid}"
               f"::{greg.fingerprint}::epi")
        return _cached_jit(key, chain,
                           region=f"{job_name}:{greg.fingerprint}")(out)

    def dispatch(node, in_vals):
        """One node's streamed-path evaluation — extracted so the
        per-operator recorder can time it inclusively. A graft
        anchor's fused epilogue applies to EVERY return path (the
        anchor may dispatch off the streaming branch — e.g. a
        demoted-at-runtime stream input — and its skipped post-chain
        nodes must still run)."""
        greg = graft_at.get(node.node_id)
        return graft_epilogue(greg, _dispatch_inner(node, in_vals,
                                                    greg))

    def _dispatch_inner(node, in_vals, greg):
        fold = getattr(node, "fold", None)
        src = getattr(node, "fold_src", 0)
        if greg is not None and greg.pre_ids:
            # the fused pre-chain was skipped by the topo loop: its
            # paged SCAN handle replaces the chain's (never computed)
            # output, and the chunk transforms run inside the fold's
            # compiled step instead
            in_vals = list(in_vals)
            in_vals[src] = values[greg.stream_src]
        if (fold is not None and len(in_vals) > src
                and isinstance(in_vals[src], PagedColumns)):
            resident = flatten_resident(
                tuple(v for i, v in enumerate(in_vals) if i != src))
            if greg is not None and greg.pre_ids:
                placement = placements.get(greg.stream_src)
                run_fold = fusion.wrap_fold_prechain(
                    fold, [node_by_id[i].fn for i in greg.pre_ids])
                sj = step_jit_for(node, fz=f"::fz{greg.fingerprint}")
            else:
                placement = placements.get(node.inputs[src].node_id)
                run_fold = fold
                sj = step_jit_for(node)
            return _run_fold(node, run_fold, in_vals[src], resident,
                             placement, sj)
        tsrcs = [i for i, v in enumerate(in_vals)
                 if isinstance(v, PagedTensor)]
        if tsrcs:
            tfold = getattr(node, "tensor_fold", None)
            if tfold is None or len(tsrcs) > 1:
                # NO silent materialization: a paged weight exists
                # because it does not fit — a fold-less consumer would
                # defeat that by construction

                def set_of(i):
                    inp = node.inputs[i]
                    return (f"{inp.db}:{inp.set_name}"
                            if isinstance(inp, ScanSet) else in_vals[i].name)

                raise ValueError(
                    f"node "
                    f"{getattr(node, 'label', node.op_kind)!r} "
                    f"consumes paged tensor set(s) "
                    f"{[set_of(i) for i in tsrcs]} but "
                    + ("declares no tensor_fold" if tfold is None else
                       "only one input may stream")
                    + "; give the node a plan.fold.TensorFold, or store "
                      "the set with storage='memory'")
            # a co-input that is a paged RELATION materializes (the
            # documented fold-less fallback) — it cannot ride into the
            # jitted tensor step as a raw stream handle
            in_vals = [demote(v) for v in in_vals]
            return _run_tensor_stream(node, tfold, in_vals, tsrcs[0],
                                      step_jit_for(node))
        if not getattr(node, "passthrough", False):
            # gather-chain nodes forward paged handles untouched so a
            # downstream fold can stream them; real consumers get the
            # host-assembled fallback (tuples from gathers included)
            in_vals = [demote(v) for v in in_vals]
        fn = getattr(node, "fn", None)
        if (fn is not None and _is_traceable(node)
                and isinstance(node, (Apply, Join, Aggregate))
                and not getattr(node, "passthrough", False)
                and _jit_safe_values(in_vals)):
            # traceable fn over table/tensor values: compile it like
            # the resident whole-plan path would, instead of eager
            # per-op dispatch (each unjitted op costs a device RTT —
            # a 15M-row q03 build filter measured minutes eager vs
            # seconds compiled). Passthrough/gather nodes are EXCLUDED:
            # jitting a pure restructuring fn would device-copy (and,
            # for host-assembled tables, device-UPLOAD) everything it
            # forwards — defeating the bounded-device-memory
            # discipline the host fallbacks exist for.
            key = (f"eager::{job_name}::{plan_key}::"
                   f"n{topo_pos[node.node_id]}")
            return _cached_jit(key, fn)(*in_vals)
        return _eval_node(node, in_vals)

    # per-operator explain recording (obs/operators.py): op ids are
    # RESERVED per plan component so auto-split jobs record every
    # component into one collision-free tree; scans register untimed
    # so the rendered tree keeps the plan's full shape
    recorder = obs.operators.current_recorder()
    op_base = recorder.reserve(len(plan.topo)) if recorder else 0
    if recorder is not None and op_base != 0:
        recorder.mode = "mixed"  # an auto-split job's later component
    op_pos = {n.node_id: op_base + i for i, n in enumerate(plan.topo)}

    def run_spine(reg) -> bool:
        """Execute one spine region as ONE compiled program (all its
        nodes replayed under a single trace — the region analogue of
        the whole-plan jit). False = runtime fallback: the caller
        un-skips the region's nodes and they dispatch per-node
        exactly as with fusion off (counted, never an error)."""
        nodes = [node_by_id[i] for i in reg.node_ids]
        rset = set(reg.node_ids)
        in_ids: List[int] = []
        for n in nodes:
            for i in n.inputs:
                if i.node_id not in rset and i.node_id not in in_ids:
                    in_ids.append(i.node_id)
        args = [values[i] for i in in_ids]
        if not _jit_safe_values(args):
            fusion.fallback("spine inputs not jit-safe")
            return False
        out_ids = [nid for nid in reg.node_ids
                   if not consumers.get(nid)
                   or any(c.node_id not in rset
                          for c in consumers.get(nid, ()))]

        def region_fn(*fargs, _nodes=tuple(nodes), _in=tuple(in_ids),
                      _out=tuple(out_ids)):
            vals = dict(zip(_in, fargs))
            for n in _nodes:
                vals[n.node_id] = n.evaluate(
                    *[vals[i.node_id] for i in n.inputs])
            return tuple(vals[o] for o in _out)

        key = (f"region::{job_name}::{plan_key}::r{reg.rid}"
               f"::{reg.fingerprint}")
        jfn = _cached_jit(key, region_fn,
                          region=f"{job_name}:{reg.fingerprint}")
        tail = nodes[-1]
        ctx = (recorder.op(op_pos[tail.node_id], tail,
                           [op_pos[i.node_id] for i in tail.inputs],
                           args)
               if recorder is not None else contextlib.nullcontext())
        with obs.span("executor.fusion_region", "executor") as sp, \
                ctx as opr:
            t0 = time.perf_counter()
            outs = jfn(*args)
            dev_s = time.perf_counter() - t0
            if sp is not None:
                sp.counters["nodes"] = len(nodes)
                sp.counters["device_est_s"] = dev_s
            obs.add("device.est_s", dev_s)
            if opr is not None:
                opr.add("device_est_s", dev_s)
                opr.add("region_nodes", len(nodes))
        for nid, v in zip(out_ids, outs):
            values[nid] = v
        if recorder is not None:
            # the whole region executed as one program: every member
            # keeps its place in the tree, marked fused with its
            # region id; the tail carries the measured wall time
            for n in nodes:
                rec = recorder.node(op_pos[n.node_id], n,
                                    [op_pos[i.node_id]
                                     for i in n.inputs])
                rec.fused = True
                rec.region = reg.rid
                if n.node_id in values:
                    rec.rows_out = obs.operators.rows_of(
                        values[n.node_id])
        return True

    for node in plan.topo:
        if node.node_id in skip:
            # subsumed by a fusion region (spine body or graft
            # pre/post chain): no evaluation here — register the node
            # so the explain tree keeps the plan's full shape
            if recorder is not None:
                opr = recorder.node(
                    op_pos[node.node_id], node,
                    [op_pos[i.node_id] for i in node.inputs])
                opr.fused = True
                opr.region = regions.region_of(node.node_id)
                if node.node_id in values:
                    opr.rows_out = obs.operators.rows_of(
                        values[node.node_id])
            continue
        if node.node_id in values:
            if recorder is not None:
                opr = recorder.node(
                    op_pos[node.node_id], node,
                    [op_pos[i.node_id] for i in node.inputs])
                opr.rows_out = obs.operators.rows_of(
                    values[node.node_id])
            continue
        sreg = (regions.spine_at.get(node.node_id)
                if regions is not None else None)
        if sreg is not None:
            if run_spine(sreg):
                continue
            skip.difference_update(sreg.node_ids)  # per-node fallback
        # a fused-away input (a graft pre-chain member) has no value —
        # dispatch substitutes the chain's paged scan handle; every
        # other input must exist (KeyError here would be a real bug)
        in_vals = [values.get(i.node_id) if i.node_id in skip
                   else values[i.node_id] for i in node.inputs]
        greg = graft_at.get(node.node_id)
        if recorder is None:
            out_val = dispatch(node, in_vals)
        else:
            with recorder.op(op_pos[node.node_id], node,
                             [op_pos[i.node_id] for i in node.inputs],
                             in_vals) as opr:
                out_val = dispatch(node, in_vals)
                opr.rows_out = obs.operators.rows_of(out_val)
                if regions is not None:
                    rid = regions.region_of(node.node_id)
                    if rid is not None:
                        opr.region = rid
        values[node.node_id] = out_val
        if greg is not None and greg.post_ids:
            # the graft epilogue already ran inside dispatch: the
            # chain's tail carries the fused result (its members were
            # skipped above)
            values[greg.post_ids[-1]] = out_val
    return values


def _jit_safe_values(vals) -> bool:
    """True when every value is a table/tensor/array (or a gather tuple
    of them) — the kinds the resident whole-plan jit already traces;
    host-object lists stay on the eager interpreter."""
    import numpy as _np

    from netsdb_tpu.relational.table import ColumnTable

    def ok(v) -> bool:
        if isinstance(v, tuple):
            return all(ok(x) for x in v)
        return isinstance(v, (ColumnTable, BlockedTensor, jax.Array,
                              _np.ndarray))

    return all(ok(v) for v in vals)


def execute_computations(
    client,
    sinks: List[WriteSet],
    job_name: str = "job",
    materialize: bool = True,
) -> Dict[SetIdentifier, Any]:
    """Plan and run; returns {output set ident: value} and (by default)
    materializes results into the store — the reference's OUTPUT sets.

    Recorded per operator when the query is traced (or an
    ``obs.operators.explain_capture`` is active): every node's wall
    time, device estimate, chunk/row counts and cache/compile ticks
    land in the explain tree (``obs/operators.py``). The recursion for
    auto-split jobs joins the outer recording — one tree per logical
    job."""
    with obs.operators.recording(job_name, client.store.config):
        return _execute_computations(client, sinks, job_name,
                                     materialize)


def _execute_computations(
    client,
    sinks: List[WriteSet],
    job_name: str = "job",
    materialize: bool = True,
) -> Dict[SetIdentifier, Any]:
    with obs.span("planner.plan", "planner"):
        plan = plan_from_sinks(sinks)
    t0 = time.perf_counter()

    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.relational.table import ColumnTable

    if len(plan.sinks) > 1:
        # AUTO-SPLIT (round 5), decided from the CHEAP storage peek
        # BEFORE any scan set is fetched: sinks whose transitive inputs
        # touch no paged set must not lose the fused whole-plan jit
        # because an unrelated sink in the same job went paged (the
        # reference plans stages per source, not per job —
        # ``TCAPAnalyzer.h:20-40``). Recursion re-plans each component;
        # the compiled cache keys on the component's own canonical plan.
        paged_scan_ids = {
            n.node_id for n in plan.topo if isinstance(n, ScanSet)
            and client.store.storage_of(
                SetIdentifier(n.db, n.set_name)) == "paged"}

        def touches_paged(sink) -> bool:
            stack, seen = [sink], set()
            while stack:
                n = stack.pop()
                if n.node_id in seen:
                    continue
                seen.add(n.node_id)
                if n.node_id in paged_scan_ids:
                    return True
                stack.extend(n.inputs)
            return False

        if paged_scan_ids:
            resident_sinks = [s for s in sinks if not touches_paged(s)]
            if resident_sinks and len(resident_sinks) < len(sinks):
                paged_sinks = [s for s in sinks if touches_paged(s)]
                out = execute_computations(client, resident_sinks,
                                           job_name, materialize)
                out.update(execute_computations(client, paged_sinks,
                                                job_name, materialize))
                return out

    scan_values: Dict[int, Any] = {}
    tensor_scans: List[ScanSet] = []
    for node in plan.topo:
        if isinstance(node, ScanSet):
            ident = SetIdentifier(node.db, node.set_name)
            items = client.store.get_items(ident)
            # single-tensor, single-table and single-array sets become
            # traced jit arguments; when their arrays carry a
            # NamedSharding from the set's placement, XLA partitions
            # the whole stage and inserts the cross-device collectives
            # (the reference's per-stage shuffle/broadcast threads,
            # QuerySchedulerServer.cc:216-330)
            # NOTE: np.ndarray single items deliberately stay on the
            # host-object path — conv staged pipelines store numpy
            # images/patches as object items and iterate them
            if len(items) == 1 and isinstance(items[0],
                                              (BlockedTensor, ColumnTable,
                                               jax.Array)):
                scan_values[node.node_id] = items[0]
                tensor_scans.append(node)
            elif len(items) == 1 and isinstance(items[0], PagedColumns):
                # paged set: the value IS the page stream handle; the
                # streamed evaluator folds consumers over it
                scan_values[node.node_id] = items[0]
            elif len(items) == 1 and isinstance(items[0], _PagedMatrix):
                # paged TENSOR set (weights in the arena): the value is
                # a streaming handle; TensorFold-bearing consumers
                # stream it, everything else errors (never materialize)
                scan_values[node.node_id] = client.store.paged_tensor(
                    ident)
            elif len(items) == 1 and isinstance(items[0], PagedObjects):
                # paged OBJECT set: the handle IS an iterable of
                # records, so the eager Filter/Join/Aggregate
                # interpreter consumes it page-streamed unchanged
                scan_values[node.node_id] = items[0]
            else:
                scan_values[node.node_id] = items

    from netsdb_tpu.storage.paged import PagedTensor

    any_paged = any(isinstance(v, (PagedColumns, PagedTensor,
                                   PagedObjects))
                    for v in scan_values.values())
    all_traceable = all(_is_traceable(n) for n in plan.topo)

    num_scans = sum(isinstance(n, ScanSet) for n in plan.topo)

    if any_paged:
        with obs.span("executor.streamed", "executor"):
            values = _execute_streamed(client, plan, scan_values, job_name)
        sink_vals = {s.node_id: values[s.inputs[0].node_id]
                     for s in plan.sinks}
    elif all_traceable and tensor_scans:
        # Cache only pure-tensor jobs: host-object scan values are traced
        # as constants, so a cached callable would pin stale data.
        cacheable = len(tensor_scans) == num_scans
        cache_key = f"{job_name}::{plan.cache_key()}"
        # canonical arg keys (topo position) so independently built
        # DAGs of the same shape hit one traced signature; host-object
        # scan values are closed over (non-cacheable jobs only)
        canon = {n.node_id: i for i, n in enumerate(plan.topo)}
        host_values = {k: v for k, v in scan_values.items()
                       if not isinstance(v, (BlockedTensor, ColumnTable,
                                             jax.Array))}

        def run(tensor_args: Dict[int, BlockedTensor],
                _plan=plan, _canon=canon, _host=host_values):
            merged = dict(_host)
            for n in _plan.topo:
                if isinstance(n, ScanSet) and _canon[n.node_id] in tensor_args:
                    merged[n.node_id] = tensor_args[_canon[n.node_id]]
            values = _evaluate(_plan, merged)
            return [values[s.inputs[0].node_id] for s in _plan.sinks]

        # _cached_jit publishes the wrapper BEFORE its first call, so
        # concurrent serve-layer threads racing the same cold plan all
        # call ONE jitted wrapper (non-cacheable jobs close over host
        # data and must not be shared)
        fn = _cached_jit(cache_key, run) if cacheable else jax.jit(run)
        topo_pos = {n.node_id: i for i, n in enumerate(plan.topo)}
        canon_args = {topo_pos[n.node_id]: scan_values[n.node_id]
                      for n in tensor_scans}
        with obs.span("executor.whole_plan_jit", "executor") as sp:
            t0_jit = time.perf_counter()
            out_list = fn(canon_args)
            dev_s = time.perf_counter() - t0_jit
            if sp is not None:
                sp.counters["device_est_s"] = dev_s
            obs.add("device.est_s", dev_s)
        rec = obs.operators.current_recorder()
        if rec is not None:
            # XLA fused the whole component: the tree keeps the plan's
            # SHAPE (nodes marked fused) with one root carrying the
            # program's measured wall/device time
            rec.mark_fused(plan.topo, dev_s, dev_s)
        sink_vals = {s.node_id: out_list[i] for i, s in enumerate(plan.sinks)}
    else:
        with obs.span("executor.eager", "executor"):
            values = _evaluate(plan, scan_values,
                               recorder=obs.operators.current_recorder())
        sink_vals = {s.node_id: values[s.inputs[0].node_id] for s in plan.sinks}

    results: Dict[SetIdentifier, Any] = {}
    with obs.span("executor.materialize", "executor"):
        for sink in plan.sinks:
            out = sink_vals[sink.node_id]
            ident = SetIdentifier(sink.db, sink.set_name)
            results[ident] = out
            if materialize:
                client.store.create_set(ident)
                if isinstance(out, BlockedTensor):
                    client.store.put_tensor(ident, out)
                elif isinstance(out, (ColumnTable, jax.Array)):
                    # one relation / one raw array IS the set's content
                    # (iterating a jax.Array into rows would be wrong)
                    client.store.clear_set(ident)
                    client.store.add_data(ident, [out])
                elif isinstance(out, dict):
                    client.store.clear_set(ident)
                    client.store.add_data(ident, list(out.items()))
                else:
                    client.store.clear_set(ident)
                    client.store.add_data(ident, list(out))

    elapsed = time.perf_counter() - t0
    # stage timing record — feeds the Lachesis-lite advisor (§2.4)
    try:
        from netsdb_tpu.learning.history import record_job

        record_job(job_name, plan, elapsed)
    except ImportError:
        pass
    return results


def clear_compiled_cache() -> None:
    with _cache_lock:
        _compiled_cache.clear()
        _region_traces.clear()
