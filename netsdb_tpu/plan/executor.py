"""Query executor — QueryScheduler + PipelineStage, single-controller.

The reference ships JobStages to every worker, whose backend builds
pipelines from TCAP and runs them threaded over pages
(``QuerySchedulerServer.cc:216-330``, ``PipelineStage.cc:933-1213``);
shuffles/broadcasts move bytes over TCP. Here one controller process
evaluates the DAG: tensor subgraphs are composed into a single traced
function and jit-compiled (XLA fuses the whole stage and, when inputs
are sharded over a mesh, inserts the collectives the reference's
shuffle threads implemented by hand); host-object nodes (relational
workloads) run eagerly.

The per-job compiled-function cache replaces the master's
``materializedWorkloads`` precompiled-plan cache
(``QuerySchedulerServer.cc:1242-1264``,
``src/queryPlanning/headers/PreCompiledWorkload.h``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.plan.computations import (
    Aggregate,
    Computation,
    Filter,
    Join,
    ScanSet,
    WriteSet,
)
from netsdb_tpu.plan.planner import LogicalPlan, plan_from_sinks
from netsdb_tpu.storage.store import SetIdentifier

# job_name+canonical-plan → compiled callable (the PreCompiledWorkload
# analogue, QuerySchedulerServer.cc:1242-1264). LRU-bounded: a serving
# loop rebuilding DAGs must not grow this without bound.
from collections import OrderedDict
import threading

_COMPILED_CACHE_CAP = 64
_compiled_cache: "OrderedDict[str, Any]" = OrderedDict()
# serve-layer jobs run on concurrent handler threads; the LRU
# reorder/insert/evict sequence must not interleave
_cache_lock = threading.Lock()


def _is_traceable(node: Computation) -> bool:
    """Host-object nodes can't go under jit: equi-joins/group-bys over
    Python records and predicate filters stay eager."""
    if isinstance(node, Filter):
        return False
    if isinstance(node, Join) and node.fn is None:
        return False
    if isinstance(node, Aggregate) and node.fn is None:
        return False
    return getattr(node, "traceable", True)


def _evaluate(plan: LogicalPlan, scan_values: Dict[int, Any]) -> Dict[int, Any]:
    """Replay the DAG in topo order, memoizing shared subgraphs (the
    reference would materialize these as intermediate per-job sets)."""
    values: Dict[int, Any] = dict(scan_values)
    for node in plan.topo:
        if node.node_id in values:
            continue
        args = [values[i.node_id] for i in node.inputs]
        values[node.node_id] = node.evaluate(*args)
    return values


def execute_computations(
    client,
    sinks: List[WriteSet],
    job_name: str = "job",
    materialize: bool = True,
) -> Dict[SetIdentifier, Any]:
    """Plan and run; returns {output set ident: value} and (by default)
    materializes results into the store — the reference's OUTPUT sets."""
    plan = plan_from_sinks(sinks)
    t0 = time.perf_counter()

    from netsdb_tpu.relational.table import ColumnTable

    scan_values: Dict[int, Any] = {}
    tensor_scans: List[ScanSet] = []
    for node in plan.topo:
        if isinstance(node, ScanSet):
            ident = SetIdentifier(node.db, node.set_name)
            items = client.store.get_items(ident)
            # single-tensor, single-table and single-array sets become
            # traced jit arguments; when their arrays carry a
            # NamedSharding from the set's placement, XLA partitions
            # the whole stage and inserts the cross-device collectives
            # (the reference's per-stage shuffle/broadcast threads,
            # QuerySchedulerServer.cc:216-330)
            # NOTE: np.ndarray single items deliberately stay on the
            # host-object path — conv staged pipelines store numpy
            # images/patches as object items and iterate them
            if len(items) == 1 and isinstance(items[0],
                                              (BlockedTensor, ColumnTable,
                                               jax.Array)):
                scan_values[node.node_id] = items[0]
                tensor_scans.append(node)
            else:
                scan_values[node.node_id] = items

    all_traceable = all(_is_traceable(n) for n in plan.topo)

    num_scans = sum(isinstance(n, ScanSet) for n in plan.topo)

    if all_traceable and tensor_scans:
        # Cache only pure-tensor jobs: host-object scan values are traced
        # as constants, so a cached callable would pin stale data.
        cacheable = len(tensor_scans) == num_scans
        cache_key = f"{job_name}::{plan.cache_key()}"
        fn = None
        if cacheable:
            with _cache_lock:
                if cache_key in _compiled_cache:
                    fn = _compiled_cache[cache_key]
                    _compiled_cache.move_to_end(cache_key)
        if fn is None:
            # canonical arg keys (topo position) so independently built
            # DAGs of the same shape hit one traced signature; host-object
            # scan values are closed over (non-cacheable jobs only)
            canon = {n.node_id: i for i, n in enumerate(plan.topo)}
            host_values = {k: v for k, v in scan_values.items()
                           if not isinstance(v, (BlockedTensor, ColumnTable,
                                                 jax.Array))}

            def run(tensor_args: Dict[int, BlockedTensor],
                    _plan=plan, _canon=canon, _host=host_values):
                merged = dict(_host)
                for n in _plan.topo:
                    if isinstance(n, ScanSet) and _canon[n.node_id] in tensor_args:
                        merged[n.node_id] = tensor_args[_canon[n.node_id]]
                values = _evaluate(_plan, merged)
                return [values[s.inputs[0].node_id] for s in _plan.sinks]

            fn = jax.jit(run)
            if cacheable:
                # publish the wrapper BEFORE the first call: concurrent
                # serve-layer threads racing the same cold plan then all
                # call ONE jitted wrapper (jax dedups the trace/compile
                # internally) instead of compiling N identical programs
                with _cache_lock:
                    if cache_key in _compiled_cache:
                        fn = _compiled_cache[cache_key]  # lost the race
                        _compiled_cache.move_to_end(cache_key)
                    else:
                        _compiled_cache[cache_key] = fn
                        while len(_compiled_cache) > _COMPILED_CACHE_CAP:
                            _compiled_cache.popitem(last=False)
        topo_pos = {n.node_id: i for i, n in enumerate(plan.topo)}
        canon_args = {topo_pos[n.node_id]: scan_values[n.node_id]
                      for n in tensor_scans}
        out_list = fn(canon_args)
        sink_vals = {s.node_id: out_list[i] for i, s in enumerate(plan.sinks)}
    else:
        values = _evaluate(plan, scan_values)
        sink_vals = {s.node_id: values[s.inputs[0].node_id] for s in plan.sinks}

    results: Dict[SetIdentifier, Any] = {}
    for sink in plan.sinks:
        out = sink_vals[sink.node_id]
        ident = SetIdentifier(sink.db, sink.set_name)
        results[ident] = out
        if materialize:
            client.store.create_set(ident)
            if isinstance(out, BlockedTensor):
                client.store.put_tensor(ident, out)
            elif isinstance(out, (ColumnTable, jax.Array)):
                # one relation / one raw array IS the set's content
                # (iterating a jax.Array into rows would be wrong)
                client.store.clear_set(ident)
                client.store.add_data(ident, [out])
            elif isinstance(out, dict):
                client.store.clear_set(ident)
                client.store.add_data(ident, list(out.items()))
            else:
                client.store.clear_set(ident)
                client.store.add_data(ident, list(out))

    elapsed = time.perf_counter() - t0
    # stage timing record — feeds the Lachesis-lite advisor (§2.4)
    try:
        from netsdb_tpu.learning.history import record_job

        record_job(job_name, plan, elapsed)
    except ImportError:
        pass
    return results


def clear_compiled_cache() -> None:
    with _cache_lock:
        _compiled_cache.clear()
