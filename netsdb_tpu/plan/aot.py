"""Ahead-of-time compiled executables — the PreCompiledWorkload cache,
TPU-native.

The reference's master keeps physical plans per job name in memory so a
repeated workload skips planning (``src/queryPlanning/headers/
PreCompiledWorkload.h``, consulted in ``QuerySchedulerServer.cc:
1242-1264``). Two persistent layers replace it here:

1. the **XLA compilation cache** (``config.enable_compilation_cache``):
   every jit this framework compiles lands in an on-disk cache keyed by
   HLO hash, so a FRESH PROCESS re-running the same workload loads the
   compiled executable instead of re-compiling — no code changes at
   call sites, enabled by ``Client.__init__``;
2. **explicit AOT export** (this module): a jitted program serialized
   with ``jax.export`` into a self-contained artifact that a later
   process can load and run without the Python that built it — the
   shippable compiled plan (serve daemons, release bundles).

Both are exercised by tests/test_aot.py; cold-vs-warm numbers live in
BASELINE.md.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict

import jax
from jax import export as jexport


def _register_serializations() -> None:
    """jax.export must know how to serialize the framework's pytree
    auxdata (BlockedTensor's BlockMeta; FFParams is a registered
    dataclass that serializes through its fields). Idempotent."""
    from netsdb_tpu.core.blocked import BlockedTensor, BlockMeta

    try:
        jexport.register_pytree_node_serialization(
            BlockedTensor,
            serialized_name="netsdb_tpu.BlockedTensor",
            serialize_auxdata=lambda meta: json.dumps(
                {"shape": list(meta.shape),
                 "block_shape": list(meta.block_shape)}).encode(),
            deserialize_auxdata=lambda blob: BlockMeta(
                tuple(json.loads(blob)["shape"]),
                tuple(json.loads(blob)["block_shape"])),
        )
    except ValueError:
        pass  # already registered

    from netsdb_tpu.models.ff import FFParams

    try:
        jexport.register_pytree_node_serialization(
            FFParams,
            serialized_name="netsdb_tpu.FFParams",
            serialize_auxdata=lambda aux: json.dumps(aux).encode()
            if aux is not None else b"null",
            deserialize_auxdata=lambda blob: json.loads(blob),
        )
    except ValueError:
        pass


_register_serializations()


def export_jitted(jitted: Callable, *example_args) -> bytes:
    """Serialize a jitted callable, traced+compiled at the example
    arguments' shapes, into a portable executable blob (same platform
    on load — the artifact embeds compiled-for-backend HLO)."""
    exp = jexport.export(jitted)(*example_args)
    return exp.serialize()


def save_exported(path: str, jitted: Callable, *example_args) -> str:
    blob = export_jitted(jitted, *example_args)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return path


def load_exported(path_or_blob) -> Callable:
    """Deserialize an exported executable; returns a callable taking
    the original example-argument structure."""
    if isinstance(path_or_blob, (bytes, bytearray)):
        blob = bytes(path_or_blob)
    else:
        with open(path_or_blob, "rb") as f:
            blob = f.read()
    exp = jexport.deserialize(blob)
    return exp.call


# ------------------------------------------------ suite-level wrappers

def _suite_statics_digest(templates: Dict[str, list]) -> str:
    """Stable digest of the suite's NON-array (compile-time) arguments
    — dictionary codes, key spaces, join plans. The exported program
    baked these in, so a load against tables whose statics differ would
    silently compute wrong answers."""
    import hashlib

    from netsdb_tpu.relational.queries import _SLOT

    canon = {name: [repr(a) for a in t if a is not _SLOT]
             for name, t in templates.items()}
    return hashlib.sha256(json.dumps(canon, sort_keys=True).encode()
                          ).hexdigest()


def export_tpch_suite(tables, path: str) -> str:
    """AOT-compile the ENTIRE fused ten-query TPC-H program
    (``relational.queries.compile_suite``) and serialize it — the whole
    benchmark suite as one shippable executable. A sidecar
    ``<path>.meta`` records the digest of the baked-in statics; the
    loader REQUIRES it, so ship both files together."""
    from netsdb_tpu.relational.queries import compile_suite

    runner = compile_suite(tables)
    with open(path + ".meta", "w") as f:
        json.dump({"statics_digest":
                   _suite_statics_digest(runner.templates)}, f)
    return save_exported(path, runner.jitted, runner.arrays)


def load_tpch_suite(path: str, tables) -> Callable[[], Dict]:
    """Load a serialized suite and re-bind the CURRENT tables' arrays.

    The artifact fixes shapes/dtypes AND the data-dependent statics
    (dictionary codes, key spaces, planner join plans) that were baked
    at export; the loader recomputes them from ``tables`` and REFUSES
    tables whose statics differ — refreshed data must be
    statics-compatible, same as the reference re-running a precompiled
    plan against reloaded sets of the same schema. Fails CLOSED when
    the ``<path>.meta`` sidecar is missing or unreadable (without it
    compatibility cannot be proven, and a silent mismatch computes
    wrong answers)."""
    from netsdb_tpu.relational.queries import suite_args_split

    call = load_exported(path)
    templates, arrays = suite_args_split(tables)
    try:
        with open(path + ".meta") as f:
            want = json.load(f)["statics_digest"]
    except (OSError, ValueError, KeyError) as e:
        raise ValueError(
            f"missing or unreadable statics sidecar {path + '.meta'} "
            "(exported suites must travel with it; re-export if lost)"
        ) from e
    if _suite_statics_digest(templates) != want:
        raise ValueError(
            "exported suite was compiled against different static "
            "arguments (dictionary codes / key spaces / join plans) "
            "than these tables produce; re-export for this data")
    return lambda: call(arrays)


def export_ff_inference(model, params, example_inputs, path: str) -> str:
    """AOT-compile the flagship FF forward (the ``__graft_entry__``
    surface) and serialize it."""
    return save_exported(path, jax.jit(model.forward), params,
                         example_inputs)
