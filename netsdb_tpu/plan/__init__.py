from netsdb_tpu.plan.computations import (
    Aggregate,
    Apply,
    Computation,
    Filter,
    Join,
    MultiApply,
    ScanSet,
    WriteSet,
)
from netsdb_tpu.plan.executor import execute_computations
from netsdb_tpu.plan.planner import LogicalPlan, plan_from_sinks

__all__ = [
    "Computation", "ScanSet", "Apply", "MultiApply", "Filter", "Join",
    "Aggregate", "WriteSet", "LogicalPlan", "plan_from_sinks",
    "execute_computations",
]
