"""Decomposed (streamable) form of a Computation — the PageScanner
contract, TPU-shaped.

In the reference, *every* pipeline stage can consume its source set
page-by-page: the backend pins one page at a time and feeds it through
``PageCircularBuffer`` to the pipeline threads, with a combiner merging
per-page partial aggregation state
(``src/storage/headers/PageScanner.h:25-34``,
``src/serverFunctionalities/source/HermesExecutionServer.cc:49-93``).
That works because the stage's logic is expressed as
(init, per-page step, finalize) rather than as a whole-set function.

A :class:`FoldSpec` is that decomposition for a traced Computation
node.  A node carrying one can run three ways with the SAME math:

- **whole-table** (resident sets): ``finalize(step(init(), table))`` —
  composed into the plan jit exactly like a plain ``fn``;
- **streamed** (paged sets): the executor folds ``step`` over the page
  stream — one compiled XLA program per pass, reused across chunks
  (static shapes; ragged tails ride the chunk validity mask);
- **streamed-sharded** (paged AND placed sets): each chunk is placed
  with the set's mesh sharding before the step, so XLA inserts the
  cross-device collectives per chunk — every "worker" streams its
  shard of every page, the reference's workers-stream-local-partitions
  model (``src/queryExecution/source/PipelineStage.cc:228-265``).

Multi-pass folds (``passes`` with more than one (init, step) pair)
re-stream the source once per pass, threading the previous pass's
state into the next ``init`` — the reference's aggregate-then-probe
stage sequences (e.g. Q17's per-key average before the small-quantity
probe) map onto this.

Signatures (``src`` is any object with ``.dicts`` — the chunk schema;
``resident`` are the node's other, non-paged input values, tuples
flattened):

- ``init(prev_state, src, *resident) -> state``  (prev_state None for
  the first pass)
- ``step(state, chunk, *resident) -> state``  (chunk: ColumnTable with
  validity mask and a ``_rowid`` global-row-index column)
- ``finalize(state, src, *resident) -> output``
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class FoldSpec:
    """(init, step)* + finalize decomposition of a Computation node."""

    passes: Tuple[Tuple[Callable, Callable], ...]
    finalize: Callable
    # merge(out_a, out_b) -> out: combines the outputs of independent
    # key partitions when the BUILD side of a join is itself paged
    # (grace-hash — ref ``src/queryExecution/headers/HashSetManager.h``
    # partitioned hash sets). None = the node does not support a
    # partitioned build.
    merge: Optional[Callable] = None
    # the equi-join columns for the ONE-PASS grace hash: with both keys
    # declared (and merge), a paged build side triggers hash-
    # partitioning of BOTH streams into arena spill partitions in one
    # pass each, then a partition-pair loop — every probe page is read
    # once, not once per build block (the reference partitions both
    # sides the same way, ``PipelineStage.cc:1652-1728``).
    # probe_key: column in the streamed (fact) chunk; build_key: column
    # in the paged build relation (also how the executor identifies
    # WHICH paged resident input is the build).
    probe_key: Optional[str] = None
    build_key: Optional[str] = None
    # columns of the probe the fold's step actually reads: the grace
    # partitioner projects the repartitioned spill pages down to these
    # (plus the key), cutting partition IO — the reference's pipelines
    # carry only the tuple attributes the TCAP computation lists.
    # None = carry everything.
    probe_columns: Optional[Tuple[str, ...]] = None
    # state_merge(state_a, state_b) -> state: combines the FINAL-pass
    # states of two independent row partitions of the source — the
    # declaration that makes the fold SCATTERABLE across a sharded
    # worker pool (serve-level scatter-gather: each shard folds its
    # local pages, the coordinator merges the bounded partial states
    # in slot order and runs ``finalize`` once). Contract: the merge
    # must be associative over row partitions, and ``finalize`` may
    # read only the source's SCHEMA surface (``src.dicts`` /
    # ``src.num_rows``) — the coordinator holds no local pages, so it
    # passes a schema proxy, never a table. Float accumulators merge
    # in a different addition order than the single-stream fold —
    # exact for integer-valued states, last-ulp reassociation for
    # floats (same caveat class as XLA reduction reordering). None =
    # not scatterable; queries over sharded sets then refuse typed.
    state_merge: Optional[Callable] = None

    def whole(self, table: Any, *resident: Any) -> Any:
        """Whole-table evaluation — the resident-set path. Runs the
        same init/step/finalize chain over the full table as one
        'chunk', so the streamed path cannot diverge semantically."""
        state = None
        for init, step in self.passes:
            state = step(init(state, table, *resident), table, *resident)
        return self.finalize(state, table, *resident)


@dataclasses.dataclass(frozen=True)
class TensorFold:
    """Streamable decomposition of a node over a paged TENSOR input —
    the weight-scan analogue of :class:`FoldSpec`.

    The reference's defining scenario is in-DB inference with
    storage-managed weights: FF inference *scans* its weight sets
    page-fed like any other pipeline (``src/FF/source/SimpleFF.cc:
    94-290``, ``src/FF/headers/FFMatrixBlockScanner.h``, fed by
    ``src/storage/headers/PageScanner.h:25-34``). A node carrying a
    TensorFold can consume a ``storage="paged"`` matrix the same way:
    the executor streams the matrix's row-block pages through the node
    instead of materializing it (which :meth:`SetStore.get_tensor`
    refuses for paged sets by design).

    Two decompositions cover the weight-matmul family:

    - ``mode="rows"``: the node's ``fn`` is ROW-decomposable in the
      paged input — row block *i* of the paged matrix produces row
      block *i* of the output (``w @ x`` patterns: ``matmul`` /
      ``matmul_t`` with the paged side on the left). The executor
      evaluates ``fn`` once per block (one compiled step, reused; the
      ragged tail block is a second trace) and concatenates the output
      rows. ``out_block`` pins the assembled BlockedTensor's block
      shape so the result's meta — and therefore downstream padded
      shapes — match the resident path exactly.

    - ``mode="reduce"``: the paged input's row blocks are CONTRACTION
      slices (``x @ w`` patterns with the paged side on the right):
      ``partial(carry, start, block, *others) -> carry`` accumulates
      partial products (carry is ``None`` on the first block);
      ``finalize(carry, *others)`` applies any epilogue (e.g. the gelu
      after an MLP up-projection). ``others`` are the node's non-paged
      input values in input order.

    ``summa_rhs`` (mode="rows" only) declares the node MATMUL-SHAPED:
    ``summa_rhs(*others)`` returns the dense right-hand operand R such
    that ``fn(block, *others) == block @ R`` row-for-row (or ``None``
    when the declaration does not apply to these inputs). With
    ``config.distributed_matmul`` on, the executor routes the stream
    through the SUMMA engine (``parallel/summa.py``) instead of the
    per-block loop: each mesh participant stages only its panel of the
    paged operand and per-host staged bytes drop to ~1/N (2-d grid
    meshes via ``config.summa_grid`` drop the panel to ~1/(pr·pc)).
    Contract caveat: SUMMA accumulates the contraction in k-panels, a
    reassociation of the single-block ``dot_general`` — byte-equal for
    integer-valued f32 operands, last-ulp for arbitrary floats — so
    models declare it only under full-precision compute (see
    ``models/ff.py``).
    """

    mode: str = "rows"
    out_block: Optional[Tuple[int, int]] = None
    partial: Optional[Callable] = None
    finalize: Optional[Callable] = None
    summa_rhs: Optional[Callable] = None

    def __post_init__(self):
        if self.mode not in ("rows", "reduce"):
            raise ValueError(f"TensorFold mode must be 'rows' or "
                             f"'reduce', got {self.mode!r}")
        if self.mode == "reduce" and self.partial is None:
            raise ValueError("TensorFold(mode='reduce') needs a partial "
                             "accumulator")


def single_pass(init: Callable, step: Callable,
                finalize: Callable, merge: Optional[Callable] = None,
                probe_key: Optional[str] = None,
                build_key: Optional[str] = None,
                probe_columns: Optional[Tuple[str, ...]] = None,
                state_merge: Optional[Callable] = None) -> FoldSpec:
    return FoldSpec(((init, step),), finalize, merge,
                    probe_key=probe_key, build_key=build_key,
                    probe_columns=probe_columns,
                    state_merge=state_merge)


def tree_add_states(a: Any, b: Any) -> Any:
    """Elementwise-add ``state_merge`` for folds whose state is a
    pytree of additive accumulators (sums/counts/histograms — the q01
    family). Associative by construction; see the float-reassociation
    caveat on :attr:`FoldSpec.state_merge`."""
    import jax

    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def flatten_resident(values: Tuple[Any, ...]) -> Tuple[Any, ...]:
    """Gather-chain tuples (relational/dag.py's tuple-passing binary
    Joins) flatten so fold callables see tables positionally."""
    out = []
    for v in values:
        if isinstance(v, tuple):
            out.extend(v)
        else:
            out.append(v)
    return tuple(out)
